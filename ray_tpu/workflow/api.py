"""Workflow execution + storage.

Storage layout (``workflow_storage.py`` analog), one directory per
workflow under ``$RAY_TPU_WORKFLOW_STORAGE`` (default
``/tmp/ray_tpu/workflows``)::

    <id>/meta.json        status + timestamps
    <id>/dag.pkl          the bound DAG (for resume)
    <id>/steps/<sid>.pkl  checkpointed step results
    <id>/output.pkl       final result

Step ids are deterministic (topological index + function name), so a
resumed run maps steps onto their prior checkpoints.  Steps run as
cluster tasks; their *values* are checkpointed (results must be
picklable — the durability contract of the reference).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import cloudpickle

from ray_tpu.dag import ClassNode, DAGNode, FunctionNode, InputNode


def _root() -> str:
    return os.environ.get("RAY_TPU_WORKFLOW_STORAGE", "/tmp/ray_tpu/workflows")


class WorkflowStorage:
    def __init__(self, workflow_id: str):
        self.workflow_id = workflow_id
        self.dir = os.path.join(_root(), workflow_id)

    def _ensure_dirs(self) -> None:
        # lazy: reads (get_status of an unknown id, cancel probes) must
        # not fabricate phantom workflow directories
        os.makedirs(os.path.join(self.dir, "steps"), exist_ok=True)

    # -- meta ----------------------------------------------------------
    def _meta_path(self) -> str:
        return os.path.join(self.dir, "meta.json")

    def write_meta(self, **updates) -> None:
        self._ensure_dirs()
        meta = self.read_meta() or {"workflow_id": self.workflow_id,
                                    "created": time.time()}
        meta.update(updates)
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, self._meta_path())

    def read_meta(self) -> Optional[dict]:
        try:
            with open(self._meta_path()) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    # -- dag / steps / output -----------------------------------------
    def save_dag(self, dag: DAGNode) -> None:
        self._ensure_dirs()
        with open(os.path.join(self.dir, "dag.pkl"), "wb") as f:
            cloudpickle.dump(dag, f)

    def load_dag(self) -> DAGNode:
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            return cloudpickle.load(f)

    def step_path(self, step_id: str) -> str:
        return os.path.join(self.dir, "steps", f"{step_id}.pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self.step_path(step_id))

    def save_step(self, step_id: str, value: Any) -> None:
        self._ensure_dirs()
        tmp = self.step_path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, self.step_path(step_id))

    def load_step(self, step_id: str) -> Any:
        with open(self.step_path(step_id), "rb") as f:
            return cloudpickle.load(f)

    def save_step_meta(self, step_id: str, meta: dict) -> None:
        self._ensure_dirs()
        tmp = self.step_path(step_id) + ".meta.tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, self.step_path(step_id) + ".meta")

    def load_step_metas(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        steps_dir = os.path.join(self.dir, "steps")
        try:
            names = os.listdir(steps_dir)
        except OSError:
            return out
        for n in names:
            if n.endswith(".pkl.meta"):
                try:
                    with open(os.path.join(steps_dir, n)) as f:
                        out[n[:-len(".pkl.meta")]] = json.load(f)
                except (OSError, json.JSONDecodeError):
                    pass
        return out

    def save_output(self, value: Any) -> None:
        self._ensure_dirs()
        with open(os.path.join(self.dir, "output.pkl"), "wb") as f:
            cloudpickle.dump(value, f)

    def load_output(self) -> Any:
        with open(os.path.join(self.dir, "output.pkl"), "rb") as f:
            return cloudpickle.load(f)

    def has_output(self) -> bool:
        return os.path.exists(os.path.join(self.dir, "output.pkl"))

    def save_inputs(self, args: tuple, kwargs: dict) -> None:
        self._ensure_dirs()
        with open(os.path.join(self.dir, "inputs.pkl"), "wb") as f:
            cloudpickle.dump((args, kwargs), f)

    def load_inputs(self) -> tuple:
        try:
            with open(os.path.join(self.dir, "inputs.pkl"), "rb") as f:
                return cloudpickle.load(f)
        except OSError:
            return (), {}


class WorkflowError(Exception):
    """Base for workflow failures (reference workflow/exceptions.py)."""


class WorkflowExecutionError(WorkflowError):
    pass


class WorkflowCancellationError(WorkflowError):
    pass


# live runs: workflow_id -> {"cancel": bool, "refs": set}
_running: Dict[str, dict] = {}
_running_lock = threading.Lock()

_WOPT_KEYS = frozenset(("name", "max_retries", "catch_exceptions",
                        "checkpoint"))


class options:
    """Per-step workflow options, as a decorator over ``@remote``
    functions (reference ``workflow.options``)::

        @workflow.options(max_retries=3, catch_exceptions=True)
        @ray_tpu.remote
        def flaky(): ...

    - ``name``: step-id suffix (stable across code moves).
    - ``max_retries``: resubmit a failed step N times before failing
      the workflow.
    - ``catch_exceptions``: the step's checkpointed value becomes
      ``(result, None)`` or ``(None, exception)`` — downstream steps
      handle the error as data.
    - ``checkpoint``: ``False`` skips persisting this step's result
      (recomputed on resume).
    """

    def __init__(self, **opts):
        unknown = set(opts) - _WOPT_KEYS
        if unknown:
            raise ValueError(f"unknown workflow options {sorted(unknown)}; "
                             f"supported: {sorted(_WOPT_KEYS)}")
        self._opts = opts

    def __call__(self, fn):
        fn.__workflow_options__ = dict(self._opts)
        return fn


def _wopts(node: FunctionNode) -> dict:
    return getattr(node._remote_fn, "__workflow_options__", None) or {}


def _step_ids(dag: DAGNode, prefix: str = "") -> Dict[int, str]:
    """Deterministic step ids over the topological order."""
    ids: Dict[int, str] = {}
    for i, node in enumerate(dag.topological()):
        if isinstance(node, FunctionNode):
            name = (_wopts(node).get("name")
                    or getattr(node._remote_fn, "__name__", None)
                    or getattr(getattr(node._remote_fn, "_function", None),
                               "__name__", "step"))
            ids[id(node)] = f"{prefix}{i:04d}-{name}"
    return ids


def _check_task_dag(dag: DAGNode) -> None:
    if any(isinstance(n, ClassNode) for n in dag.topological()):
        raise TypeError("workflows support task DAGs only (no actor nodes)")


# last cross-process cancel poll per workflow (monotonic seconds): meta.json
# is disk + JSON parse, so the per-step-boundary check is throttled — a
# foreign cancel() lands within the poll interval, not instantly
_meta_cancel_poll: dict = {}
_META_CANCEL_POLL_S = 1.0


def _check_cancel(workflow_id: str) -> None:
    with _running_lock:
        st = _running.get(workflow_id)
        if st is not None and st["cancel"]:
            raise WorkflowCancellationError(
                f"workflow {workflow_id} was cancelled")
    if workflow_id:
        # cross-PROCESS cancel lands as a flag in meta.json (the owning
        # process's status must not be overwritten under it); honor it at
        # a step boundary within the poll interval
        now = time.monotonic()
        if now - _meta_cancel_poll.get(workflow_id, 0.0) \
                < _META_CANCEL_POLL_S:
            return
        _meta_cancel_poll[workflow_id] = now
        meta = WorkflowStorage(workflow_id).read_meta()
        if meta and meta.get("cancel_requested") \
                and meta.get("status") == "RUNNING":
            raise WorkflowCancellationError(
                f"workflow {workflow_id} was cancelled (cross-process)")


def _pid_alive(pid) -> bool:
    try:
        os.kill(int(pid), 0)
    except (ProcessLookupError, ValueError, TypeError, OverflowError):
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    return True


def _live_foreign_run(meta: Optional[dict]) -> bool:
    """Does ``meta`` record a RUNNING workflow owned by a DIFFERENT
    process that is verifiably alive?  The pid + host stamped into
    meta.json at RUNNING time make a cross-process ``cancel()`` /
    ``resume_all()`` distinguish a live run (must not double-run or have
    its status overwritten) from a crashed one (safe to take over).
    Liveness is only probeable on the recording host; a RUNNING meta from
    another host is treated as dead — the storage root is host-local by
    default, so a foreign-host meta means the dir was copied."""
    if not meta or meta.get("status") != "RUNNING":
        return False
    pid = meta.get("pid")
    if not pid or int(pid) == os.getpid():
        return False
    import socket

    if meta.get("host") not in (None, socket.gethostname()):
        return False
    return _pid_alive(pid)


def _track_ref(workflow_id: str, ref) -> None:
    with _running_lock:
        st = _running.get(workflow_id)
        if st is not None:
            st["refs"].add(ref)


def _finish_value(value: Any, storage: WorkflowStorage, sid: str,
                  workflow_id: str, depth: int) -> Any:
    """Continuation handling: a step that RETURNS a DAG continues the
    workflow with that DAG (reference ``workflow.continuation``); the
    sub-DAG executes durably under ``<sid>~`` step ids and its final
    value becomes the step's value."""
    if isinstance(value, DAGNode):
        if depth > 50:
            raise WorkflowExecutionError(
                f"continuation depth > 50 at step {sid} (unbounded "
                f"recursive continuation?)")
        return _execute_durably(value, storage, (), {},
                                workflow_id=workflow_id,
                                prefix=f"{sid}~", depth=depth + 1)
    return value


def _run_step_sync(node: FunctionNode, args: tuple, kwargs: dict,
                   storage: WorkflowStorage, sid: str, workflow_id: str,
                   depth: int) -> Any:
    """Resolve one step to a VALUE, honoring max_retries /
    catch_exceptions.  Used for steps with workflow options (they are
    synchronization points: an error-as-data value must not flow
    downstream as a raising ObjectRef)."""
    import ray_tpu

    wopts = _wopts(node)
    retries = int(wopts.get("max_retries", 0))
    attempt = 0
    while True:
        _check_cancel(workflow_id)
        step_meta = {"start": time.time(), "attempt": attempt}
        try:
            ref = node._execute_impl(args, kwargs)
            _track_ref(workflow_id, ref)
            value = _finish_value(ray_tpu.get(ref), storage, sid,
                                  workflow_id, depth)
            storage.save_step_meta(sid, dict(step_meta, status="SUCCEEDED",
                                             end=time.time()))
            return (value, None) if wopts.get("catch_exceptions") else value
        except WorkflowCancellationError:
            raise
        except Exception as e:  # noqa: BLE001 — retry/catch semantics
            # a cancel() lands as TaskCancelledError out of the get —
            # surface it as cancellation, not step failure
            _check_cancel(workflow_id)
            storage.save_step_meta(sid, dict(step_meta, status="FAILED",
                                             end=time.time(),
                                             error=str(e)[:500]))
            if attempt < retries:
                attempt += 1
                continue
            if wopts.get("catch_exceptions"):
                return (None, e)
            raise


def _execute_durably(dag: DAGNode, storage: WorkflowStorage,
                     input_args: tuple, input_kwargs: dict, *,
                     workflow_id: str = "", prefix: str = "",
                     depth: int = 0) -> Any:
    import ray_tpu
    from ray_tpu.dag.dag_node import _DAGInput

    _check_task_dag(dag)
    ids = _step_ids(dag, prefix)
    results: Dict[int, Any] = {}
    # submit eagerly: steps whose checkpoints are missing get their
    # upstream *ObjectRefs* as args (data moves through the object plane,
    # independent branches run concurrently); checkpoints are then taken
    # in topological order as each ref resolves.  Steps with workflow
    # options (retries / catch_exceptions) resolve synchronously instead.
    submitted = []
    for node in dag.topological():
        if isinstance(node, InputNode):
            # same input representation as DAGNode.execute()
            results[id(node)] = (input_args[0]
                                 if len(input_args) == 1 and not input_kwargs
                                 else _DAGInput(input_args, input_kwargs))
            continue
        _check_cancel(workflow_id)
        sid = ids[id(node)]
        if storage.has_step(sid):
            results[id(node)] = storage.load_step(sid)
            continue
        args = tuple(node._resolve(a, results) for a in node._bound_args)
        kwargs = {k: node._resolve(v, results)
                  for k, v in node._bound_kwargs.items()}
        wopts = _wopts(node)
        if wopts.get("max_retries") or wopts.get("catch_exceptions"):
            # ONLY these two force a synchronization point (an
            # error-as-data value must not flow downstream as a raising
            # ObjectRef); name/checkpoint options keep the eager path
            value = _run_step_sync(node, args, kwargs, storage, sid,
                                   workflow_id, depth)
            if wopts.get("checkpoint", True):
                storage.save_step(sid, value)
            results[id(node)] = value
            continue
        ref = node._execute_impl(args, kwargs)
        _track_ref(workflow_id, ref)
        results[id(node)] = ref
        submitted.append((sid, node, ref))
    for sid, node, ref in submitted:
        _check_cancel(workflow_id)
        step_meta = {"start": time.time()}
        try:
            value = _finish_value(ray_tpu.get(ref), storage, sid,
                                  workflow_id, depth)
        except WorkflowCancellationError:
            raise
        except Exception as e:  # noqa: BLE001 — record then surface
            _check_cancel(workflow_id)  # cancelled get, not a step failure
            storage.save_step_meta(sid, dict(step_meta, status="FAILED",
                                             end=time.time(),
                                             error=str(e)[:500]))
            raise
        if _wopts(node).get("checkpoint", True):
            storage.save_step(sid, value)
        storage.save_step_meta(sid, dict(step_meta, status="SUCCEEDED",
                                         end=time.time()))
        results[id(node)] = value
    return results[id(dag)]


def _run_sync(dag: DAGNode, storage: WorkflowStorage,
              args: tuple, kwargs: dict) -> Any:
    wid = storage.workflow_id
    with _running_lock:
        # setdefault, never overwrite: run_async/resume_all pre-register
        # BEFORE their thread starts, so a cancel() in the start window
        # lands on this entry instead of being lost
        _running.setdefault(wid, {"cancel": False, "refs": set()})
    try:
        import socket

        # pid + host let another process probe liveness (cancel /
        # resume_all); cancel_requested resets so a resumed run doesn't
        # inherit a stale cross-process cancel aimed at its predecessor
        storage.write_meta(status="RUNNING", started=time.time(),
                           pid=os.getpid(), host=socket.gethostname(),
                           cancel_requested=False)
        _check_cancel(wid)  # cancelled before the first step ran
        out = _execute_durably(dag, storage, args, kwargs, workflow_id=wid)
    except WorkflowCancellationError:
        storage.write_meta(status="CANCELED", ended=time.time(), pid=None)
        raise
    except BaseException as e:
        storage.write_meta(status="FAILED", error=str(e), ended=time.time(),
                           pid=None)
        raise
    finally:
        with _running_lock:
            _running.pop(wid, None)
    storage.save_output(out)
    storage.write_meta(status="SUCCEEDED", ended=time.time(), pid=None)
    return out


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        args: tuple = (), kwargs: Optional[dict] = None) -> Any:
    """Run a DAG durably; blocks and returns the final result."""
    from ray_tpu._private.usage import record_feature
    record_feature("workflow")
    _check_task_dag(dag)
    workflow_id = workflow_id or f"wf-{os.urandom(4).hex()}"
    storage = WorkflowStorage(workflow_id)
    storage.save_dag(dag)
    storage.save_inputs(args, kwargs or {})
    return _run_sync(dag, storage, args, kwargs or {})


class WorkflowHandle:
    """Async-run handle: ``.result(timeout)`` blocks for the value."""

    def __init__(self, workflow_id: str):
        self.workflow_id = workflow_id
        self._value = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(f"workflow {self.workflow_id} still running")
        if self._error is not None:
            raise self._error
        return self._value


def _start_async_run(dag: DAGNode, storage: WorkflowStorage, args: tuple,
                     kwargs: dict) -> WorkflowHandle:
    h = WorkflowHandle(storage.workflow_id)
    with _running_lock:
        # visible to cancel()/resume_all() from the moment the handle
        # exists, not from whenever the thread gets scheduled
        _running.setdefault(storage.workflow_id,
                            {"cancel": False, "refs": set()})

    def runner():
        try:
            h._value = _run_sync(dag, storage, args, kwargs)
        except BaseException as e:  # noqa: BLE001
            h._error = e
        finally:
            h._done.set()

    threading.Thread(target=runner, daemon=True,
                     name=f"workflow-{storage.workflow_id}").start()
    return h


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              args: tuple = (), kwargs: Optional[dict] = None):
    """Run in a background thread; returns a handle with .result()."""
    _check_task_dag(dag)
    workflow_id = workflow_id or f"wf-{os.urandom(4).hex()}"
    storage = WorkflowStorage(workflow_id)
    storage.save_dag(dag)
    storage.save_inputs(args, kwargs or {})
    return _start_async_run(dag, storage, args, kwargs or {})


def resume(workflow_id: str) -> Any:
    """Re-run a workflow; completed steps load from their checkpoints."""
    storage = WorkflowStorage(workflow_id)
    if storage.has_output():
        return storage.load_output()
    if _live_foreign_run(storage.read_meta()):
        raise ValueError(
            f"workflow {workflow_id!r} is running in another live process; "
            f"resuming would double-run it")
    dag = storage.load_dag()
    args, kwargs = storage.load_inputs()  # the original run's inputs
    return _run_sync(dag, storage, args, kwargs)


def get_status(workflow_id: str) -> Optional[str]:
    meta = WorkflowStorage(workflow_id).read_meta()
    return meta.get("status") if meta else None


def get_output(workflow_id: str) -> Any:
    storage = WorkflowStorage(workflow_id)
    if not storage.has_output():
        raise ValueError(f"workflow {workflow_id} has no output "
                         f"(status={get_status(workflow_id)})")
    return storage.load_output()


def list_all() -> List[Dict[str, Any]]:
    out = []
    try:
        ids = sorted(os.listdir(_root()))
    except OSError:
        return out
    for wid in ids:
        if not os.path.isdir(os.path.join(_root(), wid)):
            continue  # stray file in the storage root is not a workflow
        meta = WorkflowStorage(wid).read_meta()
        if meta:
            out.append(meta)
    return out


def delete(workflow_id: str) -> None:
    import shutil

    shutil.rmtree(os.path.join(_root(), workflow_id), ignore_errors=True)


def cancel(workflow_id: str) -> None:
    """Cancel a running workflow: in-flight step tasks are cancelled and
    the run raises :class:`WorkflowCancellationError`; checkpoints stay,
    so ``resume`` can pick up later (reference ``workflow.cancel``)."""
    import ray_tpu

    with _running_lock:
        st = _running.get(workflow_id)
        if st is None:
            # not running in this process: mark storage — but never
            # fabricate a phantom workflow for an unknown id, and never
            # downgrade a terminal status
            meta = WorkflowStorage(workflow_id).read_meta()
            if meta is None:
                raise ValueError(f"no workflow {workflow_id!r}")
            if meta.get("status") in ("SUCCEEDED", "FAILED", "CANCELED"):
                return
            if _live_foreign_run(meta):
                # the owning process is ALIVE: overwriting its status
                # would let it keep running under a CANCELED label.
                # Request cancellation instead — the owner honors the
                # flag at its next step boundary and writes CANCELED
                # itself.
                WorkflowStorage(workflow_id).write_meta(
                    cancel_requested=True)
                return
            WorkflowStorage(workflow_id).write_meta(status="CANCELED",
                                                    ended=time.time())
            return
        st["cancel"] = True
        refs = list(st["refs"])
    for ref in refs:
        try:
            ray_tpu.cancel(ref, force=True)
        except Exception:  # noqa: BLE001 — already-finished refs are fine
            pass


def resume_all(include_failed: bool = False) -> List[tuple]:
    """Resume every resumable workflow (status RUNNING whose process
    died, or CANCELED; plus FAILED with ``include_failed``).  Returns
    ``[(workflow_id, handle)]`` with async handles (reference
    ``workflow.resume_all``)."""
    out = []
    for meta in list_all():
        status = meta.get("status")
        wid = meta["workflow_id"]
        with _running_lock:
            if wid in _running:
                continue  # actually live in this process
        if _live_foreign_run(meta):
            continue  # live in ANOTHER process: resuming would double-run
        if status in ("RUNNING", "CANCELED") or (
                include_failed and status == "FAILED"):
            storage = WorkflowStorage(wid)
            if storage.has_output():
                continue
            try:
                dag = storage.load_dag()
                args, kwargs = storage.load_inputs()
            except Exception:  # noqa: BLE001 — one corrupt dir (missing
                continue  # dag.pkl, bad pickle) must not abort the sweep
            out.append((wid, _start_async_run(dag, storage, args, kwargs)))
    return out


def get_metadata(workflow_id: str) -> Dict[str, Any]:
    """Workflow + per-step metadata (status, timestamps, attempts,
    errors) — reference ``workflow.get_metadata``."""
    storage = WorkflowStorage(workflow_id)
    meta = storage.read_meta()
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    return {**meta, "steps": storage.load_step_metas()}


# ---------------------------------------------------------------------------
# events (reference workflow/event_listener.py + api.wait_for_event):
# an event is just two chained steps — poll (runs until the event
# arrives; NOT checkpointed mid-poll) then commit (checkpointed, so a
# resumed workflow doesn't re-wait a consumed event).


class EventListener:
    """Subclass with ``async poll_for_event(*args)`` (resolve when the
    event arrives) and optionally ``async event_checkpointed(event)``
    (commit the consumption upstream, e.g. ack a queue offset)."""

    async def poll_for_event(self, *args, **kwargs):
        raise NotImplementedError

    async def event_checkpointed(self, event) -> None:
        pass


class TimerListener(EventListener):
    async def poll_for_event(self, end_time: float):
        import asyncio

        await asyncio.sleep(max(0.0, end_time - time.time()))
        return end_time


def wait_for_event(event_listener_type, *args, **kwargs) -> DAGNode:
    """A DAG node that resolves once the listener observes its event
    (reference ``workflow.wait_for_event``)."""
    if not (isinstance(event_listener_type, type)
            and issubclass(event_listener_type, EventListener)):
        raise TypeError(f"{event_listener_type!r} is not an EventListener "
                        f"subclass")

    import ray_tpu

    @ray_tpu.remote
    def get_message(listener_cls, *a, **kw):
        import asyncio

        return asyncio.run(listener_cls().poll_for_event(*a, **kw))

    @ray_tpu.remote
    def message_committed(listener_cls, event):
        import asyncio

        asyncio.run(listener_cls().event_checkpointed(event))
        return event

    get_message.__name__ = f"wait_for_event.{event_listener_type.__name__}"
    message_committed.__name__ = "event_committed"
    return message_committed.bind(
        event_listener_type,
        get_message.bind(event_listener_type, *args, **kwargs))


def sleep(duration: float) -> DAGNode:
    """A step that resolves ``duration`` seconds after it first runs;
    the wake-up TIME is checkpointed, so a resumed workflow doesn't
    restart the clock (reference ``workflow.sleep``)."""
    import ray_tpu

    @ray_tpu.remote
    def end_time():
        return time.time() + duration

    end_time.__name__ = "sleep.end_time"
    return wait_for_event(TimerListener, end_time.bind())


def continuation(dag_node: DAGNode):
    """Mark a DAG as a continuation (reference
    ``workflow.continuation``): returned from inside a workflow step, it
    continues the workflow; called outside one, it just executes."""
    import ray_tpu
    from ray_tpu._private.worker import global_worker

    if not isinstance(dag_node, DAGNode):
        raise TypeError("workflow.continuation() expects a DAG")
    if global_worker.mode == "worker":
        return dag_node  # inside a step: the executor picks it up
    return ray_tpu.get(dag_node.execute())
