"""Workflow execution + storage.

Storage layout (``workflow_storage.py`` analog), one directory per
workflow under ``$RAY_TPU_WORKFLOW_STORAGE`` (default
``/tmp/ray_tpu/workflows``)::

    <id>/meta.json        status + timestamps
    <id>/dag.pkl          the bound DAG (for resume)
    <id>/steps/<sid>.pkl  checkpointed step results
    <id>/output.pkl       final result

Step ids are deterministic (topological index + function name), so a
resumed run maps steps onto their prior checkpoints.  Steps run as
cluster tasks; their *values* are checkpointed (results must be
picklable — the durability contract of the reference).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import cloudpickle

from ray_tpu.dag import ClassNode, DAGNode, FunctionNode, InputNode


def _root() -> str:
    return os.environ.get("RAY_TPU_WORKFLOW_STORAGE", "/tmp/ray_tpu/workflows")


class WorkflowStorage:
    def __init__(self, workflow_id: str):
        self.workflow_id = workflow_id
        self.dir = os.path.join(_root(), workflow_id)
        os.makedirs(os.path.join(self.dir, "steps"), exist_ok=True)

    # -- meta ----------------------------------------------------------
    def _meta_path(self) -> str:
        return os.path.join(self.dir, "meta.json")

    def write_meta(self, **updates) -> None:
        meta = self.read_meta() or {"workflow_id": self.workflow_id,
                                    "created": time.time()}
        meta.update(updates)
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, self._meta_path())

    def read_meta(self) -> Optional[dict]:
        try:
            with open(self._meta_path()) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    # -- dag / steps / output -----------------------------------------
    def save_dag(self, dag: DAGNode) -> None:
        with open(os.path.join(self.dir, "dag.pkl"), "wb") as f:
            cloudpickle.dump(dag, f)

    def load_dag(self) -> DAGNode:
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            return cloudpickle.load(f)

    def step_path(self, step_id: str) -> str:
        return os.path.join(self.dir, "steps", f"{step_id}.pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self.step_path(step_id))

    def save_step(self, step_id: str, value: Any) -> None:
        tmp = self.step_path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, self.step_path(step_id))

    def load_step(self, step_id: str) -> Any:
        with open(self.step_path(step_id), "rb") as f:
            return cloudpickle.load(f)

    def save_output(self, value: Any) -> None:
        with open(os.path.join(self.dir, "output.pkl"), "wb") as f:
            cloudpickle.dump(value, f)

    def load_output(self) -> Any:
        with open(os.path.join(self.dir, "output.pkl"), "rb") as f:
            return cloudpickle.load(f)

    def has_output(self) -> bool:
        return os.path.exists(os.path.join(self.dir, "output.pkl"))

    def save_inputs(self, args: tuple, kwargs: dict) -> None:
        with open(os.path.join(self.dir, "inputs.pkl"), "wb") as f:
            cloudpickle.dump((args, kwargs), f)

    def load_inputs(self) -> tuple:
        try:
            with open(os.path.join(self.dir, "inputs.pkl"), "rb") as f:
                return cloudpickle.load(f)
        except OSError:
            return (), {}


def _step_ids(dag: DAGNode) -> Dict[int, str]:
    """Deterministic step ids over the topological order."""
    ids: Dict[int, str] = {}
    for i, node in enumerate(dag.topological()):
        if isinstance(node, FunctionNode):
            name = getattr(node._remote_fn, "__name__", "step")
            ids[id(node)] = f"{i:04d}-{name}"
    return ids


def _check_task_dag(dag: DAGNode) -> None:
    if any(isinstance(n, ClassNode) for n in dag.topological()):
        raise TypeError("workflows support task DAGs only (no actor nodes)")


def _execute_durably(dag: DAGNode, storage: WorkflowStorage,
                     input_args: tuple, input_kwargs: dict) -> Any:
    import ray_tpu
    from ray_tpu.dag.dag_node import _DAGInput

    _check_task_dag(dag)
    ids = _step_ids(dag)
    results: Dict[int, Any] = {}
    # submit eagerly: steps whose checkpoints are missing get their
    # upstream *ObjectRefs* as args (data moves through the object plane,
    # independent branches run concurrently); checkpoints are then taken
    # in topological order as each ref resolves
    submitted = []
    for node in dag.topological():
        if isinstance(node, InputNode):
            # same input representation as DAGNode.execute()
            results[id(node)] = (input_args[0]
                                 if len(input_args) == 1 and not input_kwargs
                                 else _DAGInput(input_args, input_kwargs))
            continue
        sid = ids[id(node)]
        if storage.has_step(sid):
            results[id(node)] = storage.load_step(sid)
            continue
        args = tuple(node._resolve(a, results) for a in node._bound_args)
        kwargs = {k: node._resolve(v, results)
                  for k, v in node._bound_kwargs.items()}
        ref = node._execute_impl(args, kwargs)
        results[id(node)] = ref
        submitted.append((sid, node, ref))
    for sid, node, ref in submitted:
        value = ray_tpu.get(ref)
        storage.save_step(sid, value)
        results[id(node)] = value
    return results[id(dag)]


def _run_sync(dag: DAGNode, storage: WorkflowStorage,
              args: tuple, kwargs: dict) -> Any:
    storage.write_meta(status="RUNNING", started=time.time())
    try:
        out = _execute_durably(dag, storage, args, kwargs)
    except BaseException as e:
        storage.write_meta(status="FAILED", error=str(e), ended=time.time())
        raise
    storage.save_output(out)
    storage.write_meta(status="SUCCEEDED", ended=time.time())
    return out


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        args: tuple = (), kwargs: Optional[dict] = None) -> Any:
    """Run a DAG durably; blocks and returns the final result."""
    from ray_tpu._private.usage import record_feature
    record_feature("workflow")
    _check_task_dag(dag)
    workflow_id = workflow_id or f"wf-{os.urandom(4).hex()}"
    storage = WorkflowStorage(workflow_id)
    storage.save_dag(dag)
    storage.save_inputs(args, kwargs or {})
    return _run_sync(dag, storage, args, kwargs or {})


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              args: tuple = (), kwargs: Optional[dict] = None):
    """Run in a background thread; returns a handle with .result()."""
    _check_task_dag(dag)
    workflow_id = workflow_id or f"wf-{os.urandom(4).hex()}"
    storage = WorkflowStorage(workflow_id)
    storage.save_dag(dag)
    storage.save_inputs(args, kwargs or {})

    class _Handle:
        def __init__(self):
            self.workflow_id = workflow_id
            self._value = None
            self._error: Optional[BaseException] = None
            self._done = threading.Event()

        def result(self, timeout: Optional[float] = None):
            if not self._done.wait(timeout):
                raise TimeoutError(f"workflow {workflow_id} still running")
            if self._error is not None:
                raise self._error
            return self._value

    h = _Handle()

    def runner():
        try:
            h._value = _run_sync(dag, storage, args, kwargs or {})
        except BaseException as e:  # noqa: BLE001
            h._error = e
        finally:
            h._done.set()

    threading.Thread(target=runner, daemon=True,
                     name=f"workflow-{workflow_id}").start()
    return h


def resume(workflow_id: str) -> Any:
    """Re-run a workflow; completed steps load from their checkpoints."""
    storage = WorkflowStorage(workflow_id)
    if storage.has_output():
        return storage.load_output()
    dag = storage.load_dag()
    args, kwargs = storage.load_inputs()  # the original run's inputs
    return _run_sync(dag, storage, args, kwargs)


def get_status(workflow_id: str) -> Optional[str]:
    meta = WorkflowStorage(workflow_id).read_meta()
    return meta.get("status") if meta else None


def get_output(workflow_id: str) -> Any:
    storage = WorkflowStorage(workflow_id)
    if not storage.has_output():
        raise ValueError(f"workflow {workflow_id} has no output "
                         f"(status={get_status(workflow_id)})")
    return storage.load_output()


def list_all() -> List[Dict[str, Any]]:
    out = []
    try:
        ids = sorted(os.listdir(_root()))
    except OSError:
        return out
    for wid in ids:
        meta = WorkflowStorage(wid).read_meta()
        if meta:
            out.append(meta)
    return out


def delete(workflow_id: str) -> None:
    import shutil

    shutil.rmtree(os.path.join(_root(), workflow_id), ignore_errors=True)
