"""Durable workflows — the ``ray.workflow`` analog.

Reference: ``python/ray/workflow/`` (``workflow_executor.py``,
``workflow_state_from_dag.py``, ``workflow_storage.py``): a task DAG runs
with every step's result checkpointed to storage, so a crashed run
resumes from the last completed step instead of starting over.

    from ray_tpu import workflow

    @ray_tpu.remote
    def a(): ...
    @ray_tpu.remote
    def b(x): ...

    result = workflow.run(b.bind(a.bind()), workflow_id="my-flow")
    # after a crash:
    result = workflow.resume("my-flow")
"""

from ray_tpu.workflow.api import (
    EventListener,
    TimerListener,
    WorkflowCancellationError,
    WorkflowError,
    WorkflowExecutionError,
    cancel,
    continuation,
    delete,
    get_metadata,
    get_output,
    get_status,
    list_all,
    options,
    resume,
    resume_all,
    run,
    run_async,
    sleep,
    wait_for_event,
)

__all__ = [
    "run", "run_async", "resume", "resume_all", "get_status", "get_output",
    "get_metadata", "list_all", "delete", "cancel", "options",
    "continuation", "sleep", "wait_for_event", "EventListener",
    "TimerListener", "WorkflowError", "WorkflowExecutionError",
    "WorkflowCancellationError",
]
