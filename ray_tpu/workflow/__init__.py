"""Durable workflows — the ``ray.workflow`` analog.

Reference: ``python/ray/workflow/`` (``workflow_executor.py``,
``workflow_state_from_dag.py``, ``workflow_storage.py``): a task DAG runs
with every step's result checkpointed to storage, so a crashed run
resumes from the last completed step instead of starting over.

    from ray_tpu import workflow

    @ray_tpu.remote
    def a(): ...
    @ray_tpu.remote
    def b(x): ...

    result = workflow.run(b.bind(a.bind()), workflow_id="my-flow")
    # after a crash:
    result = workflow.resume("my-flow")
"""

from ray_tpu.workflow.api import (
    delete,
    get_output,
    get_status,
    list_all,
    resume,
    run,
    run_async,
)

__all__ = ["run", "run_async", "resume", "get_status", "get_output",
           "list_all", "delete"]
