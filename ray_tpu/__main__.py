"""``python -m ray_tpu`` — the CLI entry point (``ray`` command analog)."""

from ray_tpu.scripts.cli import main

main()
