"""ray_tpu.rllib — reinforcement learning on the jax substrate.

Analog of the reference's ``rllib/`` minimal spine (SURVEY §2.4):
``Algorithm``/``AlgorithmConfig`` as Tune Trainables, ``RolloutWorker``
actors gathered in a ``WorkerSet``, ``SampleBatch`` columns, GAE
postprocessing, and jax algorithm families: PPO/A2C/IMPALA (on-policy,
V-trace for the latter), DQN (replay + target net), SAC (continuous
control), with vectorized envs, greedy evaluation, and offline JSON IO.

Env<->policy preprocessing is composable ``connectors`` pipelines (the
reference's ``rllib/connectors/``), and models plug in through the
``RLModule`` surface (``core/rl_module``) — see those modules' docs.
"""

from ray_tpu.rllib import connectors
from ray_tpu.rllib.a2c import A2C, A2CConfig
from ray_tpu.rllib.algorithm import (
    Algorithm,
    AlgorithmConfig,
    synchronous_parallel_sample,
    train_one_step,
)
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.appo import APPO, APPOConfig
from ray_tpu.rllib.impala import Impala, ImpalaConfig, compute_vtrace
from ray_tpu.rllib.multi_agent import (
    MultiAgentBatch,
    MultiAgentEnv,
    MultiAgentPPO,
    MultiAgentPPOConfig,
    MultiAgentRolloutWorker,
)
from ray_tpu.rllib.envs import SyntheticAtariEnv, synthetic_atari_creator
from ray_tpu.rllib.offline import JsonReader, JsonWriter
from ray_tpu.rllib.policy_server import PolicyServer, RemotePolicy, serve_policy
from ray_tpu.rllib.rl_module import Columns, DefaultActorCriticModule, RLModule
from ray_tpu.rllib.sac import SAC, SACConfig, SACPolicy
from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.postprocessing import compute_gae
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.rollout_worker import RolloutWorker
from ray_tpu.rllib.sample_batch import SampleBatch
from ray_tpu.rllib.worker_set import WorkerSet

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "connectors",
    "RLModule",
    "DefaultActorCriticModule",
    "Columns",
    "PPO",
    "PPOConfig",
    "A2C",
    "A2CConfig",
    "Impala",
    "APPO",
    "APPOConfig",
    "ImpalaConfig",
    "compute_vtrace",
    "DQN",
    "DQNConfig",
    "SAC",
    "SACConfig",
    "SACPolicy",
    "JsonReader",
    "JsonWriter",
    "ReplayBuffer",
    "JaxPolicy",
    "RolloutWorker",
    "WorkerSet",
    "SampleBatch",
    "MultiAgentBatch",
    "MultiAgentEnv",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "MultiAgentRolloutWorker",
    "compute_gae",
    "synchronous_parallel_sample",
    "train_one_step",
    "SyntheticAtariEnv",
    "synthetic_atari_creator",
    "PolicyServer",
    "RemotePolicy",
    "serve_policy",
]
