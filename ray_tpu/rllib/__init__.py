"""ray_tpu.rllib — reinforcement learning on the jax substrate.

Analog of the reference's ``rllib/`` minimal spine (SURVEY §2.4):
``Algorithm``/``AlgorithmConfig`` as Tune Trainables, ``RolloutWorker``
actors gathered in a ``WorkerSet``, ``SampleBatch`` columns, GAE
postprocessing, PPO with a fully-jitted loss+update, and DQN with a
replay buffer + target network (``rllib/algorithms/dqn``).
"""

from ray_tpu.rllib.algorithm import (
    Algorithm,
    AlgorithmConfig,
    synchronous_parallel_sample,
    train_one_step,
)
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.postprocessing import compute_gae
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.rollout_worker import RolloutWorker
from ray_tpu.rllib.sample_batch import SampleBatch
from ray_tpu.rllib.worker_set import WorkerSet

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "PPO",
    "PPOConfig",
    "DQN",
    "DQNConfig",
    "ReplayBuffer",
    "JaxPolicy",
    "RolloutWorker",
    "WorkerSet",
    "SampleBatch",
    "compute_gae",
    "synchronous_parallel_sample",
    "train_one_step",
]
