"""Uniform replay buffer for off-policy algorithms.

Analog of the reference's ``rllib/utils/replay_buffers/replay_buffer.py``
(uniform sampling storage behind DQN-family algorithms): a preallocated
numpy ring over transition columns — O(1) add, vectorized sample.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


class ReplayBuffer:
    def __init__(self, capacity: int = 50_000, seed: int = 0):
        self.capacity = capacity
        self._cols: Optional[Dict[str, np.ndarray]] = None
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: SampleBatch) -> None:
        n = batch.count
        if n == 0:
            return
        if self._cols is None:
            self._cols = {
                k: np.zeros((self.capacity, *np.asarray(v).shape[1:]),
                            dtype=np.asarray(v).dtype)
                for k, v in batch.items()
            }
        for k, buf in self._cols.items():
            v = np.asarray(batch[k])
            take = min(n, self.capacity)
            v = v[-take:]  # a fragment larger than capacity keeps its tail
            end = self._idx + take
            if end <= self.capacity:
                buf[self._idx:end] = v
            else:
                split = self.capacity - self._idx
                buf[self._idx:] = v[:split]
                buf[:end - self.capacity] = v[split:]
        self._idx = (self._idx + min(n, self.capacity)) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> SampleBatch:
        idx = self._rng.integers(0, self._size, batch_size)
        return SampleBatch({k: buf[idx] for k, buf in self._cols.items()})
