"""Actor-critic model in pure jax.

The reference's ``ModelCatalog`` (``rllib/models/catalog.py:195``) builds
torch/tf nets; here the default model is a jax MLP with separate policy and
value trunks, expressed as a params pytree + pure apply so the whole PPO
update jits into one XLA program.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp


def _dense_params(key, n_in, n_out, scale=1.0):
    """Scaled-normal weights, zero bias (final layers down-scaled as in
    PPO practice) — shared by every model family in this module."""
    w = jax.random.normal(key, (n_in, n_out)) * scale / jnp.sqrt(n_in)
    return {"w": w, "b": jnp.zeros((n_out,))}


def _mlp(layers, x):
    """tanh MLP with a linear last layer."""
    for layer in layers[:-1]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    last = layers[-1]
    return x @ last["w"] + last["b"]


def init_actor_critic(
    rng: jax.Array, obs_dim: int, num_actions: int,
    hiddens: Sequence[int] = (64, 64),
) -> Dict:
    """Params for policy and value MLPs."""
    keys = jax.random.split(rng, 2 * len(hiddens) + 2)
    pi, vf = [], []
    n_in = obs_dim
    for i, h in enumerate(hiddens):
        pi.append(_dense_params(keys[2 * i], n_in, h))
        vf.append(_dense_params(keys[2 * i + 1], n_in, h))
        n_in = h
    pi.append(_dense_params(keys[-2], n_in, num_actions, 0.01))
    vf.append(_dense_params(keys[-1], n_in, 1))
    return {"pi": pi, "vf": vf}


def apply_actor_critic(params: Dict, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """obs [B, obs_dim] -> (logits [B, A], value [B])."""
    logits = _mlp(params["pi"], obs)
    value = _mlp(params["vf"], obs)[..., 0]
    return logits, value


# ---------------------------------------------------------------------------
# continuous-control nets (SAC): squashed-Gaussian actor + state-action Q
# ---------------------------------------------------------------------------


def init_gaussian_actor(rng, obs_dim: int, act_dim: int,
                        hiddens: Sequence[int] = (64, 64)) -> Dict:
    """Actor emitting (mean, log_std) per action dim."""
    keys = jax.random.split(rng, len(hiddens) + 1)
    layers = []
    n_in = obs_dim
    for i, h in enumerate(hiddens):
        layers.append(_dense_params(keys[i], n_in, h))
        n_in = h
    layers.append(_dense_params(keys[-1], n_in, 2 * act_dim, 0.01))
    return {"layers": layers}


def apply_gaussian_actor(params: Dict, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """obs [B, D] -> (mean [B, A], log_std [B, A]) with log_std bounded."""
    out = _mlp(params["layers"], obs)
    act_dim = out.shape[-1] // 2  # static: from the layer width, not a traced leaf
    mean, log_std = out[..., :act_dim], out[..., act_dim:]
    log_std = jnp.clip(log_std, -20.0, 2.0)
    return mean, log_std


def init_q_network(rng, obs_dim: int, act_dim: int,
                   hiddens: Sequence[int] = (64, 64)) -> Dict:
    keys = jax.random.split(rng, len(hiddens) + 1)
    layers = []
    n_in = obs_dim + act_dim
    for i, h in enumerate(hiddens):
        layers.append(_dense_params(keys[i], n_in, h))
        n_in = h
    layers.append(_dense_params(keys[-1], n_in, 1))
    return {"layers": layers}


def apply_q_network(params: Dict, obs: jax.Array, act: jax.Array) -> jax.Array:
    """(obs [B, D], act [B, A]) -> Q [B]."""
    x = jnp.concatenate([obs, act], axis=-1)
    return _mlp(params["layers"], x)[..., 0]
