"""Actor-critic model in pure jax.

The reference's ``ModelCatalog`` (``rllib/models/catalog.py:195``) builds
torch/tf nets; here the default model is a jax MLP with separate policy and
value trunks, expressed as a params pytree + pure apply so the whole PPO
update jits into one XLA program.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp


def init_actor_critic(
    rng: jax.Array, obs_dim: int, num_actions: int,
    hiddens: Sequence[int] = (64, 64),
) -> Dict:
    """Params for policy and value MLPs (orthogonal-ish init: scaled
    normal, zeros bias; final layers down-scaled as in PPO practice)."""

    def dense(key, n_in, n_out, scale):
        w_key, _ = jax.random.split(key)
        w = jax.random.normal(w_key, (n_in, n_out)) * scale / jnp.sqrt(n_in)
        return {"w": w, "b": jnp.zeros((n_out,))}

    keys = jax.random.split(rng, 2 * len(hiddens) + 2)
    pi, vf = [], []
    n_in = obs_dim
    for i, h in enumerate(hiddens):
        pi.append(dense(keys[2 * i], n_in, h, 1.0))
        vf.append(dense(keys[2 * i + 1], n_in, h, 1.0))
        n_in = h
    pi.append(dense(keys[-2], n_in, num_actions, 0.01))
    vf.append(dense(keys[-1], n_in, 1, 1.0))
    return {"pi": pi, "vf": vf}


def apply_actor_critic(params: Dict, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """obs [B, obs_dim] -> (logits [B, A], value [B])."""

    def mlp(layers, x):
        for layer in layers[:-1]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        last = layers[-1]
        return x @ last["w"] + last["b"]

    logits = mlp(params["pi"], obs)
    value = mlp(params["vf"], obs)[..., 0]
    return logits, value
