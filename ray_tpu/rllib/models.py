"""Model catalog in pure jax: MLP and CNN actor-critics.

The reference's ``ModelCatalog`` (``rllib/models/catalog.py:195``) builds
torch/tf nets by observation space; here the catalog picks a jax MLP for
flat observations and a Nature-DQN-style CNN (NHWC convs — the TPU-native
layout) for image observations, both expressed as a params pytree + pure
apply so the whole PPO update jits into one XLA program.  Dispatch is
structural (``apply_model``): the params pytree carries its architecture,
so one loss function serves both model families.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _dense_params(key, n_in, n_out, scale=1.0):
    """Scaled-normal weights, zero bias (final layers down-scaled as in
    PPO practice) — shared by every model family in this module."""
    w = jax.random.normal(key, (n_in, n_out)) * scale / jnp.sqrt(n_in)
    return {"w": w, "b": jnp.zeros((n_out,))}


def _mlp(layers, x):
    """tanh MLP with a linear last layer."""
    for layer in layers[:-1]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    last = layers[-1]
    return x @ last["w"] + last["b"]


def init_actor_critic(
    rng: jax.Array, obs_dim: int, num_actions: int,
    hiddens: Sequence[int] = (64, 64),
) -> Dict:
    """Params for policy and value MLPs."""
    keys = jax.random.split(rng, 2 * len(hiddens) + 2)
    pi, vf = [], []
    n_in = obs_dim
    for i, h in enumerate(hiddens):
        pi.append(_dense_params(keys[2 * i], n_in, h))
        vf.append(_dense_params(keys[2 * i + 1], n_in, h))
        n_in = h
    pi.append(_dense_params(keys[-2], n_in, num_actions, 0.01))
    vf.append(_dense_params(keys[-1], n_in, 1))
    return {"pi": pi, "vf": vf}


def apply_actor_critic(params: Dict, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """obs [B, obs_dim] -> (logits [B, A], value [B])."""
    logits = _mlp(params["pi"], obs)
    value = _mlp(params["vf"], obs)[..., 0]
    return logits, value


# ---------------------------------------------------------------------------
# CNN actor-critic (Atari-shaped inputs — catalog.py:195's conv path)
# ---------------------------------------------------------------------------

# Nature-DQN conv stack: (out_channels, kernel, stride)
NATURE_CONV_FILTERS = ((32, 8, 4), (64, 4, 2), (64, 3, 1))

import dataclasses as _dataclasses


@jax.tree_util.register_static
@_dataclasses.dataclass(frozen=True)
class ConvSpec:
    """Static conv architecture metadata carried INSIDE the params pytree
    (treedef, not leaf): optimizers skip it, jit specializes on it."""

    filters: Tuple[Tuple[int, int, int], ...]


def _conv_params(key, k, c_in, c_out):
    # HWIO kernels (the TPU-native conv layout alongside NHWC activations)
    fan_in = k * k * c_in
    w = jax.random.normal(key, (k, k, c_in, c_out)) * np.sqrt(2.0 / fan_in)
    return {"w": w, "b": jnp.zeros((c_out,))}


def _conv_forward(convs, x, filters):
    for layer, (_, k, stride) in zip(convs, filters):
        x = jax.lax.conv_general_dilated(
            x, layer["w"], window_strides=(stride, stride), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + layer["b"]
        x = jax.nn.relu(x)
    return x.reshape(x.shape[0], -1)


def init_conv_actor_critic(
    rng: jax.Array, obs_shape: Tuple[int, int, int], num_actions: int,
    conv_filters: Sequence[Tuple[int, int, int]] = NATURE_CONV_FILTERS,
    hiddens: Sequence[int] = (256,),
) -> Dict:
    """Shared conv trunk + separate pi/vf dense heads for [H, W, C] obs.
    The params dict carries its architecture (``conv_spec`` static node)
    so ``apply_model`` can dispatch without side-channel config."""
    H, W, C = obs_shape
    keys = jax.random.split(rng, len(conv_filters) + 2 * len(hiddens) + 2)
    convs = []
    c_in = C
    for i, (c_out, k, stride) in enumerate(conv_filters):
        convs.append(_conv_params(keys[i], k, c_in, c_out))
        c_in = c_out
    # flattened trunk width via shape-only tracing (no FLOPs)
    flat = jax.eval_shape(
        lambda cs, x: _conv_forward(cs, x, conv_filters),
        convs, jax.ShapeDtypeStruct((1, H, W, C), jnp.float32),
    ).shape[-1]
    base = len(conv_filters)
    pi, vf = [], []
    n_in = flat
    for i, h in enumerate(hiddens):
        pi.append(_dense_params(keys[base + 2 * i], n_in, h))
        vf.append(_dense_params(keys[base + 2 * i + 1], n_in, h))
        n_in = h
    pi.append(_dense_params(keys[-2], n_in, num_actions, 0.01))
    vf.append(_dense_params(keys[-1], n_in, 1))
    return {
        "conv": convs, "pi": pi, "vf": vf,
        # STATIC pytree node: part of the treedef, not a leaf — the
        # optimizer never sees it, jit specializes on it, and apply_model
        # reads the true strides instead of assuming the Nature defaults
        "conv_spec": ConvSpec(tuple(tuple(f) for f in conv_filters)),
    }


def apply_conv_actor_critic(params: Dict, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """obs [B, H, W, C] (float; scale pixels yourself) -> (logits, value)."""
    filters = params["conv_spec"].filters
    x = _conv_forward(params["conv"], obs, filters)  # relu'd + flat
    logits = _mlp(params["pi"], x)
    value = _mlp(params["vf"], x)[..., 0]
    return logits, value


def apply_model(params: Dict, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Catalog dispatch: the params pytree names its architecture.

    uint8 pixel observations are cast+scaled HERE (device-side), so the
    whole pipeline — rollout transport, sample batches, SGD minibatches —
    carries 1-byte pixels instead of 4-byte floats (4x less host<->device
    and object-store traffic; the wrapped-Atari preprocessing the
    reference does in ``atari_wrappers.py:244``)."""
    obs = jnp.asarray(obs)
    if jnp.issubdtype(obs.dtype, jnp.integer):
        obs = obs.astype(jnp.float32) / 255.0
    if "conv" in params:
        return apply_conv_actor_critic(params, obs)
    return apply_actor_critic(params, obs)


# ---------------------------------------------------------------------------
# continuous-control nets (SAC): squashed-Gaussian actor + state-action Q
# ---------------------------------------------------------------------------


def init_gaussian_actor(rng, obs_dim: int, act_dim: int,
                        hiddens: Sequence[int] = (64, 64)) -> Dict:
    """Actor emitting (mean, log_std) per action dim."""
    keys = jax.random.split(rng, len(hiddens) + 1)
    layers = []
    n_in = obs_dim
    for i, h in enumerate(hiddens):
        layers.append(_dense_params(keys[i], n_in, h))
        n_in = h
    layers.append(_dense_params(keys[-1], n_in, 2 * act_dim, 0.01))
    return {"layers": layers}


def apply_gaussian_actor(params: Dict, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """obs [B, D] -> (mean [B, A], log_std [B, A]) with log_std bounded."""
    out = _mlp(params["layers"], obs)
    act_dim = out.shape[-1] // 2  # static: from the layer width, not a traced leaf
    mean, log_std = out[..., :act_dim], out[..., act_dim:]
    log_std = jnp.clip(log_std, -20.0, 2.0)
    return mean, log_std


def init_q_network(rng, obs_dim: int, act_dim: int,
                   hiddens: Sequence[int] = (64, 64)) -> Dict:
    keys = jax.random.split(rng, len(hiddens) + 1)
    layers = []
    n_in = obs_dim + act_dim
    for i, h in enumerate(hiddens):
        layers.append(_dense_params(keys[i], n_in, h))
        n_in = h
    layers.append(_dense_params(keys[-1], n_in, 1))
    return {"layers": layers}


def apply_q_network(params: Dict, obs: jax.Array, act: jax.Array) -> jax.Array:
    """(obs [B, D], act [B, A]) -> Q [B]."""
    x = jnp.concatenate([obs, act], axis=-1)
    return _mlp(params["layers"], x)[..., 0]
