"""RLModule: the model plugin surface (``rl_module.py:23`` analog).

The reference's RLModule separates "what the network computes" from "how
the policy samples/learns": a module exposes ``forward_inference`` (the
greedy serving path), ``forward_exploration`` (the sampling path) and
``forward_train`` (the loss path), and algorithms are written against
those three.  Here the same split lands on the jax substrate: a module is
a STATELESS description — ``init(rng) -> params`` plus pure forward
functions over the params pytree — so every forward jits, params remain a
plain optimizer-visible pytree, and one module serves CPU rollout workers
and the chip-resident PolicyServer alike.

Custom JAX models plug in WITHOUT subclassing Policy::

    class MyModule(RLModule):
        def init(self, rng): ...
        def forward_train(self, params, obs):
            return {Columns.ACTION_DIST_INPUTS: logits,
                    Columns.VF_PREDS: value}

    config.rl_module(lambda ctx: MyModule(ctx.obs_dim, ctx.num_actions))

The factory rides the config dict to every rollout worker and the
PolicyServer; ``JaxPolicy`` routes acting, value bootstraps, greedy
inference, and every algorithm loss through the module's forwards.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from ray_tpu.rllib.models import (
    apply_model,
    init_actor_critic,
    init_conv_actor_critic,
)


class Columns:
    """Forward-output keys (the reference's ``core.columns.Columns``)."""

    ACTION_DIST_INPUTS = "action_dist_inputs"
    VF_PREDS = "vf_preds"


class RLModule:
    """Base plugin: pure functions over a params pytree.

    ``forward_train`` is the only required forward — exploration and
    inference default to it, which is correct for any shared-trunk
    actor-critic.  Override them when the paths genuinely differ
    (e.g. dropout off at inference, exploration noise heads).

    Every forward MUST be jax-traceable (no python side effects on data):
    they run under ``jax.jit`` inside sampling, loss, and server-side SGD.
    """

    def init(self, rng) -> Any:
        """Build the params pytree."""
        raise NotImplementedError

    def forward_train(self, params, obs) -> Dict[str, Any]:
        """Loss-path forward: must return ``Columns.ACTION_DIST_INPUTS``
        (logits / dist params) and ``Columns.VF_PREDS``."""
        raise NotImplementedError

    def forward_exploration(self, params, obs) -> Dict[str, Any]:
        """Sampling-path forward (stochastic acting)."""
        return self.forward_train(params, obs)

    def forward_inference(self, params, obs) -> Dict[str, Any]:
        """Greedy serving-path forward (evaluation, PolicyServer)."""
        return self.forward_exploration(params, obs)


class DefaultActorCriticModule(RLModule):
    """The catalog's MLP/CNN actor-critic as a module: what every policy
    uses when no custom module is configured.  Picklable by construction
    (plain python scalars), so it rides config dicts to remote workers."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Tuple[int, ...] = (64, 64),
                 obs_shape: Optional[Tuple[int, ...]] = None):
        self.obs_dim = int(obs_dim)
        self.num_actions = int(num_actions)
        self.hiddens = tuple(int(h) for h in hiddens)
        self.obs_shape = tuple(obs_shape) if obs_shape else None

    def init(self, rng) -> Any:
        if self.obs_shape is not None and len(self.obs_shape) == 3:
            return init_conv_actor_critic(
                rng, self.obs_shape, self.num_actions, hiddens=self.hiddens)
        return init_actor_critic(
            rng, self.obs_dim, self.num_actions, self.hiddens)

    def forward_train(self, params, obs) -> Dict[str, Any]:
        logits, value = apply_model(params, obs)
        return {Columns.ACTION_DIST_INPUTS: logits, Columns.VF_PREDS: value}
