"""Chip-resident policy service: batched inference + learner in one actor.

The reference scales Atari PPO by running policy inference inside each
CPU rollout worker and shipping gradients/weights around
(``/root/reference/rllib/evaluation/rollout_worker.py:153``,
``rllib/execution/train_ops.py:26``).  On TPU that shape is wrong twice
over: CPU conv inference starves the chip, and per-minibatch host round
trips dominate SGD on a remote-attached device.  Here ONE actor owns the
chip and exposes the whole policy surface:

- ``compute_actions`` — rollout workers ship uint8 observation batches
  and get (actions, logp, vf) back; concurrent worker calls pipeline on
  the device (the actor runs threaded; readbacks overlap dispatch).
- ``train_on_batch`` — the learner: one batch ships once, every SGD
  minibatch update runs device-side with no intermediate readbacks.

Rollout workers plug in through :class:`RemotePolicy`, which implements
the JaxPolicy calling convention over an actor handle, so RolloutWorker,
the algorithms, and checkpointing are unchanged (``_policy_class`` seam).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu._private import events


class PolicyServer:
    """Actor hosting the real JaxPolicy (build with ``num_tpus=1`` and
    ``max_concurrency > num_rollout_workers`` so worker inference calls
    overlap on the device)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 policy_kwargs: Optional[Dict[str, Any]] = None,
                 algo_config: Optional[Dict[str, Any]] = None):
        from ray_tpu.rllib.policy import JaxPolicy

        kwargs = dict(policy_kwargs or {})
        if algo_config is not None:
            # mirror RolloutWorker's policy construction from a config
            factory = algo_config.get("_loss_factory")
            if factory is not None and "loss_fn" not in kwargs:
                kwargs["loss_fn"] = factory(algo_config)
            kwargs.setdefault("lr", algo_config.get("lr", 5e-4))
            kwargs.setdefault(
                "hiddens", tuple(algo_config.get("fcnet_hiddens", (64, 64))))
            kwargs.setdefault("grad_clip", algo_config.get("grad_clip", 0.5))
            kwargs.setdefault("seed", int(algo_config.get("seed") or 0))
            module_factory = algo_config.get("_rl_module_factory")
            if module_factory is not None and "module" not in kwargs:
                # same RLModule plugin seam as RolloutWorker: the server-
                # resident policy routes its forwards through the module
                from ray_tpu.rllib.connectors import ConnectorContext

                obs_shape = tuple(kwargs.get("obs_shape") or (obs_dim,))
                kwargs["module"] = module_factory(ConnectorContext(
                    obs_shape=obs_shape, obs_dim=obs_dim,
                    num_actions=num_actions, config=dict(algo_config)))
        self.policy = JaxPolicy(obs_dim, num_actions, **kwargs)
        # serializes rng splits and param updates; device dispatch happens
        # inside, readbacks outside, so concurrent callers overlap the
        # expensive part (host<->device transit)
        self._lock = threading.Lock()
        self._weights_version = 0
        # frame-stack transport (remote-attached chips: host->device moves
        # ~10-30 MB/s, so shipping full 4-channel stacks every tick — 3 of
        # whose channels the device already holds — wastes 4x bandwidth):
        # per-worker device-resident stacked observations, advanced from
        # single new frames; snapshots cached device-side so training
        # never re-ships pixels at all
        self._rollouts: Dict[int, Dict[str, Any]] = {}
        # insertion-ordered (python dict): eviction is FIFO = oldest first
        self._obs_cache: Dict[Tuple[int, int], Any] = {}
        self._obs_cache_bytes = 0
        # backstop if training never consumes the cache; sized in bytes so
        # n_envs doesn't change the memory envelope
        self._obs_cache_cap_bytes = 2 << 30
        self._advance_jit = None
        self._update_cached_jit = None

    def describe(self) -> Dict[str, Any]:
        return {
            "has_conv": "conv" in self.policy.params,
            "weights_version": self._weights_version,
        }

    # -- inference ------------------------------------------------------
    def compute_actions(self, obs: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        p = self.policy
        with self._lock:
            p._rng, key = jax.random.split(p._rng)
            a, lp, v = p._sample_jit(p.params, key, jnp.asarray(obs))
            for x in (a, lp, v):
                if hasattr(x, "copy_to_host_async"):
                    x.copy_to_host_async()
        out = np.asarray(a), np.asarray(lp), np.asarray(v)
        # server-side compute span: a rollout worker's infer_s minus the
        # sum of these is the transport share of its inference wait
        events.emit("rllib", "policy inference", entity_id="policy-server",
                    span_dur=time.perf_counter() - t0, batch=len(out[0]))
        return out

    # -- frame-stack transport -----------------------------------------
    def start_rollout(self, worker_id: int, n_envs: int) -> bool:
        """(Re)initialize a worker's device-resident stacked observation
        state; clears its cached snapshots (worker restart path)."""
        with self._lock:
            self._rollouts[worker_id] = {"state": None, "n_envs": n_envs,
                                         "tick": -1}
            self._obs_cache = {
                k: v for k, v in self._obs_cache.items() if k[0] != worker_id
            }
        return True

    def _build_advance(self):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def advance(state, new_frames, reset_mask):
            # state [n, H, W, C] uint8; new_frames [n, H, W]; reset rows
            # become C copies of the fresh frame (the DeepMind frame-stack
            # reset semantic); live rows roll and append
            rolled = jnp.concatenate(
                [state[..., 1:], new_frames[..., None]], axis=-1)
            stacked = jnp.repeat(
                new_frames[..., None], state.shape[-1], axis=-1)
            return jnp.where(
                reset_mask[:, None, None, None], stacked, rolled)

        return advance

    def compute_actions_stacked(self, worker_id: int, new_frames: np.ndarray,
                                reset_mask: np.ndarray):
        """One rollout tick shipping ONLY each env's newest frame
        [n, H, W] uint8 (+ reset mask); the device rolls its resident
        stacks, runs the policy, and snapshots the stacks for training.
        Returns (actions, logp, vf, tick) — obs references (worker, tick,
        env) stand in for pixels in the sample batch."""
        import jax
        import jax.numpy as jnp

        t_start = time.perf_counter()
        p = self.policy
        with self._lock:
            ro = self._rollouts.get(worker_id)
            if ro is None:
                ro = self._rollouts[worker_id] = {
                    "state": None, "n_envs": len(new_frames), "tick": -1}
            if self._advance_jit is None:
                self._advance_jit = self._build_advance()
            if ro["state"] is None:
                n, h, w = new_frames.shape
                c = 4
                ro["state"] = jnp.zeros((n, h, w, c), jnp.uint8)
            ro["state"] = self._advance_jit(
                ro["state"], jnp.asarray(new_frames),
                jnp.asarray(reset_mask.astype(bool)))
            ro["tick"] += 1
            tick = ro["tick"]
            self._obs_cache[(worker_id, tick)] = ro["state"]
            self._obs_cache_bytes += int(np.prod(ro["state"].shape))
            while (self._obs_cache_bytes > self._obs_cache_cap_bytes
                   and len(self._obs_cache) > 1):
                oldest = next(iter(self._obs_cache))  # FIFO: oldest insert
                self._obs_cache_bytes -= int(
                    np.prod(self._obs_cache.pop(oldest).shape))
            p._rng, key = jax.random.split(p._rng)
            a, lp, v = p._sample_jit(p.params, key, ro["state"])
            for x in (a, lp, v):
                if hasattr(x, "copy_to_host_async"):
                    x.copy_to_host_async()
        events.emit("rllib", "policy inference", entity_id="policy-server",
                    span_dur=time.perf_counter() - t_start,
                    batch=len(new_frames), stacked=True)
        return np.asarray(a), np.asarray(lp), np.asarray(v), tick

    def peek_obs(self, worker_id: int) -> Optional[np.ndarray]:
        """Current device-resident stacks for a worker (tests/debugging)."""
        with self._lock:
            ro = self._rollouts.get(worker_id)
            if ro is None or ro["state"] is None:
                return None
            return np.asarray(ro["state"])

    def value(self, obs: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        t0 = time.perf_counter()
        with self._lock:
            v = self.policy._value_jit(self.policy.params, jnp.asarray(obs))
        out = np.asarray(v)
        # bootstrap value calls count into the workers' infer_s; without
        # this span their server-side compute would read as "transport"
        # in the scaling-knee attribution
        events.emit("rllib", "policy inference", entity_id="policy-server",
                    span_dur=time.perf_counter() - t0, batch=len(out))
        return out

    def greedy_action(self, obs: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        with self._lock:
            a = self.policy._greedy_jit(self.policy.params, jnp.asarray(obs))
        return np.asarray(a)

    def action_logp(self, obs: np.ndarray, actions: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        with self._lock:
            lp = self.policy._action_logp_jit(
                self.policy.params, jnp.asarray(obs), jnp.asarray(actions))
        return np.asarray(lp)

    # -- learning -------------------------------------------------------
    def train_on_batch(self, cols: Dict[str, np.ndarray], *,
                       num_sgd_iter: int, sgd_minibatch_size: int,
                       seed: int = 0) -> Dict[str, float]:
        """Minibatch SGD epochs entirely server-side: the batch crosses
        the wire once; each update is a single device dispatch (metrics
        read back once at the end).  An ``obs`` column of [N, 3] int32
        (worker, tick, env) references — the frame-stack transport path —
        is resolved against the device-resident snapshots instead:
        training then ships NO pixels at all."""
        obs = cols.get("obs")
        if (isinstance(obs, np.ndarray) and obs.ndim == 2
                and obs.shape[1] == 3
                and np.issubdtype(obs.dtype, np.integer)):
            # reference rows are unambiguous — an empty cache is an error
            # (evicted or purged), never a reason to train on coordinates
            return self._train_cached(
                cols, num_sgd_iter=num_sgd_iter,
                sgd_minibatch_size=sgd_minibatch_size, seed=seed)
        from ray_tpu.rllib.sample_batch import SampleBatch

        batch = SampleBatch(cols)
        rng = np.random.default_rng(seed)
        mb_size = min(sgd_minibatch_size, batch.count)
        metrics: Dict[str, float] = {}
        count = 0
        with self._lock:
            for _ in range(num_sgd_iter):
                for mb in batch.minibatches(mb_size, rng):
                    out = self.policy.learn_on_minibatch(dict(mb.items()))
                    for k, v in out.items():
                        metrics[k] = metrics.get(k, 0.0) + v
                    count += 1
            self._weights_version += 1
        return {k: v / max(count, 1) for k, v in metrics.items()}

    def _build_update_cached(self):
        import jax
        import optax

        loss_fn = self.policy._loss_fn
        optimizer = self.policy.optimizer

        @jax.jit
        def upd(params, opt_state, flat_obs, cols, idx):
            batch = {k: v[idx] for k, v in cols.items()}
            batch["obs"] = flat_obs[idx]  # device gather — no host pixels
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, metrics

        return upd

    def _train_cached(self, cols: Dict[str, np.ndarray], *,
                      num_sgd_iter: int, sgd_minibatch_size: int,
                      seed: int) -> Dict[str, float]:
        import jax.numpy as jnp

        cols = dict(cols)
        refs = cols.pop("obs")
        with self._lock:
            # concatenate ONLY the snapshots this batch references — other
            # workers'/rounds' entries stay in cache, unmaterialized
            needed = sorted({(int(w), int(t)) for w, t, _ in refs})
            missing = [k for k in needed if k not in self._obs_cache]
            if missing:
                raise RuntimeError(
                    f"observation snapshots {missing[:3]} (of {len(missing)})"
                    " were evicted before training — raise the PolicyServer"
                    " obs cache cap or train sooner")
            offsets: Dict[Tuple[int, int], int] = {}
            arrs = []
            off = 0
            for k in needed:
                arr = self._obs_cache[k]
                offsets[k] = off
                off += arr.shape[0]
                arrs.append(arr)
            flat = jnp.concatenate(arrs, axis=0)
            row = np.array(
                [offsets[(int(w), int(t))] + int(e) for w, t, e in refs],
                np.int32)
            cols_dev = {k: jnp.asarray(v) for k, v in cols.items()}
            if self._update_cached_jit is None:
                self._update_cached_jit = self._build_update_cached()
            rng = np.random.default_rng(seed)
            n = len(row)
            mb = min(sgd_minibatch_size, n)
            params, opt_state = self.policy.params, self.policy.opt_state
            acc = None
            count = 0
            for _ in range(num_sgd_iter):
                perm = rng.permutation(n)
                for s in range(0, n - mb + 1, mb):
                    idx = jnp.asarray(row[perm[s:s + mb]])
                    params, opt_state, loss, m = self._update_cached_jit(
                        params, opt_state, flat, cols_dev, idx)
                    m = dict(m, total_loss=loss)
                    # accumulate ON DEVICE; one readback at the end
                    acc = m if acc is None else {
                        k: acc[k] + m[k] for k in m}
                    count += 1
            self.policy.params, self.policy.opt_state = params, opt_state
            self._weights_version += 1
            for k in needed:  # consumed; other entries await their batch
                self._obs_cache.pop(k, None)
            self._obs_cache_bytes = sum(
                int(np.prod(v.shape)) for v in self._obs_cache.values())
        names = sorted(acc)
        vals = np.asarray(jnp.stack([acc[k] for k in names]))
        return {k: float(v) / max(count, 1) for k, v in zip(names, vals)}

    # -- weights / state ------------------------------------------------
    def get_weights(self):
        with self._lock:
            return self.policy.get_weights()

    def set_weights(self, weights) -> int:
        with self._lock:
            self.policy.set_weights(weights)
            self._weights_version += 1
            return self._weights_version

    def get_state(self) -> Dict[str, Any]:
        with self._lock:
            return self.policy.get_state()

    def set_state(self, state: Dict[str, Any]) -> int:
        with self._lock:
            self.policy.set_state(state)
            self._weights_version += 1
            return self._weights_version


_SERVER_WEIGHTS_SENTINEL = "__policy_server_weights__"


class RemotePolicy:
    """JaxPolicy-shaped client over a PolicyServer handle.

    Accepts (and ignores) the local-policy construction kwargs so it drops
    into RolloutWorker through the ``_policy_class`` config seam.  Weight
    sync between workers becomes O(1): every worker's policy IS the same
    server, so ``get_weights`` returns a version token and ``set_weights``
    with a token is a no-op.
    """

    def __init__(self, obs_dim: int, num_actions: int, *, server=None,
                 timeout: float = 300.0, **_ignored):
        if server is None:
            raise ValueError(
                "RemotePolicy needs a PolicyServer actor handle: pass "
                "config['_policy_kwargs'] = {'server': handle}")
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self._server = server
        self._timeout = timeout
        import ray_tpu

        self._get = lambda ref: ray_tpu.get(ref, timeout=self._timeout)
        desc = self._get(server.describe.remote())
        # RolloutWorker sniffs `"conv" in policy.params` to keep image
        # observations [H, W, C]; mirror the server's architecture flag
        self.params: Dict[str, Any] = {"conv": True} if desc["has_conv"] else {}

    # -- acting ---------------------------------------------------------
    def compute_actions(self, obs):
        return self._get(self._server.compute_actions.remote(obs))

    def start_rollout(self, worker_id: int, n_envs: int):
        return self._get(self._server.start_rollout.remote(worker_id, n_envs))

    def compute_actions_stacked(self, worker_id, new_frames, reset_mask):
        return self._get(self._server.compute_actions_stacked.remote(
            worker_id, new_frames, reset_mask))

    def value(self, obs):
        return self._get(self._server.value.remote(obs))

    def greedy_action(self, obs):
        return self._get(self._server.greedy_action.remote(obs))

    def action_logp(self, obs, actions):
        return self._get(self._server.action_logp.remote(obs, actions))

    # -- learning -------------------------------------------------------
    def train_on_batch(self, batch, *, num_sgd_iter: int,
                       sgd_minibatch_size: int, required_keys: tuple,
                       seed: int = 0) -> Dict[str, float]:
        cols = {k: batch[k] for k in required_keys}
        return self._get(self._server.train_on_batch.remote(
            cols, num_sgd_iter=num_sgd_iter,
            sgd_minibatch_size=sgd_minibatch_size, seed=seed))

    def learn_on_minibatch(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        return self._get(self._server.train_on_batch.remote(
            dict(batch), num_sgd_iter=1, sgd_minibatch_size=1 << 62))

    # -- weights --------------------------------------------------------
    def get_weights(self):
        return {_SERVER_WEIGHTS_SENTINEL: True}

    def set_weights(self, weights) -> None:
        if isinstance(weights, dict) and weights.get(_SERVER_WEIGHTS_SENTINEL):
            return  # all workers share the server; nothing to ship
        self._get(self._server.set_weights.remote(weights))

    def get_state(self):
        return self._get(self._server.get_state.remote())

    def set_state(self, state):
        self._get(self._server.set_state.remote(state))


def serve_policy(algo_config: Dict[str, Any], obs_dim: int, num_actions: int,
                 *, obs_shape: Optional[tuple] = None, num_tpus: float = 0,
                 max_concurrency: int = 16, frame_stack_transport: bool = False):
    """Start a PolicyServer actor for ``algo_config`` and return its
    handle, plus the config entries that point rollout workers at it::

        handle, overrides = serve_policy(cfg, obs_dim, n_act,
                                         obs_shape=(84, 84, 4), num_tpus=1)
        cfg.update(overrides)

    ``frame_stack_transport=True`` (channel-stacked uint8 image envs whose
    reset stacks copies of the first frame — the DeepMind Atari contract):
    workers ship only each env's NEWEST frame per tick, the server keeps
    the stacks device-resident, and training resolves observations from
    device snapshots — pixels cross the host->device link once instead of
    5x (4x stack redundancy + training re-ship).
    """
    import ray_tpu

    policy_kwargs: Dict[str, Any] = {}
    if obs_shape is not None and len(obs_shape) == 3:
        policy_kwargs["obs_shape"] = tuple(obs_shape)
    opts: Dict[str, Any] = {"max_concurrency": max_concurrency}
    if num_tpus:
        opts["num_tpus"] = num_tpus
    handle = ray_tpu.remote(PolicyServer).options(**opts).remote(
        obs_dim, num_actions, policy_kwargs, algo_config)
    overrides = {
        "_policy_class": RemotePolicy,
        "_policy_kwargs": {"server": handle},
        "_frame_stack_transport": bool(frame_stack_transport),
    }
    return handle, overrides
