"""Algorithm / AlgorithmConfig: the RLlib training driver.

Analog of ``/root/reference/rllib/algorithms/algorithm.py:142`` (Algorithm
— a Tune Trainable whose ``step`` runs ``training_step`` and aggregates
rollout metrics) and ``algorithm_config.py:112`` (the fluent builder).
An Algorithm owns a WorkerSet; subclasses implement ``training_step()``
(sample → SGD → sync), the reference's ``algorithm.py:1284`` seam.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional, Type

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch
from ray_tpu.rllib.worker_set import WorkerSet
from ray_tpu.tune.trainable import Trainable


class AlgorithmConfig:
    """Fluent config builder (``algorithm_config.py:112`` analog)."""

    def __init__(self, algo_class: Optional[Type["Algorithm"]] = None):
        self.algo_class = algo_class
        self._config: Dict[str, Any] = {
            "env": None,
            "env_creator": None,
            "env_config": {},
            "num_rollout_workers": 0,
            "num_cpus_per_worker": 1,
            "rollout_fragment_length": 200,
            "num_envs_per_worker": 1,
            "train_batch_size": 4000,
            "evaluation_interval": 0,  # 0 = never
            "evaluation_num_episodes": 5,
            "input": None,
            "output": None,
            "gamma": 0.99,
            "lr": 5e-4,
            "fcnet_hiddens": (64, 64),
            "seed": 0,
            "framework": "jax",
            # env<->policy transform pipelines (rllib/connectors); None =
            # defaults derived from the spaces.  "observation_filter"
            # appends running-stat normalization to the default pipeline
            # (the reference's MeanStdFilter config knob).
            "agent_connectors": None,
            "action_connectors": None,
            "observation_filter": None,
            # RLModule plugin: factory(ConnectorContext) -> RLModule
            "_rl_module_factory": None,
        }

    # -- fluent sections (reference section names) ---------------------
    def environment(self, env: Optional[str] = None, *, env_creator=None,
                    env_config: Optional[Dict] = None) -> "AlgorithmConfig":
        if env is not None:
            self._config["env"] = env
        if env_creator is not None:
            self._config["env_creator"] = env_creator
        if env_config is not None:
            self._config["env_config"] = env_config
        return self

    def rollouts(self, *, num_rollout_workers: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self._config["num_rollout_workers"] = num_rollout_workers
        if rollout_fragment_length is not None:
            self._config["rollout_fragment_length"] = rollout_fragment_length
        if num_envs_per_worker is not None:
            self._config["num_envs_per_worker"] = num_envs_per_worker
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        self._config.update(kwargs)
        return self

    def resources(self, *, num_cpus_per_worker: Optional[int] = None) -> "AlgorithmConfig":
        if num_cpus_per_worker is not None:
            self._config["num_cpus_per_worker"] = num_cpus_per_worker
        return self

    def framework(self, framework: str = "jax") -> "AlgorithmConfig":
        if framework != "jax":
            raise ValueError("only framework='jax' is supported")
        return self

    def connectors(self, *, agent_connectors=None, action_connectors=None,
                   observation_filter: Optional[str] = None
                   ) -> "AlgorithmConfig":
        """Compose the env<->policy transform pipelines.

        ``agent_connectors``/``action_connectors`` accept a list of
        connector instances, ``(name, kwargs)`` pairs, or a factory
        ``fn(ctx) -> connectors``; ``observation_filter="MeanStdFilter"``
        appends running-stat normalization to the default pipeline."""
        if agent_connectors is not None:
            self._config["agent_connectors"] = agent_connectors
        if action_connectors is not None:
            self._config["action_connectors"] = action_connectors
        if observation_filter is not None:
            self._config["observation_filter"] = observation_filter
        return self

    def rl_module(self, module_factory) -> "AlgorithmConfig":
        """Plug a custom model in WITHOUT subclassing Policy:
        ``module_factory(ctx: ConnectorContext) -> RLModule`` builds the
        network every policy (rollout workers, learner, PolicyServer)
        routes its forwards through."""
        self._config["_rl_module_factory"] = module_factory
        return self

    def evaluation(self, *, evaluation_interval: Optional[int] = None,
                   evaluation_num_episodes: Optional[int] = None) -> "AlgorithmConfig":
        if evaluation_interval is not None:
            self._config["evaluation_interval"] = evaluation_interval
        if evaluation_num_episodes is not None:
            self._config["evaluation_num_episodes"] = evaluation_num_episodes
        return self

    def offline_data(self, *, input_: Optional[str] = None,
                     output: Optional[str] = None) -> "AlgorithmConfig":
        """Offline IO (``rllib/offline`` analog): ``output`` makes every
        rollout worker write its fragments as JSON lines; ``input_`` trains
        replay-based algorithms from recorded batches instead of an env."""
        if input_ is not None:
            self._config["input"] = input_
        if output is not None:
            self._config["output"] = output
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self._config["seed"] = seed
        return self

    # -- materialize ---------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        d = copy.copy(self._config)
        d["_algo_class"] = self.algo_class
        return d

    def build(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("config has no algo_class; use e.g. PPOConfig()")
        return self.algo_class(config=self.to_dict())


class Algorithm(Trainable):
    """Tune-trainable RL driver (``algorithm.py:142``)."""

    _default_config: Dict[str, Any] = {}

    def __init__(self, config: Optional[Any] = None, **kwargs):
        if isinstance(config, AlgorithmConfig):
            config = config.to_dict()
        super().__init__(config, **kwargs)

    # -- Trainable hooks -----------------------------------------------
    def setup(self, config: Dict[str, Any]) -> None:
        from ray_tpu._private.usage import record_feature
        record_feature("rllib")
        merged = dict(self._default_config)
        merged.update({k: v for k, v in config.items() if k != "_algo_class"})
        self.config = merged
        self.workers = WorkerSet(merged)
        self._timesteps_total = 0
        self._iteration_count = 0
        self.reader = None
        if merged.get("input"):
            from ray_tpu.rllib.offline import JsonReader

            self.reader = JsonReader(merged["input"])

    def step(self) -> Dict[str, Any]:
        results = self.training_step()
        self._iteration_count += 1
        metrics = (
            self.workers.collect_metrics()
            + [self.workers.local_worker.get_metrics()]
            if self.workers.remote_workers
            else [self.workers.local_worker.get_metrics()]
        )
        rews = [m["episode_reward_mean"] for m in metrics
                if not np.isnan(m["episode_reward_mean"])]
        lens = [m["episode_len_mean"] for m in metrics
                if not np.isnan(m["episode_len_mean"])]
        results.update({
            "episode_reward_mean": float(np.mean(rews)) if rews else np.nan,
            "episode_len_mean": float(np.mean(lens)) if lens else np.nan,
            "episodes_total": int(sum(m["episodes_total"] for m in metrics)),
            "timesteps_total": self._timesteps_total,
        })
        interval = self.config.get("evaluation_interval") or 0
        if interval and self._iteration_count % interval == 0:
            results["evaluation"] = self.evaluate()
        return results

    def evaluate(self) -> Dict[str, Any]:
        """Greedy episodes on a fresh env (``Algorithm.evaluate`` analog)."""
        return self.workers.local_worker.evaluate_episodes(
            int(self.config.get("evaluation_num_episodes", 5))
        )

    def _read_offline(self, min_env_steps: int) -> SampleBatch:
        """Accumulate recorded batches from ``config.input`` to at least
        ``min_env_steps`` transitions (offline-training sampling seam)."""
        parts, total = [], 0
        while total < min_env_steps:
            b = self.reader.next()
            if b.count == 0:
                continue
            parts.append(b)
            total += b.count
        return SampleBatch.concat_samples(parts)

    def training_step(self) -> Dict[str, Any]:
        """Default: sample and do nothing (``algorithm.py:1284`` is
        framework-specific; subclasses override)."""
        batch = self.workers.synchronous_parallel_sample()
        self.workers.sync_filters()
        self._timesteps_total += batch.count
        return {}

    def cleanup(self) -> None:
        self.workers.stop()

    # -- checkpointing (Trainable currency) ----------------------------
    def save_checkpoint(self) -> Dict:
        worker = self.workers.local_worker
        state = {
            "policy_state": worker.policy.get_state(),
            "timesteps_total": self._timesteps_total,
            "config": {k: v for k, v in self.config.items()
                       if isinstance(v, (int, float, str, bool, tuple, list, dict, type(None)))},
        }
        # connector pipelines (running-stat filters etc.) ride checkpoints
        getter = getattr(worker, "get_connector_state", None)
        if getter is not None:
            state["connector_state"] = getter()
        return state

    def load_checkpoint(self, state: Dict) -> None:
        if "policy_state" in state:
            self.workers.local_worker.policy.set_state(state["policy_state"])
        else:  # older checkpoints carried bare weights
            self.workers.local_worker.set_weights(state["weights"])
        if state.get("connector_state") is not None:
            self.workers.local_worker.set_connector_state(
                state["connector_state"])
            self.workers.sync_connectors()
        self._timesteps_total = state.get("timesteps_total", 0)
        self.workers.sync_weights()

    # -- inference ------------------------------------------------------
    def compute_single_action(self, obs, explore: bool = False,
                              episode_start: bool = False) -> int:
        """Greedy (or sampled) action for one observation.

        Stateful connectors (frame stacks) track the caller's episode on
        the shared eval stream: pass ``episode_start=True`` on the first
        observation of each new episode so their state resets with it."""
        worker = self.workers.local_worker
        policy = worker.policy
        if episode_start:
            from ray_tpu.rllib.rollout_worker import EVAL_ENV_ID

            worker.agent_connectors.reset(EVAL_ENV_ID)
        # the same pipeline as sampling (eval stream: frozen statistics)
        obs = worker._prep_obs(obs)[None]
        if explore:
            action, _, _ = policy.compute_actions(obs)
            return int(action[0])
        # greedy through the policy's RLModule forward_inference path
        return int(np.asarray(policy.greedy_action(obs))[0])

    def get_policy(self):
        return self.workers.local_worker.policy


# -- execution ops (rollout_ops/train_ops analogs as free functions) -----

def synchronous_parallel_sample(worker_set: WorkerSet, *, max_env_steps: int) -> SampleBatch:
    """Sample rounds until at least ``max_env_steps`` are collected
    (``execution/rollout_ops.py:21``)."""
    batches = []
    total = 0
    while total < max_env_steps:
        b = worker_set.synchronous_parallel_sample()
        batches.append(b)
        total += b.count
    # remote workers' running-stat filters (MeanStdFilter) fold into the
    # learner's pipelines once per sampling round; no-op without stats
    worker_set.sync_filters()
    return SampleBatch.concat_samples(batches)


def train_one_step(
    policy,
    batch: SampleBatch,
    *,
    num_sgd_iter: int,
    sgd_minibatch_size: int,
    rng: np.random.Generator,
    required_keys: tuple,
) -> Dict[str, float]:
    """Minibatch SGD epochs over one train batch
    (``execution/train_ops.py:26``)."""
    import time

    from ray_tpu._private import events

    t_wall = time.perf_counter()
    if hasattr(policy, "train_on_batch"):
        # server-resident learner (policy_server.py): the batch crosses
        # the wire once and every SGD update runs device-side — per-
        # minibatch round trips would dominate on a remote-attached chip
        out = policy.train_on_batch(
            batch, num_sgd_iter=num_sgd_iter,
            sgd_minibatch_size=sgd_minibatch_size,
            required_keys=required_keys, seed=int(rng.integers(1 << 31)))
        events.emit("rllib", "learner train", entity_id="learner",
                    span_dur=time.perf_counter() - t_wall,
                    env_steps=batch.count, server_side=True)
        return out
    metrics: Dict[str, float] = {}
    count = 0
    mb_size = min(sgd_minibatch_size, batch.count)
    for _ in range(num_sgd_iter):
        for mb in batch.minibatches(mb_size, rng):
            out = policy.learn_on_minibatch(
                {k: mb[k] for k in required_keys}
            )
            for k, v in out.items():
                metrics[k] = metrics.get(k, 0.0) + v
            count += 1
    events.emit("rllib", "learner train", entity_id="learner",
                span_dur=time.perf_counter() - t_wall,
                env_steps=batch.count, sgd_minibatches=count)
    return {k: v / max(count, 1) for k, v in metrics.items()}
