"""WorkerSet: local learner-side worker + remote rollout actors.

Analog of ``/root/reference/rllib/evaluation/worker_set.py:77`` plus the
execution ops it feeds (``execution/rollout_ops.py:21``
``synchronous_parallel_sample``): remote workers sample in parallel as
actors; weight sync broadcasts one ``put`` object to all of them.
"""

from __future__ import annotations

from typing import Any, Dict, List

import ray_tpu
from ray_tpu.rllib.rollout_worker import RolloutWorker
from ray_tpu.rllib.sample_batch import SampleBatch


def _has_stats(blob) -> bool:
    """True when a stat-states/deltas blob carries anything: per-policy
    dicts (multi-agent) hold positional lists whose stateless-connector
    entries are None."""
    if isinstance(blob, dict):
        return any(_has_stats(v) for v in blob.values())
    if isinstance(blob, (list, tuple)):
        return any(x is not None for x in blob)
    return blob is not None


class WorkerSet:
    def __init__(self, config: Dict[str, Any]):
        self.config = config
        n = config.get("num_rollout_workers", 0)
        worker_cls = config.get("_worker_class") or RolloutWorker
        # Local worker: holds the learner policy; also samples when n == 0.
        self.local_worker = worker_cls(config, worker_index=0)
        RemoteWorker = ray_tpu.remote(worker_cls)
        # rollout workers restart on crash and retry the in-flight sample
        # (the reference recreates failed rollout workers the same way);
        # sync_weights re-broadcasts the policy each training step anyway
        opts = {"num_cpus": config.get("num_cpus_per_worker", 1),
                "max_restarts": 2, "max_task_retries": 2}
        self.remote_workers = [
            RemoteWorker.options(**opts).remote(config, worker_index=i + 1)
            for i in range(n)
        ]

    # ------------------------------------------------------------------
    def sync_weights(self) -> None:
        """Broadcast local-worker weights to all remotes (one shared object,
        not one copy per worker)."""
        if not self.remote_workers:
            return
        ref = ray_tpu.put(self.local_worker.get_weights())
        ray_tpu.get(
            [w.set_weights.remote(ref) for w in self.remote_workers], timeout=120
        )

    def sync_connectors(self) -> None:
        """Broadcast the local worker's connector-pipeline state (e.g. a
        restored running-stat filter) to all remotes; a checkpoint restore
        must not leave remote workers normalizing with fresh statistics."""
        getter = getattr(self.local_worker, "get_connector_state", None)
        if getter is None or not self.remote_workers:
            return
        state = getter()
        ray_tpu.get(
            [w.set_connector_state.remote(state) for w in self.remote_workers],
            timeout=120,
        )

    def sync_filters(self) -> None:
        """Fold remote workers' running-stat deltas (Welford buffers) into
        the local worker's pipelines and broadcast the merged statistics
        back (``FilterManager.synchronize`` analog).  Stats only — per-env
        episode state (frame stacks) is never touched.  Without this the
        local worker of a ``MeanStdFilter`` run with remote workers keeps
        n=0 statistics, so evaluation, ``compute_single_action``, and
        checkpoints would ride fresh filters while training normalized.
        Skipped entirely when the pipelines carry no statistics."""
        if not self.remote_workers:
            return
        getter = getattr(self.local_worker, "get_connector_stat_states", None)
        if getter is None or not _has_stats(getter()):
            return
        deltas = ray_tpu.get(
            [w.pop_connector_stat_deltas.remote() for w in self.remote_workers],
            timeout=120,
        )
        for d in deltas:
            if _has_stats(d):
                self.local_worker.apply_connector_stat_deltas(d)
        merged = self.local_worker.get_connector_stat_states()
        ray_tpu.get(
            [w.set_connector_stat_states.remote(merged)
             for w in self.remote_workers],
            timeout=120,
        )

    def sync_global_vars(self, timesteps_total: int) -> None:
        """Broadcast the global env-step count so per-worker exploration
        schedules (e.g. epsilon anneal) track global progress instead of
        each worker's local step count (reference: WorkerSet.sync_weights
        global_vars propagation)."""
        self.local_worker.set_global_vars(timesteps_total)
        if self.remote_workers:
            ray_tpu.get(
                [w.set_global_vars.remote(timesteps_total) for w in self.remote_workers],
                timeout=120,
            )

    def synchronous_parallel_sample(self) -> SampleBatch:
        """One sampling round across all workers
        (``execution/rollout_ops.py:21`` analog)."""
        if not self.remote_workers:
            return self.local_worker.sample()
        batches = ray_tpu.get(
            [w.sample.remote() for w in self.remote_workers], timeout=600
        )
        # MultiAgentBatch and SampleBatch both expose concat_samples
        return type(batches[0]).concat_samples(batches)

    def collect_metrics(self) -> List[Dict[str, Any]]:
        if not self.remote_workers:
            return [self.local_worker.get_metrics()]
        # generous: on a 1-core host several workers cold-boot jax
        # SERIALLY (~30s each), and metrics calls queue behind any
        # in-flight async sample (APPO keeps one outstanding per worker)
        return ray_tpu.get(
            [w.get_metrics.remote() for w in self.remote_workers], timeout=300
        )

    def stop(self) -> None:
        for w in self.remote_workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
