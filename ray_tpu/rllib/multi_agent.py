"""Multi-agent RL: MultiAgentEnv + per-policy mapping + multi-agent PPO.

Reference counterparts: ``rllib/env/multi_agent_env.py:30`` (the dict-keyed
env API with ``"__all__"`` termination), per-policy training via the
``multiagent`` config (``policies`` + ``policy_mapping_fn``), and
``MultiAgentBatch``.  Each policy is an independent :class:`JaxPolicy`
(shared-policy setups map several agents onto one id); sampling groups
observations per policy so each tick is one batched forward per policy.
"""

from __future__ import annotations

import copy
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, train_one_step
from ray_tpu.rllib.connectors import (
    ActionConnectorPipeline,
    AgentConnectorPipeline,
    ConnectorContext,
    DiscreteAction,
    NormalizeObs,
    build_pipeline,
    default_agent_connectors,
)
from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.postprocessing import compute_gae
from ray_tpu.rllib.ppo import PPOConfig
from ray_tpu.rllib.sample_batch import SampleBatch


class MultiAgentEnv:
    """Base class for dict-keyed multi-agent environments
    (``multi_agent_env.py:30``).

    - ``reset() -> (obs_dict, info_dict)``
    - ``step(action_dict) -> (obs, rewards, terminateds, truncateds,
      infos)`` — all dicts keyed by agent id; ``terminateds``/``truncateds``
      carry the special ``"__all__"`` key ending the episode for everyone.
    - ``observation_space(agent_id)`` / ``action_space(agent_id)`` describe
      per-agent spaces.
    """

    agents: List[Any] = []

    def reset(self, *, seed: Optional[int] = None, options=None):
        raise NotImplementedError

    def step(self, action_dict: Dict):
        raise NotImplementedError

    def observation_space(self, agent_id):
        raise NotImplementedError

    def action_space(self, agent_id):
        raise NotImplementedError


class MultiAgentBatch:
    """Per-policy SampleBatches (``policy/sample_batch.py`` MultiAgentBatch
    analog).  ``count`` is SUMMED AGENT steps (a 2-agent tick counts 2) —
    size train_batch_size in agent steps, unlike the reference's
    env-step count."""

    def __init__(self, policy_batches: Dict[str, SampleBatch]):
        self.policy_batches = policy_batches

    @property
    def count(self) -> int:
        return sum(b.count for b in self.policy_batches.values())

    @staticmethod
    def concat_samples(batches: List["MultiAgentBatch"]) -> "MultiAgentBatch":
        merged: Dict[str, List[SampleBatch]] = {}
        for mb in batches:
            for pid, b in mb.policy_batches.items():
                merged.setdefault(pid, []).append(b)
        return MultiAgentBatch({
            pid: SampleBatch.concat_samples(parts)
            for pid, parts in merged.items()
        })


class _AgentTrail:
    """Per-agent column buffers within the running episode."""

    __slots__ = ("cols", "last_obs")

    def __init__(self, keys):
        self.cols: Dict[str, List] = {k: [] for k in keys}
        self.last_obs = None


class MultiAgentRolloutWorker:
    """Steps ONE MultiAgentEnv; groups per-policy forwards; GAE per agent
    trail (the multi-agent half of ``rollout_worker.py:153``)."""

    _KEYS = (
        SampleBatch.OBS, SampleBatch.ACTIONS, SampleBatch.REWARDS,
        SampleBatch.TERMINATEDS, SampleBatch.TRUNCATEDS, SampleBatch.EPS_ID,
        SampleBatch.ACTION_LOGP, SampleBatch.VF_PREDS,
    )

    def __init__(self, config: Dict[str, Any], worker_index: int = 0):
        self.config = config
        self.worker_index = worker_index
        ma = config["multiagent"]
        env_creator: Callable = config["env_creator"]
        self.env: MultiAgentEnv = env_creator(config.get("env_config", {}))
        self.mapping_fn: Callable = ma["policy_mapping_fn"]
        seed = int(config.get("seed") or 0) + worker_index

        loss_factory = config.get("_loss_factory")
        module_factory = config.get("_rl_module_factory")
        self.policies: Dict[str, JaxPolicy] = {}
        # per-policy connector pipelines (agents map onto their policy's
        # pipelines; episode state inside a pipeline is keyed by agent id,
        # so two agents sharing a policy never share a frame stack).
        # A config spec applies to every policy; None installs the same
        # defaults as the single-agent worker.
        self.agent_connectors: Dict[str, AgentConnectorPipeline] = {}
        self.action_connectors: Dict[str, ActionConnectorPipeline] = {}
        agent_spec = config.get("agent_connectors")
        action_spec = config.get("action_connectors")
        if not self.env.agents:
            raise ValueError(
                "MultiAgentEnv must list its agent ids in `.agents` at "
                "construction time (used to probe per-policy spaces)")
        for i, pid in enumerate(ma["policies"]):
            # probe spaces through any agent mapped to this policy
            agent = next((a for a in self.env.agents
                          if self.mapping_fn(a) == pid), None)
            if agent is None:
                raise ValueError(
                    f"policy {pid!r} has no agent mapped to it "
                    f"(agents: {self.env.agents}; check policy_mapping_fn)")
            obs_space = self.env.observation_space(agent)
            act_space = self.env.action_space(agent)
            obs_shape = tuple(obs_space.shape)
            ctx = ConnectorContext(
                obs_shape=obs_shape, obs_dim=int(np.prod(obs_shape)),
                num_actions=int(act_space.n), discrete=True, config=config)
            conv = len(obs_shape) == 3
            # per-policy deepcopy: a spec may carry connector INSTANCES,
            # and stateful ones (NormalizeObs) must not be shared across
            # policies with independent obs streams (or shapes)
            pipe = build_pipeline(
                AgentConnectorPipeline, ctx,
                copy.deepcopy(agent_spec)
                if isinstance(agent_spec, (list, tuple)) else agent_spec)
            if agent_spec is None:
                for c in default_agent_connectors(ctx, conv):
                    pipe.append(c)
                if config.get("observation_filter") == "MeanStdFilter":
                    # same knob as the single-agent worker
                    pipe.append(NormalizeObs())
            else:
                # an explicit pipeline may reshape the policy's input
                # (frame stacking); size the policy off a zeros probe
                probe = pipe(np.zeros(obs_shape, np.float32),
                             env_id="__probe__", training=False)
                pipe.reset("__probe__")
                ctx.obs_shape = tuple(probe.shape)
                ctx.obs_dim = int(np.prod(probe.shape))
            self.agent_connectors[pid] = pipe
            apipe = build_pipeline(
                ActionConnectorPipeline, ctx,
                copy.deepcopy(action_spec)
                if isinstance(action_spec, (list, tuple)) else action_spec)
            if action_spec is None:
                apipe.append(DiscreteAction())
            self.action_connectors[pid] = apipe
            self.policies[pid] = JaxPolicy(
                ctx.obs_dim,
                ctx.num_actions,
                lr=config.get("lr", 5e-4),
                hiddens=tuple(config.get("fcnet_hiddens", (64, 64))),
                seed=seed * 131 + i,
                loss_fn=loss_factory(config) if loss_factory else None,
                grad_clip=config.get("grad_clip", 0.5),
                obs_shape=ctx.obs_shape if len(ctx.obs_shape) == 3 else None,
                **({"module": module_factory(ctx)} if module_factory else {}),
            )
        self.gamma = config.get("gamma", 0.99)
        self.lambda_ = config.get("lambda_", 0.95)
        self.fragment_length = config.get("rollout_fragment_length", 200)

        self._obs, _ = self.env.reset(seed=seed)
        self._trails: Dict[Any, _AgentTrail] = {}
        # fragment-boundary obs already transformed with real episode
        # state; the next fragment's first tick reuses it (the
        # single-agent worker's ``prepped`` cache analog)
        self._boundary_prepped: Dict[Any, np.ndarray] = {}
        self._eps_id = worker_index * 1_000_000
        self._episode_reward = 0.0
        self._episode_len = 0
        self._episode_rewards: deque = deque(maxlen=100)
        self._episode_lengths: deque = deque(maxlen=100)
        self._episodes_total = 0
        self._total_steps = 0

    # -- helpers --------------------------------------------------------
    def _prep_for_policy(self, pid: str, obs) -> np.ndarray:
        """Single-obs inference path (``compute_single_action``): the
        policy's agent pipeline on a dedicated stream, statistics
        frozen."""
        return self.agent_connectors[pid](
            obs, env_id="__inference__", training=False)

    def _prep(self, agent, obs, training: bool = True) -> np.ndarray:
        """One obs through the agent's policy pipeline, episode state
        keyed by agent id."""
        return self.agent_connectors[self.mapping_fn(agent)](
            obs, env_id=agent, training=training)

    def _trail(self, agent) -> _AgentTrail:
        t = self._trails.get(agent)
        if t is None:
            t = self._trails[agent] = _AgentTrail(self._KEYS)
        return t

    # -- sampling -------------------------------------------------------
    def sample(self) -> MultiAgentBatch:
        segments: Dict[str, List[SampleBatch]] = {pid: [] for pid in self.policies}

        def close_trail(agent, trail, bootstrap: float):
            if not trail.cols[SampleBatch.OBS]:
                return
            pid = self.mapping_fn(agent)
            seg = SampleBatch({k: np.asarray(v) for k, v in trail.cols.items()})
            seg = compute_gae(seg, bootstrap, self.gamma, self.lambda_)
            segments[pid].append(seg)
            for v in trail.cols.values():
                v.clear()

        for _ in range(self.fragment_length):
            # group live agents by policy -> one batched forward per policy
            by_pid: Dict[str, List[Any]] = {}
            prepped: Dict[Any, np.ndarray] = {}
            for agent, obs in self._obs.items():
                by_pid.setdefault(self.mapping_fn(agent), []).append(agent)
                pre = self._boundary_prepped.pop(agent, None)
                prepped[agent] = self._prep(agent, obs) if pre is None else pre
            actions: Dict[Any, Any] = {}
            logps: Dict[Any, float] = {}
            vfs: Dict[Any, float] = {}
            for pid, agents in by_pid.items():
                batch = np.stack([prepped[a] for a in agents])
                acts, lps, vs = self.policies[pid].compute_actions(batch)
                for j, a in enumerate(agents):
                    actions[a] = acts[j]
                    logps[a] = lps[j]
                    vfs[a] = vs[j]
            prev_obs = self._obs
            obs, rewards, terms, truncs, _ = self.env.step({
                a: self.action_connectors[self.mapping_fn(a)](actions[a])
                for a in actions})
            all_term = bool(terms.get("__all__"))
            all_done = all_term or bool(truncs.get("__all__"))
            for agent in prev_obs:
                t = self._trail(agent)
                t.cols[SampleBatch.OBS].append(prepped[agent])
                t.cols[SampleBatch.ACTIONS].append(actions[agent])
                t.cols[SampleBatch.REWARDS].append(
                    np.float32(rewards.get(agent, 0.0)))
                # termination (no bootstrap) vs truncation (bootstrap
                # v(s_T)) — same split as the single-agent worker
                term = bool(terms.get(agent, False)) or all_term
                trunc = bool(truncs.get(agent, False)) or (all_done and not all_term)
                t.cols[SampleBatch.TERMINATEDS].append(term)
                t.cols[SampleBatch.TRUNCATEDS].append(trunc)
                t.cols[SampleBatch.EPS_ID].append(self._eps_id)
                t.cols[SampleBatch.ACTION_LOGP].append(np.float32(logps[agent]))
                t.cols[SampleBatch.VF_PREDS].append(np.float32(vfs[agent]))
                t.last_obs = obs.get(agent, prev_obs[agent])
                self._episode_reward += float(rewards.get(agent, 0.0))
                self._total_steps += 1
                if term or trunc:
                    bootstrap = 0.0 if term else self._bootstrap(agent, t.last_obs)
                    close_trail(agent, t, bootstrap)
                    # this agent's episode ended: fresh connector episode
                    # state (frame stacks) for its next life
                    self.agent_connectors[self.mapping_fn(agent)].reset(agent)
            self._episode_len += 1
            if all_done:
                for agent, t in self._trails.items():
                    close_trail(agent, t, 0.0 if all_term
                                else self._bootstrap(agent, t.last_obs))
                self._episode_rewards.append(self._episode_reward)
                self._episode_lengths.append(self._episode_len)
                self._episodes_total += 1
                self._episode_reward = 0.0
                self._episode_len = 0
                self._eps_id += 1
                self._obs, _ = self.env.reset()
                self._boundary_prepped.clear()
                for pipe in self.agent_connectors.values():
                    pipe.reset()
            else:
                self._obs = obs
        # fragment boundary: bootstrap open trails with v(current obs).
        # A live agent's boundary obs goes through its pipeline ONCE with
        # real episode state and is cached for the next fragment's first
        # tick — a training=False peek would still advance frame-stack
        # state, so the next fragment's _prep of the same obs would
        # duplicate the frame for the rest of the episode.
        for agent, t in self._trails.items():
            if t.cols[SampleBatch.OBS]:
                if agent in self._obs:
                    pre = self._prep(agent, self._obs[agent])
                    self._boundary_prepped[agent] = pre
                    pid = self.mapping_fn(agent)
                    boot = float(self.policies[pid].value(pre[None])[0])
                else:
                    # agent absent from the boundary obs dict: there is no
                    # new obs to transform, and re-pushing last_obs would
                    # duplicate a frame already in its connector episode
                    # state — bootstrap with the trail's own v(s_T) (the
                    # mid-fragment truncation convention in compute_gae)
                    boot = float(t.cols[SampleBatch.VF_PREDS][-1])
                close_trail(agent, t, boot)
        return MultiAgentBatch({
            pid: SampleBatch.concat_samples(parts)
            for pid, parts in segments.items() if parts
        })

    def _bootstrap(self, agent, obs) -> float:
        # training=False: the bootstrap peek must not double-count the
        # obs in running statistics (the sample loop already saw it or
        # will see it next fragment)
        pid = self.mapping_fn(agent)
        return float(self.policies[pid].value(
            self._prep(agent, obs, training=False)[None])[0])

    # -- WorkerSet surface ---------------------------------------------
    def get_metrics(self) -> Dict[str, Any]:
        rewards = list(self._episode_rewards)
        return {
            "episode_reward_mean": float(np.mean(rewards)) if rewards else np.nan,
            "episode_len_mean": (
                float(np.mean(self._episode_lengths))
                if self._episode_lengths else np.nan),
            "episodes_total": self._episodes_total,
            "worker_steps": self._total_steps,
        }

    def get_connector_state(self) -> Dict[str, Any]:
        return {
            "agent": {pid: p.to_state()
                      for pid, p in self.agent_connectors.items()},
            "action": {pid: p.to_state()
                       for pid, p in self.action_connectors.items()},
        }

    def set_connector_state(self, state: Dict[str, Any]) -> bool:
        # cached boundary transforms came from the replaced pipelines
        self._boundary_prepped.clear()
        for pid, s in state.get("agent", {}).items():
            self.agent_connectors[pid].set_state(s)
        for pid, s in state.get("action", {}).items():
            self.action_connectors[pid].set_state(s)
        return True

    # -- distributed filter sync (stats only; episode state untouched) --
    def pop_connector_stat_deltas(self):
        return {pid: p.pop_stat_deltas()
                for pid, p in self.agent_connectors.items()}

    def apply_connector_stat_deltas(self, deltas) -> bool:
        for pid, d in (deltas or {}).items():
            self.agent_connectors[pid].apply_stat_deltas(d)
        return True

    def get_connector_stat_states(self):
        return {pid: p.get_stat_states()
                for pid, p in self.agent_connectors.items()}

    def set_connector_stat_states(self, states) -> bool:
        for pid, s in (states or {}).items():
            self.agent_connectors[pid].set_stat_states(s)
        return True

    def get_weights(self) -> Dict[str, Any]:
        return {pid: p.get_weights() for pid, p in self.policies.items()}

    def set_weights(self, weights: Dict[str, Any]) -> bool:
        for pid, w in weights.items():
            self.policies[pid].set_weights(w)
        return True

    def set_global_vars(self, timesteps_total: int) -> bool:
        return True

    def evaluate_episodes(self, num_episodes: int,
                          max_steps_per_episode: int = 10_000) -> Dict[str, Any]:
        rewards = []
        for ep in range(num_episodes):
            obs, _ = self.env.reset(seed=977 + ep)
            # eval episodes must not inherit frame-stack residue from
            # training (ep 0) or the previous eval episode (ep 1..);
            # training episode state is rebuilt by the full reset below
            for pipe in self.agent_connectors.values():
                pipe.reset()
            total, steps = 0.0, 0
            while steps < max_steps_per_episode:
                acts = {}
                for agent, o in obs.items():
                    pid = self.mapping_fn(agent)
                    acts[agent] = self.action_connectors[pid](
                        self.policies[pid].greedy_action(
                            self._prep(agent, o, training=False)[None])[0])
                obs, rs, terms, truncs, _ = self.env.step(acts)
                total += float(sum(rs.values()))
                steps += 1
                if terms.get("__all__") or truncs.get("__all__"):
                    break
            rewards.append(total)
        # the shared env was disturbed: fresh training episode state
        self._obs, _ = self.env.reset()
        self._trails.clear()
        self._boundary_prepped.clear()
        for pipe in self.agent_connectors.values():
            pipe.reset()
        self._episode_reward = 0.0
        self._episode_len = 0
        return {"episode_reward_mean": float(np.mean(rewards)),
                "episodes_this_eval": num_episodes}

    def apply(self, fn_blob: bytes):
        import cloudpickle

        return cloudpickle.loads(fn_blob)(self)


class MultiAgentPPOConfig(PPOConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = MultiAgentPPO
        self._config.update(
            multiagent={"policies": {}, "policy_mapping_fn": None},
            _worker_class=MultiAgentRolloutWorker,
        )

    def multi_agent(self, *, policies, policy_mapping_fn) -> "MultiAgentPPOConfig":
        self._config["multiagent"] = {
            "policies": dict.fromkeys(policies),
            "policy_mapping_fn": policy_mapping_fn,
        }
        return self


class MultiAgentPPO(Algorithm):
    """PPO over per-policy batches: each policy runs clipped-surrogate SGD
    on its own agents' trajectories (the reference's multi-agent
    ``training_step`` over ``MultiAgentBatch``)."""

    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        self._sgd_rng = np.random.default_rng(self.config.get("seed", 0))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        self.workers.sync_weights()
        batches: List[MultiAgentBatch] = []
        total = 0
        while total < cfg["train_batch_size"]:
            b = self.workers.synchronous_parallel_sample()
            batches.append(b)
            total += b.count
        batch = MultiAgentBatch.concat_samples(batches)
        # remote workers' running-stat filters fold into the learner's
        # per-policy pipelines; no-op without stats
        self.workers.sync_filters()
        self._timesteps_total += batch.count
        learner: Dict[str, Dict[str, float]] = {}
        for pid, pb in batch.policy_batches.items():
            learner[pid] = train_one_step(
                self.workers.local_worker.policies[pid],
                pb,
                num_sgd_iter=cfg["num_sgd_iter"],
                sgd_minibatch_size=cfg["sgd_minibatch_size"],
                rng=self._sgd_rng,
                required_keys=(
                    SampleBatch.OBS, SampleBatch.ACTIONS,
                    SampleBatch.ACTION_LOGP, SampleBatch.ADVANTAGES,
                    SampleBatch.VALUE_TARGETS,
                ),
            )
        return {"info": {"learner": learner}}

    def save_checkpoint(self) -> Dict:
        worker = self.workers.local_worker
        return {
            "policy_state": {
                pid: p.get_state() for pid, p in worker.policies.items()
            },
            "connector_state": worker.get_connector_state(),
            "timesteps_total": self._timesteps_total,
        }

    def load_checkpoint(self, state: Dict) -> None:
        for pid, s in state["policy_state"].items():
            self.workers.local_worker.policies[pid].set_state(s)
        if state.get("connector_state") is not None:
            self.workers.local_worker.set_connector_state(
                state["connector_state"])
            self.workers.sync_connectors()
        self._timesteps_total = state.get("timesteps_total", 0)
        self.workers.sync_weights()

    def get_policy(self, policy_id: Optional[str] = None):
        policies = self.workers.local_worker.policies
        if policy_id is None:
            if len(policies) != 1:
                raise ValueError(
                    f"multiple policies {sorted(policies)}; pass policy_id")
            return next(iter(policies.values()))
        return policies[policy_id]

    def compute_single_action(self, obs, policy_id: Optional[str] = None,
                              explore: bool = False,
                              episode_start: bool = False) -> int:
        worker = self.workers.local_worker
        policies = worker.policies
        if policy_id is None and len(policies) == 1:
            policy_id = next(iter(policies))
        policy = self.get_policy(policy_id)
        if episode_start:
            # stateful connectors (frame stacks) track the caller's
            # episode on the shared inference stream — same contract as
            # the single-agent Algorithm.compute_single_action
            worker.agent_connectors[policy_id].reset("__inference__")
        # the worker's prep, so inference matches sampling exactly
        o = worker._prep_for_policy(policy_id, obs)
        if explore:
            action, _, _ = policy.compute_actions(o[None])
            return int(action[0])
        return int(policy.greedy_action(o[None])[0])


# set after the class exists (MultiAgentPPOConfig references MultiAgentPPO)
MultiAgentPPO._default_config = MultiAgentPPOConfig().to_dict()
