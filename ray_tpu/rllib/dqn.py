"""DQN: off-policy Q-learning with replay + target network, in jax.

Analog of ``/root/reference/rllib/algorithms/dqn/dqn.py`` (training_step:
sample -> store to replay -> TD updates from replay -> periodic target
sync) with the torch loss of ``dqn_torch_policy.py`` expressed as a pure
jitted function.  The Q-network reuses the actor-critic MLP's logits head
as Q-values; exploration is epsilon-greedy with a linear anneal.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig, synchronous_parallel_sample
from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.rl_module import Columns
from ray_tpu.rllib.sample_batch import SampleBatch


def make_dqn_loss():
    """Huber TD loss on Q(s, a) vs precomputed targets (the target-network
    max lives outside the loss, computed with the frozen params).  The
    logits head of the module's forward doubles as the Q-value head."""

    def loss(module, params, batch):
        q_all = module.forward_train(
            params, batch[SampleBatch.OBS])[Columns.ACTION_DIST_INPUTS]
        actions = batch[SampleBatch.ACTIONS].astype(jnp.int32)
        q = jnp.take_along_axis(q_all, actions[:, None], axis=-1)[:, 0]
        td = q - batch[SampleBatch.VALUE_TARGETS]
        # Huber (delta=1)
        abs_td = jnp.abs(td)
        loss_val = jnp.mean(jnp.where(abs_td <= 1.0, 0.5 * td ** 2, abs_td - 0.5))
        return loss_val, {"mean_q": jnp.mean(q), "mean_td_error": jnp.mean(abs_td)}

    return loss


def _dqn_loss_factory(config: Dict[str, Any]):
    return make_dqn_loss()


def _dqn_policy_kwargs(config: Dict[str, Any]) -> Dict[str, Any]:
    """Exploration schedule from the (possibly .training()-overridden)
    algorithm config to the per-worker policy constructors."""
    return {
        "epsilon_timesteps": config["epsilon_timesteps"],
        "final_epsilon": config["final_epsilon"],
    }


class DQNPolicy(JaxPolicy):
    """Epsilon-greedy acting + a frozen target network for TD targets."""

    def __init__(self, *args, **kwargs):
        self._epsilon_timesteps = kwargs.pop("epsilon_timesteps", 10_000)
        self._final_epsilon = kwargs.pop("final_epsilon", 0.02)
        super().__init__(*args, **kwargs)
        self.target_params = jax.tree_util.tree_map(jnp.asarray, self.params)
        self._steps = 0
        self._np_rng = np.random.default_rng(kwargs.get("seed", 0) or 0)

        module = self.module

        @jax.jit
        def _td_targets(target_params, next_obs, rewards, dones, gamma):
            q_next = module.forward_train(
                target_params, next_obs)[Columns.ACTION_DIST_INPUTS]
            return rewards + gamma * (1.0 - dones) * q_next.max(axis=-1)

        self._td_targets_jit = _td_targets

        @jax.jit
        def _q(params, obs):
            return module.forward_train(params, obs)[Columns.ACTION_DIST_INPUTS]

        self._q_jit = _q

    @property
    def epsilon(self) -> float:
        frac = min(1.0, self._steps / max(1, self._epsilon_timesteps))
        return 1.0 + frac * (self._final_epsilon - 1.0)

    def on_global_timestep(self, timesteps_total: int) -> None:
        """Anneal from GLOBAL sampled steps — with N workers each stepping
        locally, per-policy counts would decay the schedule N× too slowly."""
        self._steps = int(timesteps_total)

    def compute_actions(self, obs: np.ndarray):
        q = np.asarray(self._q_jit(self.params, jnp.asarray(obs)))
        greedy = np.argmax(q, axis=-1)
        explore = self._np_rng.random(len(greedy)) < self.epsilon
        random_a = self._np_rng.integers(0, self.num_actions, len(greedy))
        actions = np.where(explore, random_a, greedy)
        self._steps += len(greedy)
        # logp/vf columns keep the RolloutWorker contract; DQN ignores them
        logp = np.zeros(len(greedy), np.float32)
        vf = q.max(axis=-1).astype(np.float32)
        return actions.astype(np.int64), logp, vf

    def value(self, obs: np.ndarray) -> np.ndarray:
        q = np.asarray(self._q_jit(self.params, jnp.asarray(obs)))
        return q.max(axis=-1)

    def compute_td_targets(self, batch: SampleBatch, gamma: float) -> np.ndarray:
        dones = batch[SampleBatch.TERMINATEDS].astype(np.float32)
        return np.asarray(self._td_targets_jit(
            self.target_params,
            jnp.asarray(batch[SampleBatch.NEXT_OBS]),
            jnp.asarray(batch[SampleBatch.REWARDS]),
            jnp.asarray(dones),
            gamma,
        ))

    def update_target(self) -> None:
        self.target_params = jax.tree_util.tree_map(jnp.asarray, self.params)


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=DQN)
        self._config.update(
            _loss_factory=_dqn_loss_factory,
            _policy_class=DQNPolicy,
            _policy_kwargs_factory=_dqn_policy_kwargs,
            _store_next_obs=True,
            lr=5e-4,
            gamma=0.99,
            train_batch_size=32,
            replay_buffer_capacity=50_000,
            learning_starts=1000,
            target_network_update_freq=500,
            epsilon_timesteps=10_000,
            final_epsilon=0.02,
            timesteps_per_iteration=1000,
            updates_per_iteration=250,
            grad_clip=10.0,
        )


class DQN(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        self.replay = ReplayBuffer(
            self.config["replay_buffer_capacity"],
            seed=self.config.get("seed") or 0,
        )
        self._since_target_sync = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        if self.reader is not None:
            # offline training: recorded transitions feed the replay buffer
            # (rllib/offline input path); no env interaction at all
            batch = self._read_offline(cfg["timesteps_per_iteration"])
        else:
            self.workers.sync_weights()
            self.workers.sync_global_vars(self._timesteps_total)
            batch = synchronous_parallel_sample(
                self.workers, max_env_steps=cfg["timesteps_per_iteration"]
            )
        self._timesteps_total += batch.count
        self.replay.add_batch(batch)

        policy: DQNPolicy = self.workers.local_worker.policy
        learner_metrics: Dict[str, Any] = {}
        if len(self.replay) >= cfg["learning_starts"]:
            for _ in range(cfg["updates_per_iteration"]):
                mb = self.replay.sample(cfg["train_batch_size"])
                mb[SampleBatch.VALUE_TARGETS] = policy.compute_td_targets(
                    mb, cfg["gamma"]
                )
                learner_metrics = policy.learn_on_minibatch({
                    SampleBatch.OBS: mb[SampleBatch.OBS],
                    SampleBatch.ACTIONS: mb[SampleBatch.ACTIONS],
                    SampleBatch.VALUE_TARGETS: mb[SampleBatch.VALUE_TARGETS],
                })
                self._since_target_sync += 1
                if self._since_target_sync >= cfg["target_network_update_freq"]:
                    policy.update_target()
                    self._since_target_sync = 0
        # sync_global_vars pins every acting policy to this same schedule
        frac = min(1.0, self._timesteps_total / max(1, cfg["epsilon_timesteps"]))
        learner_metrics["epsilon"] = 1.0 + frac * (cfg["final_epsilon"] - 1.0)
        learner_metrics["replay_size"] = len(self.replay)
        return {"info": {"learner": learner_metrics}}


DQN._default_config = DQNConfig().to_dict()
