"""RolloutWorker: env stepping + trajectory postprocessing.

Analog of ``/root/reference/rllib/evaluation/rollout_worker.py:153`` with
the vector-env stepping of ``env_runner_v2.py:198``: owns ``num_envs``
env instances stepped in lockstep (one batched policy forward per tick),
collects fixed-size sample fragments, postprocesses each episode segment
at its boundary (GAE for on-policy learners; raw transitions for
replay-based ones), and exposes get/set_weights for learner sync.  Runs
inline (local worker) or as an actor (``num_rollout_workers > 0``).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.postprocessing import compute_gae
from ray_tpu.rllib.sample_batch import SampleBatch


def _default_env_creator(env_name: str):
    import gymnasium as gym

    return gym.make(env_name)


class _EnvState:
    """Per-env rollout bookkeeping (column buffers + episode stats)."""

    __slots__ = ("env", "obs", "cols", "episode_reward", "episode_len", "eps_id")

    def __init__(self, env, obs, keys, eps_id):
        self.env = env
        self.obs = obs
        self.cols: Dict[str, List] = {k: [] for k in keys}
        self.episode_reward = 0.0
        self.episode_len = 0
        self.eps_id = eps_id


class RolloutWorker:
    def __init__(self, config: Dict[str, Any], worker_index: int = 0):
        self.config = config
        self.worker_index = worker_index
        env_creator: Optional[Callable] = config.get("env_creator")
        self._make_env = (
            (lambda: env_creator(config.get("env_config", {})))
            if env_creator is not None
            else (lambda: _default_env_creator(config["env"]))
        )
        self.num_envs = max(1, int(config.get("num_envs_per_worker", 1)))
        probe_env = self._make_env()
        self._obs_shape = tuple(probe_env.observation_space.shape)
        obs_dim = int(np.prod(probe_env.observation_space.shape))
        space = probe_env.action_space
        self._discrete = hasattr(space, "n")
        if self._discrete:
            num_actions = int(space.n)
            self._action_low = self._action_high = None
        else:
            num_actions = int(np.prod(space.shape))
            self._action_low = np.asarray(space.low, np.float32)
            self._action_high = np.asarray(space.high, np.float32)
        seed = int(config.get("seed") or 0) + worker_index

        from ray_tpu.rllib.policy import JaxPolicy

        loss_factory = config.get("_loss_factory")
        policy_cls = config.get("_policy_class") or JaxPolicy
        # algorithm-specific policy constructor args travel as one dict
        # (or a factory over the live config) so this worker stays
        # algorithm-agnostic
        pk_factory = config.get("_policy_kwargs_factory")
        extra = (dict(pk_factory(config)) if pk_factory
                 else dict(config.get("_policy_kwargs") or {}))
        if len(self._obs_shape) == 3 and policy_cls is JaxPolicy:
            # image observations -> the catalog's CNN (catalog.py:195
            # dispatch); subclass policies keep their own model choices
            extra.setdefault("obs_shape", self._obs_shape)
        self.policy = policy_cls(
            obs_dim,
            num_actions,
            lr=config.get("lr", 5e-4),
            hiddens=tuple(config.get("fcnet_hiddens", (64, 64))),
            seed=seed,  # per-worker: decorrelates action sampling rng
            loss_fn=loss_factory(config) if loss_factory else None,
            grad_clip=config.get("grad_clip", 0.5),
            **extra,
        )
        # obs stay [H, W, C] only when the BUILT policy actually carries a
        # conv net — a flat-MLP policy (DQN/SAC on image envs) gets
        # flattened observations instead of a shape crash
        p = getattr(self.policy, "params", None)
        self._conv = isinstance(p, dict) and "conv" in p
        self._store_next_obs = bool(config.get("_store_next_obs"))
        # on-policy learners want GAE + behavior logp/vf columns; replay
        # learners want raw transitions; IMPALA wants transitions AND the
        # behavior policy's logp for V-trace importance ratios
        self._postprocess_gae = bool(
            config.get("_postprocess_gae", not self._store_next_obs)
        )
        self._keep_behavior_logp = self._postprocess_gae or bool(
            config.get("_keep_behavior_logp")
        )
        # frame-stack transport (policy_server.py): ship each env's newest
        # frame instead of the full stack; pixels for training stay on the
        # server's device. Requires a remote policy exposing the stacked
        # tick API and channel-stacked uint8 observations.
        self._fst = bool(config.get("_frame_stack_transport")) and hasattr(
            self.policy, "compute_actions_stacked")
        if self._fst:
            # reference rows replace pixels in the OBS column, so every
            # consumer that reads OBS as pixels is incompatible: offline
            # writers, replay learners (next_obs), V-trace logp recompute
            if (config.get("output") or self._store_next_obs
                    or not self._postprocess_gae):
                raise ValueError(
                    "frame_stack_transport supports on-policy GAE learners "
                    "(PPO/A2C) without offline output: the obs column holds "
                    "device-snapshot references, not pixels")
            self.policy.start_rollout(worker_index, self.num_envs)
            self._reset_mask = np.ones((self.num_envs,), bool)
        self.gamma = config.get("gamma", 0.99)
        self.lambda_ = config.get("lambda_", 0.95)
        self.fragment_length = config.get("rollout_fragment_length", 200)

        keys = [
            SampleBatch.OBS, SampleBatch.ACTIONS, SampleBatch.REWARDS,
            SampleBatch.TERMINATEDS, SampleBatch.TRUNCATEDS, SampleBatch.EPS_ID,
        ]
        if self._store_next_obs:
            keys.append(SampleBatch.NEXT_OBS)
        if self._keep_behavior_logp:
            keys += [SampleBatch.ACTION_LOGP, SampleBatch.VF_PREDS]
        self._keys = keys

        self._eps_counter = worker_index * 1_000_000
        self._envs: List[_EnvState] = []
        for i in range(self.num_envs):
            env = probe_env if i == 0 else self._make_env()
            obs, _ = env.reset(seed=seed * 10_000 + i)
            self._envs.append(_EnvState(env, obs, keys, self._next_eps_id()))
        self._episode_rewards: deque = deque(maxlen=100)
        self._episode_lengths: deque = deque(maxlen=100)
        self._episodes_total = 0
        self._total_steps = 0
        # offline output (rllib/offline JsonWriter analog)
        self._writer = None
        if config.get("output"):
            from ray_tpu.rllib.offline import JsonWriter

            self._writer = JsonWriter(config["output"], worker_index=worker_index)

    def _next_eps_id(self) -> int:
        self._eps_counter += 1
        return self._eps_counter

    def _prep_obs(self, o) -> np.ndarray:
        """Image obs keep [H, W, C] for the CNN — and keep uint8 pixels
        uint8 (the policy casts device-side; 4x less transport); flat obs
        flatten to float32.  Always copies: envs that return their internal
        frame buffer would otherwise alias every stored row."""
        if self._conv:
            return np.array(o)
        return np.asarray(o, np.float32).reshape(-1)

    def _env_action(self, action: np.ndarray):
        """Policy output -> what env.step accepts.  Continuous policies act
        in the canonical [-1, 1] box (tanh squash); rescale to the env's
        bounds so full-range actions are reachable (clip only when a bound
        is infinite and rescaling is undefined)."""
        if self._discrete:
            return int(action)
        lo, hi = self._action_low, self._action_high
        if np.all(np.isfinite(lo)) and np.all(np.isfinite(hi)):
            return lo + (np.clip(action, -1.0, 1.0) + 1.0) * (hi - lo) / 2.0
        return np.clip(action, lo, hi)

    # ------------------------------------------------------------------
    def sample(self) -> SampleBatch:
        """One fragment of ``num_envs * rollout_fragment_length`` steps,
        postprocessed per episode segment at its boundary.

        Bootstrap values (truncation and fragment-end) are computed in ONE
        batched ``policy.value`` call at the end of the fragment: with a
        remote policy (policy_server.py) per-segment calls would each pay
        a device round trip."""
        segments: List[SampleBatch] = []
        # segments awaiting a bootstrap value: (cols_snapshot, boot_obs)
        deferred: List = []

        def snapshot(es: _EnvState):
            seg_cols = {k: np.asarray(v) for k, v in es.cols.items()}
            for v in es.cols.values():
                v.clear()
            return seg_cols

        def close_terminal(es: _EnvState):
            if len(es.cols[SampleBatch.OBS]) == 0:
                return
            seg = SampleBatch(snapshot(es))
            if self._postprocess_gae:
                seg = compute_gae(seg, 0.0, self.gamma, self.lambda_)
            segments.append(seg)

        def defer_bootstrap(es: _EnvState, boot_obs):
            if len(es.cols[SampleBatch.OBS]) == 0:
                return
            deferred.append((snapshot(es), self._prep_obs(boot_obs)))

        for _ in range(self.fragment_length):
            if self._fst:
                # newest channel only (uint8 [n, H, W]); the server holds
                # and advances the full stacks device-side
                new_frames = np.stack(
                    [np.asarray(es.obs)[..., -1] for es in self._envs])
                actions, logps, vfs, tick = self.policy.compute_actions_stacked(
                    self.worker_index, new_frames, self._reset_mask)
                self._reset_mask[:] = False
                # [N, 3] (worker, tick, env) reference rows stand in for
                # pixel observations in the sample batch
                obs_batch = np.stack([
                    np.array([self.worker_index, tick, i], np.int32)
                    for i in range(self.num_envs)])
            else:
                obs_batch = np.stack(
                    [self._prep_obs(es.obs) for es in self._envs])
                actions, logps, vfs = self.policy.compute_actions(obs_batch)
            for i, es in enumerate(self._envs):
                a = actions[i]
                next_obs, reward, terminated, truncated, _ = es.env.step(
                    self._env_action(a)
                )
                es.cols[SampleBatch.OBS].append(obs_batch[i])
                es.cols[SampleBatch.ACTIONS].append(a)
                es.cols[SampleBatch.REWARDS].append(np.float32(reward))
                es.cols[SampleBatch.TERMINATEDS].append(terminated)
                es.cols[SampleBatch.TRUNCATEDS].append(truncated)
                es.cols[SampleBatch.EPS_ID].append(es.eps_id)
                if self._store_next_obs:
                    es.cols[SampleBatch.NEXT_OBS].append(self._prep_obs(next_obs))
                if self._keep_behavior_logp:
                    es.cols[SampleBatch.ACTION_LOGP].append(np.float32(logps[i]))
                    es.cols[SampleBatch.VF_PREDS].append(np.float32(vfs[i]))
                es.episode_reward += float(reward)
                es.episode_len += 1
                self._total_steps += 1
                es.obs = next_obs
                if terminated or truncated:
                    # terminal: no bootstrap; truncation: bootstrap v(s_T)
                    if terminated:
                        close_terminal(es)
                    else:
                        defer_bootstrap(es, next_obs)
                    self._episode_rewards.append(es.episode_reward)
                    self._episode_lengths.append(es.episode_len)
                    self._episodes_total += 1
                    es.episode_reward = 0.0
                    es.episode_len = 0
                    es.eps_id = self._next_eps_id()
                    es.obs, _ = es.env.reset()
                    if self._fst:
                        self._reset_mask[i] = True
        # fragment ended mid-episode: bootstrap with v(current obs)
        for es in self._envs:
            defer_bootstrap(es, es.obs)
        if deferred:
            if self._postprocess_gae:
                boots = self.policy.value(
                    np.stack([b for _, b in deferred]))
                for (seg_cols, _), v in zip(deferred, boots):
                    segments.append(compute_gae(
                        SampleBatch(seg_cols), float(v),
                        self.gamma, self.lambda_))
            else:
                segments.extend(SampleBatch(c) for c, _ in deferred)
        batch = SampleBatch.concat_samples(segments)
        if self._writer is not None:
            self._writer.write(batch)
        return batch

    # ------------------------------------------------------------------
    def evaluate_episodes(self, num_episodes: int,
                          max_steps_per_episode: int = 10_000) -> Dict[str, Any]:
        """Greedy evaluation on a dedicated cached env (``evaluation_config``'s
        explore=False path).  The step cap guards envs with no TimeLimit —
        training is fragment-bounded but this loop would otherwise hang."""
        env = getattr(self, "_eval_env", None)
        if env is None:
            env = self._eval_env = self._make_env()
        rewards, lengths = [], []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=977 + ep)
            total, steps = 0.0, 0
            while steps < max_steps_per_episode:
                a = self.policy.greedy_action(self._prep_obs(obs)[None])[0]
                obs, r, term, trunc, _ = env.step(self._env_action(a))
                total += float(r)
                steps += 1
                if term or trunc:
                    break
            rewards.append(total)
            lengths.append(steps)
        return {
            "episode_reward_mean": float(np.mean(rewards)),
            "episode_len_mean": float(np.mean(lengths)),
            "episodes_this_eval": num_episodes,
        }

    # ------------------------------------------------------------------
    def get_metrics(self) -> Dict[str, Any]:
        rewards = list(self._episode_rewards)
        return {
            "episode_reward_mean": float(np.mean(rewards)) if rewards else np.nan,
            "episode_len_mean": (
                float(np.mean(self._episode_lengths)) if self._episode_lengths else np.nan
            ),
            "episodes_total": self._episodes_total,
            "worker_steps": self._total_steps,
        }

    def set_global_vars(self, timesteps_total: int) -> bool:
        """Pin the policy's exploration schedule to global progress."""
        hook = getattr(self.policy, "on_global_timestep", None)
        if hook is not None:
            hook(timesteps_total)
        return True

    def get_weights(self):
        return self.policy.get_weights()

    def set_weights(self, weights) -> bool:
        self.policy.set_weights(weights)
        return True

    def apply(self, fn_blob: bytes):
        """Run a pickled fn(worker) — the reference's foreach_worker hook."""
        import cloudpickle

        return cloudpickle.loads(fn_blob)(self)
