"""RolloutWorker: env stepping + trajectory postprocessing.

Analog of ``/root/reference/rllib/evaluation/rollout_worker.py:153``: owns
env instances and a policy copy, collects fixed-size sample fragments,
postprocesses each episode segment with GAE at its boundary (terminal → no
bootstrap; truncation/fragment end → bootstrap with v(s_T)), and exposes
get/set_weights for learner sync.  Runs inline (local worker) or as an
actor (``num_rollout_workers > 0``).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.postprocessing import compute_gae
from ray_tpu.rllib.sample_batch import SampleBatch


def _default_env_creator(env_name: str):
    import gymnasium as gym

    return gym.make(env_name)


class RolloutWorker:
    def __init__(self, config: Dict[str, Any], worker_index: int = 0):
        self.config = config
        self.worker_index = worker_index
        env_creator: Optional[Callable] = config.get("env_creator")
        if env_creator is not None:
            self.env = env_creator(config.get("env_config", {}))
        else:
            self.env = _default_env_creator(config["env"])
        obs_dim = int(np.prod(self.env.observation_space.shape))
        num_actions = int(self.env.action_space.n)
        seed = int(config.get("seed") or 0) + worker_index

        from ray_tpu.rllib.policy import JaxPolicy

        loss_factory = config.get("_loss_factory")
        policy_cls = config.get("_policy_class") or JaxPolicy
        # algorithm-specific policy constructor args travel as one dict
        # (or a factory over the live config) so this worker stays
        # algorithm-agnostic
        pk_factory = config.get("_policy_kwargs_factory")
        extra = (dict(pk_factory(config)) if pk_factory
                 else dict(config.get("_policy_kwargs") or {}))
        self.policy = policy_cls(
            obs_dim,
            num_actions,
            lr=config.get("lr", 5e-4),
            hiddens=tuple(config.get("fcnet_hiddens", (64, 64))),
            seed=seed,  # per-worker: decorrelates action sampling rng
            loss_fn=loss_factory(config) if loss_factory else None,
            grad_clip=config.get("grad_clip", 0.5),
            **extra,
        )
        self._store_next_obs = bool(config.get("_store_next_obs"))
        self.gamma = config.get("gamma", 0.99)
        self.lambda_ = config.get("lambda_", 0.95)
        self.fragment_length = config.get("rollout_fragment_length", 200)
        self._obs, _ = self.env.reset(seed=seed)
        self._episode_reward = 0.0
        self._episode_len = 0
        self._episode_rewards: deque = deque(maxlen=100)
        self._episode_lengths: deque = deque(maxlen=100)
        self._eps_id = worker_index * 1_000_000
        self._total_steps = 0

    # ------------------------------------------------------------------
    def sample(self) -> SampleBatch:
        """One fragment of ``rollout_fragment_length`` steps, GAE-complete
        (``rollout_worker.py`` sample -> SamplerInput analog)."""
        keys = [
            SampleBatch.OBS, SampleBatch.ACTIONS, SampleBatch.REWARDS,
            SampleBatch.TERMINATEDS, SampleBatch.TRUNCATEDS, SampleBatch.EPS_ID,
        ]
        if self._store_next_obs:
            # off-policy algorithms store raw transitions; logp/vf/GAE
            # columns would be dead weight in the replay buffer
            keys.append(SampleBatch.NEXT_OBS)
        else:
            keys += [SampleBatch.ACTION_LOGP, SampleBatch.VF_PREDS]
        cols: Dict[str, List] = {k: [] for k in keys}
        segments: List[SampleBatch] = []
        seg_start = 0

        def close_segment(last_value_fn):
            nonlocal seg_start
            if seg_start >= len(cols[SampleBatch.OBS]):
                return
            seg = SampleBatch({
                k: np.asarray(v[seg_start:]) for k, v in cols.items()
            })
            if self._store_next_obs:
                segments.append(seg)  # TD targets are recomputed at replay time
            else:
                segments.append(
                    compute_gae(seg, last_value_fn(), self.gamma, self.lambda_)
                )
            seg_start = len(cols[SampleBatch.OBS])

        for _ in range(self.fragment_length):
            # flatten: the policy is an MLP over a 1-D feature vector
            obs = np.asarray(self._obs, dtype=np.float32).reshape(-1)
            action, logp, vf = self.policy.compute_actions(obs[None])
            a = int(action[0])
            next_obs, reward, terminated, truncated, _ = self.env.step(a)
            cols[SampleBatch.OBS].append(obs)
            cols[SampleBatch.ACTIONS].append(a)
            cols[SampleBatch.REWARDS].append(np.float32(reward))
            cols[SampleBatch.TERMINATEDS].append(terminated)
            cols[SampleBatch.TRUNCATEDS].append(truncated)
            if not self._store_next_obs:
                cols[SampleBatch.ACTION_LOGP].append(np.float32(logp[0]))
                cols[SampleBatch.VF_PREDS].append(np.float32(vf[0]))
            cols[SampleBatch.EPS_ID].append(self._eps_id)
            if self._store_next_obs:
                cols[SampleBatch.NEXT_OBS].append(
                    np.asarray(next_obs, np.float32).reshape(-1)
                )
            self._episode_reward += float(reward)
            self._episode_len += 1
            self._total_steps += 1
            self._obs = next_obs
            if terminated or truncated:
                # terminal: no bootstrap; truncation: bootstrap v(s_T)
                _next = next_obs
                close_segment(lambda: 0.0 if terminated else float(
                    self.policy.value(
                        np.asarray(_next, np.float32).reshape(1, -1)
                    )[0]
                ))
                self._episode_rewards.append(self._episode_reward)
                self._episode_lengths.append(self._episode_len)
                self._episode_reward = 0.0
                self._episode_len = 0
                self._eps_id += 1
                self._obs, _ = self.env.reset()
        # fragment ended mid-episode: bootstrap with v(current obs)
        close_segment(lambda: float(
            self.policy.value(np.asarray(self._obs, np.float32).reshape(1, -1))[0]
        ))
        return SampleBatch.concat_samples(segments)

    # ------------------------------------------------------------------
    def get_metrics(self) -> Dict[str, Any]:
        rewards = list(self._episode_rewards)
        return {
            "episode_reward_mean": float(np.mean(rewards)) if rewards else np.nan,
            "episode_len_mean": (
                float(np.mean(self._episode_lengths)) if self._episode_lengths else np.nan
            ),
            "episodes_total": self._eps_id - self.worker_index * 1_000_000,
            "worker_steps": self._total_steps,
        }

    def set_global_vars(self, timesteps_total: int) -> bool:
        """Pin the policy's exploration schedule to global progress."""
        hook = getattr(self.policy, "on_global_timestep", None)
        if hook is not None:
            hook(timesteps_total)
        return True

    def get_weights(self):
        return self.policy.get_weights()

    def set_weights(self, weights) -> bool:
        self.policy.set_weights(weights)
        return True

    def apply(self, fn_blob: bytes):
        """Run a pickled fn(worker) — the reference's foreach_worker hook."""
        import cloudpickle

        return cloudpickle.loads(fn_blob)(self)
