"""RolloutWorker: env stepping + trajectory postprocessing.

Analog of ``/root/reference/rllib/evaluation/rollout_worker.py:153`` with
the vector-env stepping of ``env_runner_v2.py:198``: owns ``num_envs``
env instances stepped in lockstep (one batched policy forward per tick),
collects fixed-size sample fragments, postprocesses each episode segment
at its boundary (GAE for on-policy learners; raw transitions for
replay-based ones), and exposes get/set_weights for learner sync.  Runs
inline (local worker) or as an actor (``num_rollout_workers > 0``).

Env<->policy preprocessing is NOT hardwired here: the observation path is
an :class:`AgentConnectorPipeline` and the action path an
:class:`ActionConnectorPipeline` (``rllib/connectors/``).  With no config
spec the worker installs defaults equivalent to the old behavior
(flatten+float32 for MLPs, uint8 [H, W, C] copies for CNNs, unsquash/clip
on continuous actions); configs compose richer pipelines (running-stat
normalization, frame stacking) through ``AlgorithmConfig.connectors``.
Each raw observation is transformed EXACTLY ONCE (cached per env as
``prepped``), so stateful connectors see the true episode stream.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu._private import events
from ray_tpu.rllib.connectors import (
    ActionConnectorPipeline,
    AgentConnectorPipeline,
    ConnectorContext,
    NormalizeObs,
    build_pipeline,
    default_action_connectors,
    default_agent_connectors,
)
from ray_tpu.rllib.postprocessing import compute_gae
from ray_tpu.rllib.sample_batch import SampleBatch

# the env_id stream used by evaluation / single-obs inference, so
# stateful connectors never mix it with training envs (0..num_envs-1)
EVAL_ENV_ID = -1


def _default_env_creator(env_name: str):
    import gymnasium as gym

    return gym.make(env_name)


class _EnvState:
    """Per-env rollout bookkeeping (column buffers + episode stats)."""

    __slots__ = ("env", "obs", "prepped", "cols", "episode_reward",
                 "episode_len", "eps_id")

    def __init__(self, env, obs, keys, eps_id):
        self.env = env
        self.obs = obs  # raw (frame-stack transport reads raw frames)
        self.prepped = None  # connector-transformed, one transform per obs
        self.cols: Dict[str, List] = {k: [] for k in keys}
        self.episode_reward = 0.0
        self.episode_len = 0
        self.eps_id = eps_id


class RolloutWorker:
    def __init__(self, config: Dict[str, Any], worker_index: int = 0):
        self.config = config
        self.worker_index = worker_index
        env_creator: Optional[Callable] = config.get("env_creator")
        self._make_env = (
            (lambda: env_creator(config.get("env_config", {})))
            if env_creator is not None
            else (lambda: _default_env_creator(config["env"]))
        )
        self.num_envs = max(1, int(config.get("num_envs_per_worker", 1)))
        probe_env = self._make_env()
        self.ctx = ConnectorContext.from_env(probe_env, config)
        self._obs_shape = self.ctx.obs_shape
        # An EXPLICIT agent pipeline may change the policy's input shape
        # (frame stacking widens it); probe with a zeros observation so
        # the policy — and the ctx custom RLModules size off — see the
        # TRANSFORMED shape.  Default pipelines preserve dims, so the
        # ctx keeps the env's shape when no spec is given.
        agent_spec = config.get("agent_connectors")
        explicit_agent_pipe = None
        if agent_spec is not None:
            explicit_agent_pipe = build_pipeline(
                AgentConnectorPipeline, self.ctx, agent_spec)
            probe = explicit_agent_pipe(
                np.zeros(self._obs_shape, np.float32),
                env_id="__probe__", training=False)
            explicit_agent_pipe.reset("__probe__")
            self.ctx.obs_shape = tuple(probe.shape)
            self.ctx.obs_dim = int(np.prod(probe.shape))
        policy_obs_shape = self.ctx.obs_shape
        obs_dim = self.ctx.obs_dim
        num_actions = self.ctx.num_actions
        seed = int(config.get("seed") or 0) + worker_index

        from ray_tpu.rllib.policy import JaxPolicy

        loss_factory = config.get("_loss_factory")
        policy_cls = config.get("_policy_class") or JaxPolicy
        # algorithm-specific policy constructor args travel as one dict
        # (or a factory over the live config) so this worker stays
        # algorithm-agnostic
        pk_factory = config.get("_policy_kwargs_factory")
        extra = (dict(pk_factory(config)) if pk_factory
                 else dict(config.get("_policy_kwargs") or {}))
        module_factory = config.get("_rl_module_factory")
        if module_factory is not None:
            # RLModule plugin seam: custom JAX models drop in without
            # subclassing Policy — the factory sizes itself off the ctx
            extra.setdefault("module", module_factory(self.ctx))
        if len(policy_obs_shape) == 3 and policy_cls is JaxPolicy:
            # image observations -> the catalog's CNN (catalog.py:195
            # dispatch); subclass policies keep their own model choices
            extra.setdefault("obs_shape", policy_obs_shape)
        self.policy = policy_cls(
            obs_dim,
            num_actions,
            lr=config.get("lr", 5e-4),
            hiddens=tuple(config.get("fcnet_hiddens", (64, 64))),
            seed=seed,  # per-worker: decorrelates action sampling rng
            loss_fn=loss_factory(config) if loss_factory else None,
            grad_clip=config.get("grad_clip", 0.5),
            **extra,
        )
        # obs stay [H, W, C] only when the BUILT policy actually carries a
        # conv net — a flat-MLP policy (DQN/SAC on image envs) gets
        # flattened observations instead of a shape crash.  A CUSTOM
        # module on an image env keeps [H, W, C] too (its params carry no
        # "conv" key to sniff; a custom module wanting flat input on an
        # image env passes explicit agent_connectors).
        p = getattr(self.policy, "params", None)
        self._conv = (isinstance(p, dict) and "conv" in p) or (
            module_factory is not None and len(policy_obs_shape) == 3)
        # -- connector pipelines: THE sample path -----------------------
        if explicit_agent_pipe is not None:
            self.agent_connectors = explicit_agent_pipe
        else:
            self.agent_connectors = AgentConnectorPipeline(
                self.ctx, default_agent_connectors(self.ctx, self._conv))
            if config.get("observation_filter") == "MeanStdFilter":
                self.agent_connectors.append(NormalizeObs())
        self.action_connectors = build_pipeline(
            ActionConnectorPipeline, self.ctx,
            config.get("action_connectors"))
        if config.get("action_connectors") is None:
            for c in default_action_connectors(self.ctx):
                self.action_connectors.append(c)
        self._store_next_obs = bool(config.get("_store_next_obs"))
        # on-policy learners want GAE + behavior logp/vf columns; replay
        # learners want raw transitions; IMPALA wants transitions AND the
        # behavior policy's logp for V-trace importance ratios
        self._postprocess_gae = bool(
            config.get("_postprocess_gae", not self._store_next_obs)
        )
        self._keep_behavior_logp = self._postprocess_gae or bool(
            config.get("_keep_behavior_logp")
        )
        # frame-stack transport (policy_server.py): ship each env's newest
        # frame instead of the full stack; pixels for training stay on the
        # server's device. Requires a remote policy exposing the stacked
        # tick API and channel-stacked uint8 observations.
        self._fst = bool(config.get("_frame_stack_transport")) and hasattr(
            self.policy, "compute_actions_stacked")
        if self._fst:
            # reference rows replace pixels in the OBS column, so every
            # consumer that reads OBS as pixels is incompatible: offline
            # writers, replay learners (next_obs), V-trace logp recompute
            if (config.get("output") or self._store_next_obs
                    or not self._postprocess_gae):
                raise ValueError(
                    "frame_stack_transport supports on-policy GAE learners "
                    "(PPO/A2C) without offline output: the obs column holds "
                    "device-snapshot references, not pixels")
            self.policy.start_rollout(worker_index, self.num_envs)
            self._reset_mask = np.ones((self.num_envs,), bool)
        self.gamma = config.get("gamma", 0.99)
        self.lambda_ = config.get("lambda_", 0.95)
        self.fragment_length = config.get("rollout_fragment_length", 200)

        keys = [
            SampleBatch.OBS, SampleBatch.ACTIONS, SampleBatch.REWARDS,
            SampleBatch.TERMINATEDS, SampleBatch.TRUNCATEDS, SampleBatch.EPS_ID,
        ]
        if self._store_next_obs:
            keys.append(SampleBatch.NEXT_OBS)
        if self._keep_behavior_logp:
            keys += [SampleBatch.ACTION_LOGP, SampleBatch.VF_PREDS]
        self._keys = keys

        self._eps_counter = worker_index * 1_000_000
        self._envs: List[_EnvState] = []
        for i in range(self.num_envs):
            env = probe_env if i == 0 else self._make_env()
            obs, _ = env.reset(seed=seed * 10_000 + i)
            es = _EnvState(env, obs, keys, self._next_eps_id())
            es.prepped = self.agent_connectors(obs, env_id=i)
            self._envs.append(es)
        self._episode_rewards: deque = deque(maxlen=100)
        self._episode_lengths: deque = deque(maxlen=100)
        self._episodes_total = 0
        self._total_steps = 0
        # offline output (rllib/offline JsonWriter analog)
        self._writer = None
        if config.get("output"):
            from ray_tpu.rllib.offline import JsonWriter

            self._writer = JsonWriter(config["output"], worker_index=worker_index)

    def _next_eps_id(self) -> int:
        self._eps_counter += 1
        return self._eps_counter

    def _prep_obs(self, o, env_id: Any = EVAL_ENV_ID,
                  training: bool = False) -> np.ndarray:
        """One obs through the agent pipeline on the EVALUATION stream:
        statistics frozen, episode state keyed off the training envs.
        The sample loop does NOT come through here — it transforms each
        env's stream inline (one transform per raw obs, cached)."""
        return self.agent_connectors(o, env_id=env_id, training=training)

    def _env_action(self, action: np.ndarray):
        """Policy output -> what env.step accepts (the action-connector
        pipeline: int cast for discrete, unsquash from the canonical
        [-1, 1] box or clip for continuous)."""
        return self.action_connectors(action)

    # -- connector state (rides checkpoints + worker sync) -------------
    def get_connector_state(self) -> Dict[str, Any]:
        return {"agent": self.agent_connectors.to_state(),
                "action": self.action_connectors.to_state()}

    def set_connector_state(self, state: Dict[str, Any]) -> bool:
        self.agent_connectors.set_state(state["agent"])
        self.action_connectors.set_state(state["action"])
        # the rebuilt pipelines invalidate every cached transform: re-prep
        # each env's current obs on fresh episode state (a restored frame
        # stack restarts mid-episode with first-frame-repeat semantics,
        # exactly like a freshly reset env; stats stay frozen — the obs
        # was already counted once when it entered the stream)
        for i, es in enumerate(getattr(self, "_envs", ())):
            self.agent_connectors.reset(i)
            es.prepped = (None if self._fst else self.agent_connectors(
                es.obs, env_id=i, training=False))
        return True

    # -- distributed filter sync (stats only; episode state untouched) --
    def pop_connector_stat_deltas(self):
        return self.agent_connectors.pop_stat_deltas()

    def apply_connector_stat_deltas(self, deltas) -> bool:
        self.agent_connectors.apply_stat_deltas(deltas)
        return True

    def get_connector_stat_states(self):
        return self.agent_connectors.get_stat_states()

    def set_connector_stat_states(self, states) -> bool:
        self.agent_connectors.set_stat_states(states)
        return True

    # ------------------------------------------------------------------
    def sample(self) -> SampleBatch:
        """One fragment of ``num_envs * rollout_fragment_length`` steps,
        postprocessed per episode segment at its boundary.

        Bootstrap values (truncation and fragment-end) are computed in ONE
        batched ``policy.value`` call at the end of the fragment: with a
        remote policy (policy_server.py) per-segment calls would each pay
        a device round trip."""
        t_wall = time.perf_counter()
        phase = {"env_s": 0.0, "infer_s": 0.0, "connector_s": 0.0,
                 "postprocess_s": 0.0}
        segments: List[SampleBatch] = []
        # segments awaiting a bootstrap value: (cols_snapshot, boot_prepped)
        deferred: List = []

        def snapshot(es: _EnvState):
            seg_cols = {k: np.asarray(v) for k, v in es.cols.items()}
            for v in es.cols.values():
                v.clear()
            return seg_cols

        def close_terminal(es: _EnvState):
            if len(es.cols[SampleBatch.OBS]) == 0:
                return
            seg = SampleBatch(snapshot(es))
            if self._postprocess_gae:
                t0 = time.perf_counter()
                seg = compute_gae(seg, 0.0, self.gamma, self.lambda_)
                phase["postprocess_s"] += time.perf_counter() - t0
            segments.append(seg)

        def defer_bootstrap(es: _EnvState, boot_prepped):
            if len(es.cols[SampleBatch.OBS]) == 0:
                return
            deferred.append((snapshot(es), boot_prepped))

        def transform(o, i):
            t0 = time.perf_counter()
            out = self.agent_connectors(o, env_id=i)
            phase["connector_s"] += time.perf_counter() - t0
            return out

        for _ in range(self.fragment_length):
            t0 = time.perf_counter()
            if self._fst:
                # newest channel only (uint8 [n, H, W]); the server holds
                # and advances the full stacks device-side
                new_frames = np.stack(
                    [np.asarray(es.obs)[..., -1] for es in self._envs])
                actions, logps, vfs, tick = self.policy.compute_actions_stacked(
                    self.worker_index, new_frames, self._reset_mask)
                self._reset_mask[:] = False
                # [N, 3] (worker, tick, env) reference rows stand in for
                # pixel observations in the sample batch
                obs_batch = np.stack([
                    np.array([self.worker_index, tick, i], np.int32)
                    for i in range(self.num_envs)])
            else:
                obs_batch = np.stack([es.prepped for es in self._envs])
                actions, logps, vfs = self.policy.compute_actions(obs_batch)
            phase["infer_s"] += time.perf_counter() - t0
            for i, es in enumerate(self._envs):
                a = actions[i]
                t0 = time.perf_counter()
                next_obs, reward, terminated, truncated, _ = es.env.step(
                    self._env_action(a)
                )
                phase["env_s"] += time.perf_counter() - t0
                es.cols[SampleBatch.OBS].append(obs_batch[i])
                es.cols[SampleBatch.ACTIONS].append(a)
                es.cols[SampleBatch.REWARDS].append(np.float32(reward))
                es.cols[SampleBatch.TERMINATEDS].append(terminated)
                es.cols[SampleBatch.TRUNCATEDS].append(truncated)
                es.cols[SampleBatch.EPS_ID].append(es.eps_id)
                # next_obs continues env i's episode stream; transform it
                # ONCE here and reuse (NEXT_OBS column, truncation
                # bootstrap, next tick's policy input).  On a TERMINAL
                # step the post-terminal obs is discarded by the reset —
                # skip the transform so a never-used obs can't bias
                # running statistics — UNLESS the learner consumes it
                # (replay algorithms read NEXT_OBS even at terminals)
                next_prepped = None
                if not self._fst and (not terminated or self._store_next_obs):
                    next_prepped = transform(next_obs, i)
                if self._store_next_obs:
                    es.cols[SampleBatch.NEXT_OBS].append(next_prepped)
                if self._keep_behavior_logp:
                    es.cols[SampleBatch.ACTION_LOGP].append(np.float32(logps[i]))
                    es.cols[SampleBatch.VF_PREDS].append(np.float32(vfs[i]))
                es.episode_reward += float(reward)
                es.episode_len += 1
                self._total_steps += 1
                es.obs = next_obs
                es.prepped = next_prepped
                if terminated or truncated:
                    # terminal: no bootstrap; truncation: bootstrap v(s_T)
                    if terminated:
                        close_terminal(es)
                    else:
                        defer_bootstrap(
                            es, next_prepped if next_prepped is not None
                            else transform(next_obs, i))
                    self._episode_rewards.append(es.episode_reward)
                    self._episode_lengths.append(es.episode_len)
                    self._episodes_total += 1
                    es.episode_reward = 0.0
                    es.episode_len = 0
                    es.eps_id = self._next_eps_id()
                    es.obs, _ = es.env.reset()
                    # episode boundary: frame stacks et al. start fresh
                    self.agent_connectors.reset(i)
                    es.prepped = (None if self._fst
                                  else transform(es.obs, i))
                    if self._fst:
                        self._reset_mask[i] = True
        # fragment ended mid-episode: bootstrap with v(current obs) —
        # already transformed (prepped) except on the frame-stack
        # transport path, where obs stay raw until here
        for i, es in enumerate(self._envs):
            defer_bootstrap(es, es.prepped if es.prepped is not None
                            else transform(es.obs, i))
        if deferred:
            if self._postprocess_gae:
                t0 = time.perf_counter()
                boots = self.policy.value(
                    np.stack([b for _, b in deferred]))
                phase["infer_s"] += time.perf_counter() - t0
                t0 = time.perf_counter()
                for (seg_cols, _), v in zip(deferred, boots):
                    segments.append(compute_gae(
                        SampleBatch(seg_cols), float(v),
                        self.gamma, self.lambda_))
                phase["postprocess_s"] += time.perf_counter() - t0
            else:
                segments.extend(SampleBatch(c) for c, _ in deferred)
        batch = SampleBatch.concat_samples(segments)
        if self._writer is not None:
            self._writer.write(batch)
        # flight-recorder span: what `ray_tpu trace`/timeline and the
        # rl_env_steps_scaling knee attribution read (env vs inference vs
        # connector vs postprocess shares of the fragment wall)
        events.emit(
            "rllib", "rollout sample",
            entity_id=f"rollout-{self.worker_index}",
            span_dur=time.perf_counter() - t_wall,
            env_steps=batch.count,
            **{k: round(v, 6) for k, v in phase.items()})
        return batch

    # ------------------------------------------------------------------
    def evaluate_episodes(self, num_episodes: int,
                          max_steps_per_episode: int = 10_000) -> Dict[str, Any]:
        """Greedy evaluation on a dedicated cached env (``evaluation_config``'s
        explore=False path).  The step cap guards envs with no TimeLimit —
        training is fragment-bounded but this loop would otherwise hang.
        Observations ride the agent pipeline on the EVAL stream (frozen
        statistics, own episode state reset per episode)."""
        env = getattr(self, "_eval_env", None)
        if env is None:
            env = self._eval_env = self._make_env()
        rewards, lengths = [], []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=977 + ep)
            self.agent_connectors.reset(EVAL_ENV_ID)
            total, steps = 0.0, 0
            while steps < max_steps_per_episode:
                a = self.policy.greedy_action(self._prep_obs(obs)[None])[0]
                obs, r, term, trunc, _ = env.step(self._env_action(a))
                total += float(r)
                steps += 1
                if term or trunc:
                    break
            rewards.append(total)
            lengths.append(steps)
        # don't leak the last eval episode's residue (frame stacks) into
        # a later external compute_single_action stream
        self.agent_connectors.reset(EVAL_ENV_ID)
        return {
            "episode_reward_mean": float(np.mean(rewards)),
            "episode_len_mean": float(np.mean(lengths)),
            "episodes_this_eval": num_episodes,
        }

    # ------------------------------------------------------------------
    def get_metrics(self) -> Dict[str, Any]:
        rewards = list(self._episode_rewards)
        return {
            "episode_reward_mean": float(np.mean(rewards)) if rewards else np.nan,
            "episode_len_mean": (
                float(np.mean(self._episode_lengths)) if self._episode_lengths else np.nan
            ),
            "episodes_total": self._episodes_total,
            "worker_steps": self._total_steps,
        }

    def set_global_vars(self, timesteps_total: int) -> bool:
        """Pin the policy's exploration schedule to global progress."""
        hook = getattr(self.policy, "on_global_timestep", None)
        if hook is not None:
            hook(timesteps_total)
        return True

    def get_weights(self):
        return self.policy.get_weights()

    def set_weights(self, weights) -> bool:
        self.policy.set_weights(weights)
        return True

    def apply(self, fn_blob: bytes):
        """Run a pickled fn(worker) — the reference's foreach_worker hook."""
        import cloudpickle

        return cloudpickle.loads(fn_blob)(self)
