"""SAC: soft actor-critic for continuous control, in jax.

Analog of ``/root/reference/rllib/algorithms/sac/sac.py`` (+
``sac_torch_policy.py``): squashed-Gaussian actor, twin Q networks with
Polyak-averaged targets, entropy-regularized objectives, and automatic
temperature tuning against a target entropy of ``-act_dim``.  The whole
update (actor + both critics + alpha + target Polyak) jits into one XLA
program — the TPU-friendly phrasing of the reference's four torch
optimizer steps.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig, synchronous_parallel_sample
from ray_tpu.rllib.models import (
    apply_gaussian_actor,
    apply_q_network,
    init_gaussian_actor,
    init_q_network,
)
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.sample_batch import SampleBatch

_LOG_2PI = float(np.log(2.0 * np.pi))


def _squashed_sample(actor_params, rng, obs):
    """Sample tanh-squashed action + its log-prob (change of variables)."""
    mean, log_std = apply_gaussian_actor(actor_params, obs)
    std = jnp.exp(log_std)
    eps = jax.random.normal(rng, mean.shape)
    pre = mean + std * eps
    act = jnp.tanh(pre)
    # Gaussian logp minus tanh Jacobian, summed over action dims
    logp = -0.5 * jnp.sum(
        ((pre - mean) / std) ** 2 + 2.0 * log_std + _LOG_2PI, axis=-1
    )
    logp -= jnp.sum(2.0 * (jnp.log(2.0) - pre - jax.nn.softplus(-2.0 * pre)), axis=-1)
    return act, logp


class SACPolicy:
    """Continuous policy: actor + twin critics + temperature, all jax.

    Constructor signature matches what RolloutWorker passes a policy
    (obs_dim, num_actions=act_dim, lr, hiddens, seed, loss_fn unused,
    grad_clip), so SAC plugs into the same rollout machinery as the
    discrete algorithms.
    """

    def __init__(self, obs_dim: int, num_actions: int, *, lr=3e-4,
                 hiddens=(64, 64), seed=0, loss_fn=None, grad_clip=None,
                 gamma=0.99, tau=0.005, initial_alpha=1.0, **_kw):
        del loss_fn
        self.obs_dim, self.act_dim = obs_dim, num_actions
        self.gamma, self.tau = gamma, tau
        self._rng = jax.random.PRNGKey(seed)
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed + 1), 3)
        self.params = {
            "actor": init_gaussian_actor(k1, obs_dim, num_actions, hiddens),
            "q1": init_q_network(k2, obs_dim, num_actions, hiddens),
            "q2": init_q_network(k3, obs_dim, num_actions, hiddens),
            "log_alpha": jnp.asarray(float(np.log(initial_alpha))),
        }
        self.target_q = jax.tree_util.tree_map(
            jnp.asarray, {"q1": self.params["q1"], "q2": self.params["q2"]}
        )
        tx = [optax.clip_by_global_norm(grad_clip)] if grad_clip else []
        self.optimizer = optax.chain(*tx, optax.adam(lr))
        self.opt_state = self.optimizer.init(self.params)
        self.target_entropy = -float(num_actions)

        @jax.jit
        def _act(params, rng, obs):
            return _squashed_sample(params["actor"], rng, obs)

        @jax.jit
        def _greedy(params, obs):
            mean, _ = apply_gaussian_actor(params["actor"], obs)
            return jnp.tanh(mean)

        @jax.jit
        def _update(params, target_q, opt_state, rng, batch):
            obs = batch[SampleBatch.OBS]
            act = batch[SampleBatch.ACTIONS]
            rew = batch[SampleBatch.REWARDS]
            done = batch[SampleBatch.TERMINATEDS].astype(jnp.float32)
            next_obs = batch[SampleBatch.NEXT_OBS]
            r1, r2 = jax.random.split(rng)

            # targets from the frozen critics (no gradient)
            next_a, next_logp = _squashed_sample(params["actor"], r1, next_obs)
            alpha = jnp.exp(params["log_alpha"])
            tq = jnp.minimum(
                apply_q_network(target_q["q1"], next_obs, next_a),
                apply_q_network(target_q["q2"], next_obs, next_a),
            ) - alpha * next_logp
            q_target = jax.lax.stop_gradient(rew + self.gamma * (1.0 - done) * tq)

            def loss_fn(p):
                q1 = apply_q_network(p["q1"], obs, act)
                q2 = apply_q_network(p["q2"], obs, act)
                critic_loss = jnp.mean((q1 - q_target) ** 2) + jnp.mean(
                    (q2 - q_target) ** 2
                )
                new_a, logp = _squashed_sample(p["actor"], r2, obs)
                a_det = jnp.exp(jax.lax.stop_gradient(p["log_alpha"]))
                q_pi = jnp.minimum(
                    apply_q_network(jax.lax.stop_gradient(p["q1"]), obs, new_a),
                    apply_q_network(jax.lax.stop_gradient(p["q2"]), obs, new_a),
                )
                actor_loss = jnp.mean(a_det * logp - q_pi)
                alpha_loss = -jnp.mean(
                    p["log_alpha"]
                    * jax.lax.stop_gradient(logp + self.target_entropy)
                )
                total = critic_loss + actor_loss + alpha_loss
                return total, {
                    "critic_loss": critic_loss,
                    "actor_loss": actor_loss,
                    "alpha_loss": alpha_loss,
                    "alpha": a_det,
                    "mean_q": jnp.mean(q1),
                    "entropy": -jnp.mean(logp),
                }

            (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            # Polyak target update, fused into the same compiled step
            target_q = jax.tree_util.tree_map(
                lambda t, s: (1.0 - self.tau) * t + self.tau * s,
                target_q,
                {"q1": params["q1"], "q2": params["q2"]},
            )
            return params, target_q, opt_state, loss, metrics

        self._act_jit = _act
        self._greedy_jit = _greedy
        self._update_jit = _update

    # -- acting (RolloutWorker contract) --------------------------------
    def compute_actions(self, obs: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        self._rng, key = jax.random.split(self._rng)
        act, logp = self._act_jit(self.params, key, jnp.asarray(obs))
        vf = np.zeros(len(obs), np.float32)  # SAC has no V head; unused
        return np.asarray(act), np.asarray(logp), vf

    def greedy_action(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(self._greedy_jit(self.params, jnp.asarray(obs)))

    def value(self, obs: np.ndarray) -> np.ndarray:
        return np.zeros(len(obs), np.float32)  # replay path never bootstraps here

    # -- learning --------------------------------------------------------
    def learn_on_minibatch(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self._rng, key = jax.random.split(self._rng)
        self.params, self.target_q, self.opt_state, loss, metrics = self._update_jit(
            self.params, self.target_q, self.opt_state, key, jb
        )
        out = {"total_loss": float(loss)}
        out.update({k: float(v) for k, v in metrics.items()})
        return out

    # -- weights ---------------------------------------------------------
    def get_weights(self):
        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)

    def get_state(self) -> Dict[str, Any]:
        return {
            "weights": self.get_weights(),
            "target_q": jax.tree_util.tree_map(np.asarray, self.target_q),
            "opt_state": jax.tree_util.tree_map(np.asarray, self.opt_state),
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.set_weights(state["weights"])
        if state.get("target_q") is not None:
            self.target_q = jax.tree_util.tree_map(jnp.asarray, state["target_q"])
        if state.get("opt_state") is not None:
            self.opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=SAC)
        self._config.update(
            _policy_class=SACPolicy,
            _policy_kwargs_factory=_sac_policy_kwargs,
            _store_next_obs=True,
            lr=3e-4,
            gamma=0.99,
            tau=0.005,
            train_batch_size=256,
            replay_buffer_capacity=100_000,
            learning_starts=500,
            timesteps_per_iteration=500,
            updates_per_iteration=250,
            grad_clip=None,
            rollout_fragment_length=100,
        )


def _sac_policy_kwargs(config: Dict[str, Any]) -> Dict[str, Any]:
    return {"gamma": config["gamma"], "tau": config["tau"]}


class SAC(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        self.replay = ReplayBuffer(
            self.config["replay_buffer_capacity"],
            seed=self.config.get("seed") or 0,
        )

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        if self.reader is not None:
            batch = self._read_offline(cfg["timesteps_per_iteration"])
        else:
            self.workers.sync_weights()
            batch = synchronous_parallel_sample(
                self.workers, max_env_steps=cfg["timesteps_per_iteration"]
            )
        self._timesteps_total += batch.count
        self.replay.add_batch(batch)

        policy: SACPolicy = self.workers.local_worker.policy
        learner_metrics: Dict[str, Any] = {}
        if len(self.replay) >= cfg["learning_starts"]:
            for _ in range(cfg["updates_per_iteration"]):
                mb = self.replay.sample(cfg["train_batch_size"])
                learner_metrics = policy.learn_on_minibatch({
                    SampleBatch.OBS: mb[SampleBatch.OBS],
                    SampleBatch.ACTIONS: mb[SampleBatch.ACTIONS],
                    SampleBatch.REWARDS: mb[SampleBatch.REWARDS],
                    SampleBatch.TERMINATEDS: mb[SampleBatch.TERMINATEDS],
                    SampleBatch.NEXT_OBS: mb[SampleBatch.NEXT_OBS],
                })
        learner_metrics["replay_size"] = len(self.replay)
        return {"info": {"learner": learner_metrics}}


SAC._default_config = SACConfig().to_dict()
