"""APPO: asynchronous PPO — IMPALA's architecture with PPO's objective.

Analog of ``/root/reference/rllib/algorithms/appo/appo.py:1``
(``appo.py`` composes the IMPALA execution plan with the clipped
surrogate; ``appo_torch_policy.py`` applies the surrogate over V-trace
advantages).  Composition here is literal:

- loss: PPO's clipped surrogate (``ppo.make_ppo_loss`` — the ratio is
  exp(current - BEHAVIOR logp), which is exactly what stale async
  samples need) over V-trace-corrected advantages/targets.
- correction: :meth:`Impala._vtrace_batch` (inherited) recomputes
  advantages with the CURRENT learner policy, so off-policy staleness
  from async sampling is handled by rho/c clipping, not ignored.
- execution: rollout workers ALWAYS have a sample() call in flight —
  the learner trains on whichever batch lands first and immediately
  re-arms that worker with fresh weights (the async rollout/learner
  overlap of ``execution/train_ops.py:82``'s async mode).  No global
  sampling barrier: a slow worker never stalls the learner.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import train_one_step
from ray_tpu.rllib.impala import Impala, ImpalaConfig
from ray_tpu.rllib.ppo import make_ppo_loss
from ray_tpu.rllib.sample_batch import SampleBatch


def _appo_loss_factory(config: Dict[str, Any]):
    """PPO's clipped surrogate; V-trace supplies ADVANTAGES and
    VALUE_TARGETS, the behavior ACTION_LOGP anchors the ratio."""
    return make_ppo_loss(
        config["clip_param"], config["vf_clip_param"],
        config["vf_loss_coeff"], config["entropy_coeff"],
    )


class APPOConfig(ImpalaConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = APPO
        self._config.update(
            _loss_factory=_appo_loss_factory,
            clip_param=0.3,
            vf_clip_param=10.0,
            num_sgd_iter=1,        # async batches go stale fast
            minibatch_size=128,
            # how many completed worker batches one training_step consumes
            # (1 = train the moment anything lands; higher amortizes the
            # device step over more data)
            batches_per_step=1,
        )


class APPO(Impala):
    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        # ref -> remote worker with that sample() in flight
        self._inflight: Dict[Any, Any] = {}
        self._weights_ref = None

    def _arm(self, worker) -> None:
        """Push current weights to ``worker`` and start its next sample —
        both fire-and-forget; the actor's FIFO runs them in order."""
        if self._weights_ref is None:
            self._weights_ref = ray_tpu.put(
                self.workers.local_worker.get_weights())
        worker.set_weights.remote(self._weights_ref)
        self._inflight[worker.sample.remote()] = worker

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        workers = self.workers.remote_workers
        if not workers:
            # no async seats: degrade to the synchronous IMPALA step with
            # the APPO loss (still V-trace-corrected)
            return super().training_step()

        self._weights_ref = None  # re-snapshot once per training step
        for w in workers:
            if w not in self._inflight.values():
                self._arm(w)
        batches = []
        want = max(1, int(cfg.get("batches_per_step", 1)))
        while len(batches) < want:
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1)
            ref = ready[0]
            worker = self._inflight.pop(ref)
            try:
                batches.append(ray_tpu.get(ref))
            except Exception:
                # worker died mid-sample: it restarts via max_restarts;
                # re-arm and keep learning off the others
                pass
            self._arm(worker)  # overlap: next sample runs during our SGD
        batch = SampleBatch.concat_samples(batches)
        self._timesteps_total += batch.count
        train_batch = self._vtrace_batch(batch)
        learner_metrics = train_one_step(
            self.workers.local_worker.policy,
            train_batch,
            num_sgd_iter=cfg["num_sgd_iter"],
            sgd_minibatch_size=cfg["minibatch_size"],
            rng=self._sgd_rng,
            required_keys=(
                SampleBatch.OBS, SampleBatch.ACTIONS,
                SampleBatch.ACTION_LOGP, SampleBatch.ADVANTAGES,
                SampleBatch.VALUE_TARGETS,
            ),
        )
        return {"info": {"learner": learner_metrics}}

    def cleanup(self) -> None:
        # cancel in-flight samples so worker actors die promptly
        for ref in list(self._inflight):
            try:
                ray_tpu.cancel(ref, force=True)
            except Exception:
                pass
        self._inflight.clear()
        super().cleanup()


APPO._default_config = APPOConfig().to_dict()
