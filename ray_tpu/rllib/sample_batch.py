"""SampleBatch: the columnar container for rollout data.

Analog of ``/root/reference/rllib/policy/sample_batch.py:125`` — a dict of
equal-length numpy arrays with the standard column names, concat/slice/
shuffle/minibatch utilities.  Columns stay numpy on the host; they are
shipped to the device once per SGD epoch as a single batched transfer
(TPU-friendly: no per-step host<->device chatter).
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np


class SampleBatch(dict):
    OBS = "obs"
    NEXT_OBS = "new_obs"
    ACTIONS = "actions"
    REWARDS = "rewards"
    TERMINATEDS = "terminateds"
    TRUNCATEDS = "truncateds"
    ACTION_LOGP = "action_logp"
    VF_PREDS = "vf_preds"
    ADVANTAGES = "advantages"
    VALUE_TARGETS = "value_targets"
    EPS_ID = "eps_id"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if not isinstance(v, np.ndarray):
                self[k] = np.asarray(v)

    def __len__(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @property
    def count(self) -> int:
        return len(self)

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch({
            k: np.concatenate([b[k] for b in batches], axis=0) for k in keys
        })

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def shuffle(self, rng: np.random.Generator) -> "SampleBatch":
        perm = rng.permutation(len(self))
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def minibatches(self, minibatch_size: int, rng: np.random.Generator) -> Iterator["SampleBatch"]:
        """Shuffled fixed-size minibatches (drops the ragged tail so every
        jitted SGD step sees one static shape — no XLA recompiles)."""
        shuffled = self.shuffle(rng)
        n = len(shuffled)
        for start in range(0, n - minibatch_size + 1, minibatch_size):
            yield shuffled.slice(start, start + minibatch_size)

    def as_arrays(self) -> Dict[str, np.ndarray]:
        return dict(self)
