"""Built-in benchmark environments.

The reference's Atari suite arrives through gym[atari] + wrappers
(``/root/reference/rllib/env/wrappers/atari_wrappers.py:244`` — the
84x84x4 ``wrap_deepmind`` stack).  Emulated ROMs aren't available here, so
the north-star "PPO Atari env-steps/s" (BASELINE config 4) is measured on
:class:`SyntheticAtariEnv`: the exact observation/action interface and
per-step host cost profile of a wrapped Atari env (uint8 [84, 84, 4]
frames, 6 discrete actions, episodic resets) with deterministic synthetic
dynamics — a moving sprite whose position the agent is rewarded for
tracking, so policies CAN learn and reward curves move.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np


class _Box:
    """Minimal observation-space shim (gymnasium.spaces.Box interface
    subset the framework reads: shape + dtype)."""

    def __init__(self, shape, dtype):
        self.shape = tuple(shape)
        self.dtype = dtype


class _Discrete:
    def __init__(self, n):
        self.n = int(n)


class SyntheticAtariEnv:
    """Atari-shaped synthetic env: obs uint8 [84, 84, 4], 6 actions.

    Dynamics: a bright 6x6 sprite drifts horizontally across a textured
    background; actions 0..5 name the horizontal sixth of the screen the
    agent believes the sprite occupies.  Reward 1 for a correct call, 0
    otherwise.  Episodes end (terminated) after ``episode_len`` steps.
    Frame stacking is emulated by rolling the channel axis each step, as
    the DeepMind wrapper does with its frame deque.
    """

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        config = dict(config or {})
        self.h = int(config.get("height", 84))
        self.w = int(config.get("width", 84))
        self.episode_len = int(config.get("episode_len", 400))
        self.observation_space = _Box((self.h, self.w, 4), np.uint8)
        self.action_space = _Discrete(6)
        self._rng = np.random.default_rng(0)
        self._frame = np.zeros((self.h, self.w, 4), np.uint8)
        self._background = np.zeros((self.h, self.w), np.uint8)
        self._t = 0
        self._x = 0
        self._dx = 1

    # -- gym API --------------------------------------------------------
    def reset(self, *, seed: Optional[int] = None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        # a fixed per-episode texture so frames aren't trivially blank
        self._background = (
            self._rng.integers(0, 48, (self.h, self.w)).astype(np.uint8))
        self._t = 0
        self._x = int(self._rng.integers(0, self.w - 6))
        self._dx = int(self._rng.choice((-2, -1, 1, 2)))
        self._frame[:] = 0
        for c in range(4):
            self._render(c)
        return self._frame.copy(), {}

    def _render(self, channel: int) -> None:
        f = self._frame[:, :, channel]
        f[:] = self._background
        y = self.h // 2 - 3
        f[y:y + 6, self._x:self._x + 6] = 255

    def step(self, action) -> Tuple[np.ndarray, float, bool, bool, Dict]:
        self._t += 1
        self._x += self._dx
        if self._x <= 0 or self._x >= self.w - 6:
            self._dx = -self._dx
            self._x = max(0, min(self.w - 6, self._x))
        # stack roll: oldest channel becomes the new frame
        self._frame = np.roll(self._frame, -1, axis=2)
        self._render(3)
        sixth = min(5, self._x * 6 // self.w)
        reward = 1.0 if int(action) == sixth else 0.0
        terminated = self._t >= self.episode_len
        return self._frame.copy(), reward, terminated, False, {}


def synthetic_atari_creator(env_config: Dict[str, Any]) -> SyntheticAtariEnv:
    """``env_creator`` hook for AlgorithmConfig.environment()."""
    return SyntheticAtariEnv(env_config)
