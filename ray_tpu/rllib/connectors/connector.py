"""Connector core: typed env<->policy transform pipelines.

Analog of ``/root/reference/rllib/connectors/connector.py:84,142,271``
(Connector / AgentConnector / ActionConnector + ConnectorPipeline): the
preprocessing that used to be hardwired into ``rollout_worker.py``
(flatten/cast on the observation path, unsquash/clip on the action path)
becomes a pipeline of small composable transforms that

- is THE sample path (RolloutWorker owns one agent pipeline and one
  action pipeline; there is no parallel hardwired path),
- serializes (``to_state``/``from_state`` through a name registry), so
  stateful transforms like running-stat normalization ride checkpoints
  and pickle cleanly through config dicts to remote rollout workers and
  the PolicyServer inference path,
- carries per-env episode state (frame stacks) keyed by ``env_id`` with
  an explicit ``reset(env_id)`` at episode boundaries.

``training=False`` transforms without updating persistent statistics
(the evaluation / single-obs inference path); per-env episode state is
NOT gated by it — a frame stack must track the episode it is in either
way.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class ConnectorContext:
    """What a connector may need to size itself: the env's spaces (as
    plain shapes/bounds so contexts pickle without gym), plus the
    algorithm config for free-form knobs.

    ``from_env`` probes a live (gymnasium-like) env; workers build one at
    construction and hand it to every connector they instantiate.
    """

    obs_shape: Tuple[int, ...] = ()
    obs_dim: int = 0
    num_actions: int = 0
    discrete: bool = True
    action_low: Optional[np.ndarray] = None
    action_high: Optional[np.ndarray] = None
    config: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_env(cls, env, config: Optional[Dict[str, Any]] = None
                 ) -> "ConnectorContext":
        obs_shape = tuple(env.observation_space.shape)
        space = env.action_space
        discrete = hasattr(space, "n")
        if discrete:
            num_actions, low, high = int(space.n), None, None
        else:
            num_actions = int(np.prod(space.shape))
            low = np.asarray(space.low, np.float32)
            high = np.asarray(space.high, np.float32)
        return cls(obs_shape=obs_shape, obs_dim=int(np.prod(obs_shape)),
                   num_actions=num_actions, discrete=discrete,
                   action_low=low, action_high=high,
                   config=dict(config or {}))


# ---------------------------------------------------------------------------
# registry: connector NAME -> class, so pipeline state is restorable
# across processes without pickling classes (``register_connector`` in the
# reference's connector.py)
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, type] = {}


def register_connector(name: str, cls: type) -> None:
    """Register a connector class under a stable name (custom connectors
    call this once at import time so ``from_state`` can rebuild them)."""
    _REGISTRY[name] = cls


def get_connector_class(name: str) -> type:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown connector {name!r}; custom connectors must be "
            f"register_connector()'d before restoring a pipeline "
            f"(known: {sorted(_REGISTRY)})") from None


class Connector:
    """One transform step.  Subclasses set ``NAME``, implement
    ``__call__``, and override ``to_state``/``from_state`` when they carry
    constructor params or learned statistics."""

    NAME = "connector"

    def __call__(self, x, env_id: Any = 0, training: bool = True):
        raise NotImplementedError

    def reset(self, env_id: Any = None) -> None:
        """Drop per-env episode state (``env_id=None`` drops all)."""

    # -- serialization --------------------------------------------------
    def to_state(self) -> Tuple[str, Dict[str, Any]]:
        return self.NAME, {}

    @classmethod
    def from_state(cls, ctx: ConnectorContext,
                   params: Dict[str, Any]) -> "Connector":
        return cls(ctx, **params) if _wants_ctx(cls) else cls(**params)


def _wants_ctx(cls: type) -> bool:
    """Connector constructors take (ctx, **params) or just (**params);
    sniff once so both styles restore through the same ``from_state``."""
    import inspect

    try:
        sig = inspect.signature(cls.__init__)
    except (TypeError, ValueError):
        return False
    params = [p for n, p in sig.parameters.items() if n != "self"]
    return bool(params) and params[0].name == "ctx"


class AgentConnector(Connector):
    """Observation-path transform: raw env obs -> policy input.  Stateful
    subclasses key episode state by ``env_id`` and honor ``reset``."""

    NAME = "agent_connector"


class ActionConnector(Connector):
    """Action-path transform: policy output -> what ``env.step`` accepts.
    Stateless by convention (actions carry no episode state)."""

    NAME = "action_connector"


# ---------------------------------------------------------------------------
# pipelines
# ---------------------------------------------------------------------------


class ConnectorPipeline:
    """Ordered composition; applies left to right.  ``to_state`` captures
    the full recipe (names + per-connector params/statistics) as plain
    dicts/arrays, so it pickles, rides checkpoints, and restores through
    the registry on any process."""

    def __init__(self, ctx: ConnectorContext,
                 connectors: Sequence[Connector] = ()):
        self.ctx = ctx
        self.connectors: List[Connector] = list(connectors)

    def __call__(self, x, env_id: Any = 0, training: bool = True):
        for c in self.connectors:
            x = c(x, env_id=env_id, training=training)
        return x

    def reset(self, env_id: Any = None) -> None:
        for c in self.connectors:
            c.reset(env_id)

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.insert(0, connector)
        return self

    def __len__(self) -> int:
        return len(self.connectors)

    def __repr__(self) -> str:
        names = " -> ".join(c.NAME for c in self.connectors) or "identity"
        return f"{type(self).__name__}({names})"

    # -- distributed running-stat sync ---------------------------------
    # Stats-only (never touches per-env episode state like frame stacks):
    # remote workers pop Welford deltas, the learner folds them in and
    # broadcasts merged statistics back.  Entries align positionally with
    # ``connectors``; stateless connectors contribute None.
    def pop_stat_deltas(self) -> List[Any]:
        return [c.pop_sync_delta() if hasattr(c, "pop_sync_delta") else None
                for c in self.connectors]

    def apply_stat_deltas(self, deltas: Sequence[Any]) -> None:
        for c, d in zip(self.connectors, deltas or ()):
            if d is not None and hasattr(c, "apply_sync_delta"):
                c.apply_sync_delta(d)

    def get_stat_states(self) -> List[Any]:
        return [c.get_sync_state() if hasattr(c, "get_sync_state") else None
                for c in self.connectors]

    def set_stat_states(self, states: Sequence[Any]) -> None:
        for c, s in zip(self.connectors, states or ()):
            if s is not None and hasattr(c, "set_sync_state"):
                c.set_sync_state(s)

    # -- serialization --------------------------------------------------
    def to_state(self) -> List[Tuple[str, Dict[str, Any]]]:
        return [c.to_state() for c in self.connectors]

    @classmethod
    def from_state(cls, ctx: ConnectorContext,
                   state: Sequence[Tuple[str, Dict[str, Any]]]
                   ) -> "ConnectorPipeline":
        return cls(ctx, [
            get_connector_class(name).from_state(ctx, dict(params))
            for name, params in state
        ])

    def set_state(self, state: Sequence[Tuple[str, Dict[str, Any]]]) -> None:
        """In-place restore (checkpoint load): rebuild the connector list
        from ``state`` under the pipeline's own ctx."""
        self.connectors = type(self).from_state(self.ctx, state).connectors


class AgentConnectorPipeline(ConnectorPipeline):
    """The observation path."""


class ActionConnectorPipeline(ConnectorPipeline):
    """The action path.  Calls ignore env state by convention, but the
    signature stays uniform so pipelines compose the same way."""


# spec: what configs may carry under "agent_connectors"/"action_connectors"
# — instances, (name, kwargs) pairs, or a factory over the ctx
ConnectorSpec = Any


def build_pipeline(pipeline_cls, ctx: ConnectorContext,
                   spec: ConnectorSpec) -> ConnectorPipeline:
    """Materialize a pipeline from a config spec:

    - ``None``: empty pipeline (callers install defaults),
    - a callable: ``spec(ctx) -> sequence of connectors``,
    - a sequence of connector instances and/or ``(name, kwargs)`` pairs
      (the picklable form configs should prefer — instances with learned
      state ship their state, pairs rebuild fresh through the registry).
    """
    if spec is None:
        return pipeline_cls(ctx, [])
    if callable(spec):
        return pipeline_cls(ctx, list(spec(ctx)))
    connectors: List[Connector] = []
    for item in spec:
        if isinstance(item, Connector):
            connectors.append(item)
        elif isinstance(item, (tuple, list)) and len(item) == 2 \
                and isinstance(item[0], str):
            connectors.append(
                get_connector_class(item[0]).from_state(ctx, dict(item[1])))
        else:
            raise TypeError(
                f"connector spec items must be Connector instances or "
                f"(name, kwargs) pairs, got {item!r}")
    return pipeline_cls(ctx, connectors)
