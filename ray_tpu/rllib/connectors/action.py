"""Action (policy-output -> env) connector library.

The composable version of what ``rollout_worker._env_action`` hardwired:
continuous policies act in the canonical [-1, 1] box (tanh squash) and
the connector rescales to the env's bounds; discrete policies emit array
scalars the env wants as ints.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rllib.connectors.connector import (
    ActionConnector,
    ConnectorContext,
    register_connector,
)


class DiscreteAction(ActionConnector):
    """Policy's array scalar -> plain int (what discrete envs accept)."""

    NAME = "discrete_action"

    def __call__(self, a, env_id: Any = 0, training: bool = True):
        return int(a)


class UnsquashAction(ActionConnector):
    """Canonical [-1, 1] action -> the env's finite Box bounds, so
    full-range actions are reachable.  ``squash`` is the exact inverse
    (offline data recorded in env units re-enters policy space with it)."""

    NAME = "unsquash_action"

    def __init__(self, ctx: Optional[ConnectorContext] = None,
                 low=None, high=None):
        low = ctx.action_low if low is None and ctx is not None else low
        high = ctx.action_high if high is None and ctx is not None else high
        if low is None or high is None:
            raise ValueError("UnsquashAction needs bounds (ctx or low/high)")
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, a, env_id: Any = 0, training: bool = True):
        a = np.clip(np.asarray(a, np.float32), -1.0, 1.0)
        return self.low + (a + 1.0) * (self.high - self.low) / 2.0

    def squash(self, x) -> np.ndarray:
        """Env units -> canonical [-1, 1] (inverse of ``__call__``)."""
        x = np.asarray(x, np.float32)
        return np.clip(
            2.0 * (x - self.low) / (self.high - self.low) - 1.0, -1.0, 1.0)

    def to_state(self) -> Tuple[str, Dict[str, Any]]:
        return self.NAME, {"low": self.low.copy(), "high": self.high.copy()}


class ClipAction(ActionConnector):
    """Clip to bounds — the fallback when a bound is infinite and
    rescaling is undefined."""

    NAME = "clip_action"

    def __init__(self, ctx: Optional[ConnectorContext] = None,
                 low=None, high=None):
        low = ctx.action_low if low is None and ctx is not None else low
        high = ctx.action_high if high is None and ctx is not None else high
        if low is None or high is None:
            raise ValueError("ClipAction needs bounds (ctx or low/high)")
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, a, env_id: Any = 0, training: bool = True):
        return np.clip(np.asarray(a, np.float32), self.low, self.high)

    def to_state(self) -> Tuple[str, Dict[str, Any]]:
        return self.NAME, {"low": self.low.copy(), "high": self.high.copy()}


def default_action_connectors(ctx: ConnectorContext):
    """What the hardwired ``_env_action`` used to do, as a pipeline."""
    if ctx.discrete:
        return [DiscreteAction()]
    if (ctx.action_low is not None and ctx.action_high is not None
            and np.all(np.isfinite(ctx.action_low))
            and np.all(np.isfinite(ctx.action_high))):
        return [UnsquashAction(low=ctx.action_low, high=ctx.action_high)]
    return [ClipAction(low=ctx.action_low, high=ctx.action_high)]


register_connector(DiscreteAction.NAME, DiscreteAction)
register_connector(UnsquashAction.NAME, UnsquashAction)
register_connector(ClipAction.NAME, ClipAction)
