"""Agent (observation-path) connector library.

The composable versions of what ``rollout_worker._prep_obs`` hardwired,
plus the two stateful transforms the hardwired path could never express:
running-stat normalization (the reference's ``MeanStdFilter``) and frame
stacking with episode-boundary resets.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rllib.connectors.connector import (
    AgentConnector,
    ConnectorContext,
    register_connector,
)


class FlattenObs(AgentConnector):
    """Flat float32 vector — the MLP policy's input contract.  Always
    produces a fresh array, so envs that hand out their internal buffers
    never alias stored sample rows."""

    NAME = "flatten_obs"

    def __call__(self, x, env_id: Any = 0, training: bool = True):
        # np.array (not asarray): already-flat contiguous float32 input
        # would come back as a VIEW of the env's buffer otherwise
        return np.array(x, np.float32).reshape(-1)


class CastObs(AgentConnector):
    """Copy (and optionally cast) keeping the array's shape — the CNN
    path, where uint8 pixels must stay uint8 ([H, W, C] layout) so
    transport ships 1-byte pixels and the model casts device-side."""

    NAME = "cast_obs"

    def __init__(self, dtype: Optional[str] = None):
        self.dtype = np.dtype(dtype).name if dtype is not None else None

    def __call__(self, x, env_id: Any = 0, training: bool = True):
        return np.array(x, dtype=self.dtype)

    def to_state(self) -> Tuple[str, Dict[str, Any]]:
        return self.NAME, {"dtype": self.dtype}


class NormalizeObs(AgentConnector):
    """Running mean/std normalization (``MeanStdFilter`` analog).

    Welford accumulators in float64 so the statistics — and therefore the
    transformed observations — are bit-stable under a ``to_state`` /
    ``from_state`` round trip mid-stream.  ``training=False`` normalizes
    with frozen statistics (evaluation / serving inference)."""

    NAME = "normalize_obs"

    def __init__(self, clip: float = 10.0, eps: float = 1e-8,
                 n: int = 0, mean=None, m2=None):
        self.clip = float(clip)
        self.eps = float(eps)
        self._n = int(n)
        self._mean = None if mean is None else np.asarray(mean, np.float64)
        self._m2 = None if m2 is None else np.asarray(m2, np.float64)
        # accumulation since the last ``pop_sync_delta`` — the worker half
        # of distributed filter sync (FilterManager.synchronize analog)
        self._dn = 0
        self._dmean = None
        self._dm2 = None

    @staticmethod
    def _welford(n: int, mean, m2, x: np.ndarray):
        if mean is None:
            mean = np.zeros(x.shape, np.float64)
            m2 = np.zeros(x.shape, np.float64)
        n += 1
        delta = x - mean
        mean = mean + delta / n
        m2 = m2 + delta * (x - mean)
        return n, mean, m2

    def _update(self, x: np.ndarray) -> None:
        self._n, self._mean, self._m2 = self._welford(
            self._n, self._mean, self._m2, x)
        self._dn, self._dmean, self._dm2 = self._welford(
            self._dn, self._dmean, self._dm2, x)

    # -- distributed running-stat sync ---------------------------------
    def pop_sync_delta(self):
        """Statistics accumulated since the last pop (None if nothing new);
        clears the buffer.  Remote workers are polled with this so their
        counts can be folded into the learner's filter."""
        if self._dn == 0:
            return None
        d = {"n": self._dn, "mean": self._dmean, "m2": self._dm2}
        self._dn, self._dmean, self._dm2 = 0, None, None
        return d

    def apply_sync_delta(self, d) -> None:
        """Fold a worker's delta in (Chan et al. parallel Welford merge)."""
        nb = int(d["n"])
        bmean = np.asarray(d["mean"], np.float64)
        bm2 = np.asarray(d["m2"], np.float64)
        if self._mean is None:
            self._n, self._mean, self._m2 = nb, bmean.copy(), bm2.copy()
            return
        na = self._n
        n = na + nb
        delta = bmean - self._mean
        self._mean = self._mean + delta * (nb / n)
        self._m2 = self._m2 + bm2 + delta * delta * (na * nb / n)
        self._n = n

    def get_sync_state(self):
        return {"n": self._n, "mean": self._mean, "m2": self._m2}

    def set_sync_state(self, s) -> None:
        """Replace statistics with the learner's merged copy (broadcast
        half of the sync); the delta buffer restarts empty."""
        self._n = int(s["n"])
        self._mean = (None if s["mean"] is None
                      else np.asarray(s["mean"], np.float64).copy())
        self._m2 = (None if s["m2"] is None
                    else np.asarray(s["m2"], np.float64).copy())
        self._dn, self._dmean, self._dm2 = 0, None, None

    def __call__(self, x, env_id: Any = 0, training: bool = True):
        x = np.asarray(x, np.float64)
        if training:
            self._update(x)
        if self._n < 2:
            return np.asarray(np.clip(x, -self.clip, self.clip), np.float32)
        std = np.sqrt(self._m2 / (self._n - 1)) + self.eps
        out = np.clip((x - self._mean) / std, -self.clip, self.clip)
        return np.asarray(out, np.float32)

    def to_state(self) -> Tuple[str, Dict[str, Any]]:
        return self.NAME, {
            "clip": self.clip, "eps": self.eps, "n": self._n,
            "mean": None if self._mean is None else self._mean.copy(),
            "m2": None if self._m2 is None else self._m2.copy(),
        }


class FrameStackObs(AgentConnector):
    """Stack the last ``num_frames`` observations along the last axis,
    per env: flat [D] obs become [D * k], image [H, W, C] obs become
    [H, W, C * k] (the DeepMind channel-stack).  The first observation of
    an episode is repeated k times (the wrapper-deque reset semantic);
    ``reset(env_id)`` at the episode boundary is what makes that happen —
    stacks never leak across episodes.

    Episode buffers are transient by design and do NOT serialize: a
    restored pipeline starts with empty stacks, exactly like a freshly
    reset env."""

    NAME = "frame_stack_obs"

    def __init__(self, num_frames: int = 4):
        self.num_frames = int(num_frames)
        self._frames: Dict[Any, list] = {}

    def __call__(self, x, env_id: Any = 0, training: bool = True):
        # copy: an env may mutate and re-return one internal obs buffer,
        # which would alias every buffered frame (the old hardwired prep
        # copied too; upstream connectors usually do, but this connector
        # can be FIRST in an explicit pipeline)
        x = np.array(x, copy=True)
        buf = self._frames.get(env_id)
        if buf is None:
            buf = self._frames[env_id] = []
        buf.append(x)
        del buf[:-self.num_frames]
        frames = [buf[0]] * (self.num_frames - len(buf)) + buf
        return np.concatenate(frames, axis=-1)

    def reset(self, env_id: Any = None) -> None:
        if env_id is None:
            self._frames.clear()
        else:
            self._frames.pop(env_id, None)

    def to_state(self) -> Tuple[str, Dict[str, Any]]:
        return self.NAME, {"num_frames": self.num_frames}


class ClipObs(AgentConnector):
    """Elementwise clip — cheap guard for envs with unbounded spikes."""

    NAME = "clip_obs"

    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = float(low), float(high)

    def __call__(self, x, env_id: Any = 0, training: bool = True):
        return np.clip(np.asarray(x, np.float32), self.low, self.high)

    def to_state(self) -> Tuple[str, Dict[str, Any]]:
        return self.NAME, {"low": self.low, "high": self.high}


def default_agent_connectors(ctx: ConnectorContext, conv: bool):
    """What the hardwired ``_prep_obs`` used to do, as a pipeline: image
    observations for a conv-bearing policy keep [H, W, C] uint8; flat
    observations flatten to float32."""
    return [CastObs()] if conv else [FlattenObs()]


register_connector(FlattenObs.NAME, FlattenObs)
register_connector(CastObs.NAME, CastObs)
register_connector(NormalizeObs.NAME, NormalizeObs)
register_connector(FrameStackObs.NAME, FrameStackObs)
register_connector(ClipObs.NAME, ClipObs)
