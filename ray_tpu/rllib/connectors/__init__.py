"""ray_tpu.rllib.connectors — composable env<->policy transform pipelines.

The reference separates env->policy preprocessing into connectors
(``rllib/connectors/``); this package is the minimal-but-real cut:
``AgentConnector`` pipelines on the observation path, ``ActionConnector``
pipelines on the action path, a ``ConnectorContext`` carrying spaces and
config, a transform library (flatten / cast / running-stat normalize /
frame-stack / clip / unsquash), and ``to_state``/``from_state``
serialization through a name registry so pipelines ride checkpoints and
pickle through configs to remote workers and the PolicyServer.

Configs opt in through ``AlgorithmConfig.connectors(...)``; with no spec
the worker installs defaults equivalent to the old hardwired path.
"""

from ray_tpu.rllib.connectors.action import (
    ClipAction,
    DiscreteAction,
    UnsquashAction,
    default_action_connectors,
)
from ray_tpu.rllib.connectors.agent import (
    CastObs,
    ClipObs,
    FlattenObs,
    FrameStackObs,
    NormalizeObs,
    default_agent_connectors,
)
from ray_tpu.rllib.connectors.connector import (
    ActionConnector,
    ActionConnectorPipeline,
    AgentConnector,
    AgentConnectorPipeline,
    Connector,
    ConnectorContext,
    ConnectorPipeline,
    build_pipeline,
    get_connector_class,
    register_connector,
)

__all__ = [
    "Connector",
    "AgentConnector",
    "ActionConnector",
    "ConnectorContext",
    "ConnectorPipeline",
    "AgentConnectorPipeline",
    "ActionConnectorPipeline",
    "build_pipeline",
    "register_connector",
    "get_connector_class",
    "FlattenObs",
    "CastObs",
    "NormalizeObs",
    "FrameStackObs",
    "ClipObs",
    "DiscreteAction",
    "UnsquashAction",
    "ClipAction",
    "default_agent_connectors",
    "default_action_connectors",
]
