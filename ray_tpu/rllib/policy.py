"""JaxPolicy: params + jitted action sampling and SGD update.

Analog of ``/root/reference/rllib/policy/policy.py:161`` (the per-agent
compute_actions / learn_on_batch surface of ``TorchPolicyV2``,
``torch_policy_v2.py:62``) on the jax substrate: everything that touches
the accelerator is a pure jitted function over a params pytree, so the same
policy runs on CPU workers for rollouts and on TPU for learner SGD.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.models import apply_model, init_actor_critic, init_conv_actor_critic


class JaxPolicy:
    def __init__(
        self,
        obs_dim: int,
        num_actions: int,
        *,
        lr: float = 5e-4,
        hiddens=(64, 64),
        seed: int = 0,
        loss_fn: Optional[Callable] = None,
        grad_clip: Optional[float] = 0.5,
        obs_shape: Optional[tuple] = None,
    ):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self._rng = jax.random.PRNGKey(seed)
        if obs_shape is not None and len(obs_shape) == 3:
            # image observations -> CNN (the ModelCatalog conv path); the
            # caller's hiddens become the post-conv dense trunk
            self.params = init_conv_actor_critic(
                jax.random.PRNGKey(seed + 1), tuple(obs_shape), num_actions,
                hiddens=tuple(hiddens),
            )
        else:
            self.params = init_actor_critic(
                jax.random.PRNGKey(seed + 1), obs_dim, num_actions, hiddens
            )
        tx = [optax.clip_by_global_norm(grad_clip)] if grad_clip else []
        self.optimizer = optax.chain(*tx, optax.adam(lr))
        self.opt_state = self.optimizer.init(self.params)
        self._loss_fn = loss_fn  # (params, batch_dict) -> (loss, metrics)

        @jax.jit
        def _sample(params, rng, obs):
            logits, value = apply_model(params, obs)
            action = jax.random.categorical(rng, logits, axis=-1)
            logp = jax.nn.log_softmax(logits)
            action_logp = jnp.take_along_axis(logp, action[:, None], axis=-1)[:, 0]
            return action, action_logp, value

        @jax.jit
        def _value(params, obs):
            _, value = apply_model(params, obs)
            return value

        @jax.jit
        def _greedy(params, obs):
            logits, _ = apply_model(params, obs)
            return jnp.argmax(logits, axis=-1)

        @jax.jit
        def _action_logp(params, obs, actions):
            logits, _ = apply_model(params, obs)
            logp = jax.nn.log_softmax(logits)
            return jnp.take_along_axis(
                logp, actions.astype(jnp.int32)[:, None], axis=-1
            )[:, 0]

        self._sample_jit = _sample
        self._value_jit = _value
        self._greedy_jit = _greedy
        self._action_logp_jit = _action_logp
        self._update_jit = None
        if loss_fn is not None:

            @jax.jit
            def _update(params, opt_state, batch):
                (loss, metrics), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params, batch)
                updates, opt_state = self.optimizer.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return params, opt_state, loss, metrics

            self._update_jit = _update

    # -- acting --------------------------------------------------------
    def compute_actions(self, obs: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """obs [B, D] -> (actions, action_logp, vf_preds), all numpy."""
        self._rng, key = jax.random.split(self._rng)
        a, lp, v = self._sample_jit(self.params, key, jnp.asarray(obs))
        return np.asarray(a), np.asarray(lp), np.asarray(v)

    def value(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(self._value_jit(self.params, jnp.asarray(obs)))

    def greedy_action(self, obs: np.ndarray) -> np.ndarray:
        """Deterministic action (evaluation / explore=False path)."""
        return np.asarray(self._greedy_jit(self.params, jnp.asarray(obs)))

    def action_logp(self, obs: np.ndarray, actions: np.ndarray) -> np.ndarray:
        """Current-policy log-prob of given actions (V-trace ratios)."""
        return np.asarray(
            self._action_logp_jit(self.params, jnp.asarray(obs), jnp.asarray(actions))
        )

    # -- learning ------------------------------------------------------
    def learn_on_minibatch(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if self._update_jit is None:
            raise RuntimeError("policy constructed without a loss_fn")
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, loss, metrics = self._update_jit(
            self.params, self.opt_state, jb
        )
        out = {"total_loss": float(loss)}
        out.update({k: float(v) for k, v in metrics.items()})
        return out

    # -- weights -------------------------------------------------------
    def get_weights(self) -> Any:
        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)

    def get_state(self) -> Dict[str, Any]:
        """Weights + optimizer moments, so a restored learner resumes with
        the exact Adam state (not zeroed moments)."""
        return {
            "weights": self.get_weights(),
            "opt_state": jax.tree_util.tree_map(np.asarray, self.opt_state),
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.set_weights(state["weights"])
        if state.get("opt_state") is not None:
            self.opt_state = jax.tree_util.tree_map(
                jnp.asarray, state["opt_state"]
            )
