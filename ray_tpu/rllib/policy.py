"""JaxPolicy: params + jitted action sampling and SGD update.

Analog of ``/root/reference/rllib/policy/policy.py:161`` (the per-agent
compute_actions / learn_on_batch surface of ``TorchPolicyV2``,
``torch_policy_v2.py:62``) on the jax substrate: everything that touches
the accelerator is a pure jitted function over a params pytree, so the same
policy runs on CPU workers for rollouts and on TPU for learner SGD.

The network itself lives behind the :class:`~ray_tpu.rllib.rl_module.
RLModule` plugin surface: the policy owns sampling rng, the optimizer, and
weight currency, and routes every forward — exploration sampling, value
bootstraps, greedy inference, and the algorithm losses — through the
module's ``forward_exploration`` / ``forward_train`` / ``forward_inference``.
Custom JAX models plug in by passing ``module=`` (or the
``config.rl_module(factory)`` seam) without subclassing this class.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.rl_module import Columns, DefaultActorCriticModule, RLModule


def bind_loss(loss_fn: Callable, module: RLModule) -> Callable:
    """Normalize a loss to ``(params, batch)``.

    In-repo loss factories produce ``loss(module, params, batch)`` so the
    forward goes through the RLModule; two-arg ``loss(params, batch)``
    closures (pre-module custom losses) still work unchanged.
    """
    try:
        n = len([p for p in inspect.signature(loss_fn).parameters.values()
                 if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)])
    except (TypeError, ValueError):
        n = 2
    if n >= 3:
        return lambda params, batch: loss_fn(module, params, batch)
    return loss_fn


class JaxPolicy:
    def __init__(
        self,
        obs_dim: int,
        num_actions: int,
        *,
        lr: float = 5e-4,
        hiddens=(64, 64),
        seed: int = 0,
        loss_fn: Optional[Callable] = None,
        grad_clip: Optional[float] = 0.5,
        obs_shape: Optional[tuple] = None,
        module: Optional[RLModule] = None,
    ):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self._rng = jax.random.PRNGKey(seed)
        if module is None:
            # catalog default: MLP, or CNN when an image obs_shape is given
            module = DefaultActorCriticModule(
                obs_dim, num_actions, hiddens=tuple(hiddens),
                obs_shape=obs_shape if obs_shape and len(obs_shape) == 3
                else None)
        self.module = module
        self.params = module.init(jax.random.PRNGKey(seed + 1))
        tx = [optax.clip_by_global_norm(grad_clip)] if grad_clip else []
        self.optimizer = optax.chain(*tx, optax.adam(lr))
        self.opt_state = self.optimizer.init(self.params)
        self._loss_fn = bind_loss(loss_fn, module) if loss_fn else None

        @jax.jit
        def _sample(params, rng, obs):
            out = module.forward_exploration(params, obs)
            logits = out[Columns.ACTION_DIST_INPUTS]
            value = out[Columns.VF_PREDS]
            action = jax.random.categorical(rng, logits, axis=-1)
            logp = jax.nn.log_softmax(logits)
            action_logp = jnp.take_along_axis(logp, action[:, None], axis=-1)[:, 0]
            return action, action_logp, value

        @jax.jit
        def _value(params, obs):
            return module.forward_train(params, obs)[Columns.VF_PREDS]

        @jax.jit
        def _greedy(params, obs):
            out = module.forward_inference(params, obs)
            return jnp.argmax(out[Columns.ACTION_DIST_INPUTS], axis=-1)

        @jax.jit
        def _action_logp(params, obs, actions):
            out = module.forward_train(params, obs)
            logp = jax.nn.log_softmax(out[Columns.ACTION_DIST_INPUTS])
            return jnp.take_along_axis(
                logp, actions.astype(jnp.int32)[:, None], axis=-1
            )[:, 0]

        self._sample_jit = _sample
        self._value_jit = _value
        self._greedy_jit = _greedy
        self._action_logp_jit = _action_logp
        self._update_jit = None
        if self._loss_fn is not None:
            bound_loss = self._loss_fn

            @jax.jit
            def _update(params, opt_state, batch):
                (loss, metrics), grads = jax.value_and_grad(
                    bound_loss, has_aux=True
                )(params, batch)
                updates, opt_state = self.optimizer.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return params, opt_state, loss, metrics

            self._update_jit = _update

    # -- acting --------------------------------------------------------
    def compute_actions(self, obs: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """obs [B, D] -> (actions, action_logp, vf_preds), all numpy."""
        self._rng, key = jax.random.split(self._rng)
        a, lp, v = self._sample_jit(self.params, key, jnp.asarray(obs))
        return np.asarray(a), np.asarray(lp), np.asarray(v)

    def value(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(self._value_jit(self.params, jnp.asarray(obs)))

    def greedy_action(self, obs: np.ndarray) -> np.ndarray:
        """Deterministic action (evaluation / explore=False path)."""
        return np.asarray(self._greedy_jit(self.params, jnp.asarray(obs)))

    def action_logp(self, obs: np.ndarray, actions: np.ndarray) -> np.ndarray:
        """Current-policy log-prob of given actions (V-trace ratios)."""
        return np.asarray(
            self._action_logp_jit(self.params, jnp.asarray(obs), jnp.asarray(actions))
        )

    # -- learning ------------------------------------------------------
    def learn_on_minibatch(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if self._update_jit is None:
            raise RuntimeError("policy constructed without a loss_fn")
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, loss, metrics = self._update_jit(
            self.params, self.opt_state, jb
        )
        out = {"total_loss": float(loss)}
        out.update({k: float(v) for k, v in metrics.items()})
        return out

    # -- weights -------------------------------------------------------
    def get_weights(self) -> Any:
        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)

    def get_state(self) -> Dict[str, Any]:
        """Weights + optimizer moments, so a restored learner resumes with
        the exact Adam state (not zeroed moments)."""
        return {
            "weights": self.get_weights(),
            "opt_state": jax.tree_util.tree_map(np.asarray, self.opt_state),
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.set_weights(state["weights"])
        if state.get("opt_state") is not None:
            self.opt_state = jax.tree_util.tree_map(
                jnp.asarray, state["opt_state"]
            )
