"""Offline IO: write rollouts to JSON-lines files, read them back.

Analog of ``/root/reference/rllib/offline/json_writer.py`` and
``json_reader.py:199``: each line is one SampleBatch with columns encoded
as nested lists + dtype tags (human-greppable, like the reference; numpy
round-trips exactly for float32/int64/bool).  ``config.output`` plugs the
writer into every RolloutWorker; a reader feeds replay-based algorithms
for offline training (``config.input``).
"""

from __future__ import annotations

import glob
import json
import os
from typing import Iterator, List, Optional

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


def _encode(batch: SampleBatch) -> str:
    payload = {}
    for k, v in batch.items():
        arr = np.asarray(v)
        payload[k] = {"data": arr.tolist(), "dtype": str(arr.dtype)}
    return json.dumps(payload)


def _decode(line: str) -> SampleBatch:
    payload = json.loads(line)
    return SampleBatch({
        k: np.asarray(spec["data"], dtype=np.dtype(spec["dtype"]))
        for k, spec in payload.items()
    })


class JsonWriter:
    """One ``output-worker_<i>-<n>.json`` file per worker, rolled at
    ``max_file_size`` bytes (``json_writer.py`` analog)."""

    def __init__(self, path: str, *, worker_index: int = 0,
                 max_file_size: int = 64 * 1024 * 1024):
        self._dir = path
        os.makedirs(path, exist_ok=True)
        self._worker = worker_index
        self._max_bytes = max_file_size
        # resume after existing files from a prior run of this worker so
        # the roll threshold accounts for bytes already on disk
        existing = sorted(
            glob.glob(os.path.join(path, f"output-worker_{worker_index}-*.json")),
            key=lambda p: int(p.rsplit("-", 1)[1].removesuffix(".json")),
        )
        if existing:
            last = existing[-1]
            self._file_idx = int(last.rsplit("-", 1)[1].removesuffix(".json"))
            self._bytes = os.path.getsize(last)
        else:
            self._file_idx = 0
            self._bytes = 0

    def _path(self) -> str:
        return os.path.join(
            self._dir, f"output-worker_{self._worker}-{self._file_idx}.json"
        )

    def write(self, batch: SampleBatch) -> None:
        line = _encode(batch)
        if self._bytes and self._bytes + len(line) > self._max_bytes:
            self._file_idx += 1
            self._bytes = 0
        with open(self._path(), "a") as f:
            f.write(line + "\n")
        self._bytes += len(line) + 1


class JsonReader:
    """Cycles through every ``*.json`` under a path, yielding SampleBatches
    (``json_reader.py:199`` analog — loops forever like the reference, so
    offline training can draw unlimited batches)."""

    def __init__(self, path: str):
        if os.path.isdir(path):
            self._files: List[str] = sorted(glob.glob(os.path.join(path, "*.json")))
        else:
            self._files = sorted(glob.glob(path))
        if not self._files:
            raise FileNotFoundError(f"no .json batch files under {path!r}")
        self._iter: Optional[Iterator[SampleBatch]] = None

    def _lines(self) -> Iterator[SampleBatch]:
        while True:  # cycle
            yielded = 0
            for fp in self._files:
                with open(fp) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            yielded += 1
                            yield _decode(line)
            if yielded == 0:
                raise RuntimeError(
                    f"offline input files contain no batches: {self._files}"
                )

    def next(self) -> SampleBatch:
        if self._iter is None:
            self._iter = self._lines()
        return next(self._iter)

    def read_all(self) -> SampleBatch:
        """Every batch in the files, concatenated once (no cycling)."""
        out = []
        for fp in self._files:
            with open(fp) as f:
                for line in f:
                    if line.strip():
                        out.append(_decode(line))
        return SampleBatch.concat_samples(out)
