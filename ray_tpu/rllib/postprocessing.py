"""GAE advantage estimation (``rllib/evaluation/postprocessing.py`` analog)."""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


def compute_gae(
    batch: SampleBatch,
    last_value: float,
    gamma: float = 0.99,
    lambda_: float = 0.95,
) -> SampleBatch:
    """Generalized Advantage Estimation over one trajectory fragment.

    ``last_value`` bootstraps the tail when the fragment was truncated
    mid-episode (0.0 if the episode terminated).  Adds ADVANTAGES and
    VALUE_TARGETS columns in place.

    Episode boundaries INSIDE the fragment are honored from the
    TERMINATEDS/TRUNCATEDS columns: a terminal step bootstraps nothing and
    cuts the GAE trace coming from the next episode's steps (we iterate
    backwards); a mid-fragment TRUNCATION (time-limit) also cuts the
    trace — the following rows belong to a different episode — but
    bootstraps with the value estimate at the truncated state instead of
    zero (the episode didn't end, the clock did).  The final state after
    truncation isn't in the batch, so its own value prediction stands in;
    the fragment's LAST row, when truncated, uses the caller-supplied
    ``last_value`` (the worker computed v(s_T) exactly).  Batches without
    a TRUNCATEDS column (hand-built unit fixtures) treat every step as
    not-truncated, the historical behavior.
    """
    rewards = batch[SampleBatch.REWARDS]
    values = batch[SampleBatch.VF_PREDS]
    terminateds = batch[SampleBatch.TERMINATEDS]
    truncateds = batch.get(SampleBatch.TRUNCATEDS)
    n = len(rewards)
    adv = np.zeros(n, dtype=np.float32)
    last_gae = 0.0
    next_value = last_value
    for t in range(n - 1, -1, -1):
        if terminateds[t]:
            # terminal: no bootstrap, cut the trace from the NEXT episode
            boot, trace = 0.0, 0.0
        elif truncateds is not None and truncateds[t]:
            # truncated: cut the trace, bootstrap with a value estimate —
            # last_value for the tail row (exact v(s_T)), the step's own
            # prediction mid-fragment (s_T isn't in the batch)
            boot, trace = (last_value if t == n - 1 else values[t]), 0.0
        else:
            boot, trace = next_value, 1.0
        delta = rewards[t] + gamma * boot - values[t]
        last_gae = delta + gamma * lambda_ * trace * last_gae
        adv[t] = last_gae
        next_value = values[t]
    batch[SampleBatch.ADVANTAGES] = adv
    batch[SampleBatch.VALUE_TARGETS] = (adv + values).astype(np.float32)
    return batch
