"""GAE advantage estimation (``rllib/evaluation/postprocessing.py`` analog)."""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


def compute_gae(
    batch: SampleBatch,
    last_value: float,
    gamma: float = 0.99,
    lambda_: float = 0.95,
) -> SampleBatch:
    """Generalized Advantage Estimation over one trajectory fragment.

    ``last_value`` bootstraps the tail when the fragment was truncated
    mid-episode (0.0 if the episode terminated).  Adds ADVANTAGES and
    VALUE_TARGETS columns in place.
    """
    rewards = batch[SampleBatch.REWARDS]
    values = batch[SampleBatch.VF_PREDS]
    terminateds = batch[SampleBatch.TERMINATEDS]
    n = len(rewards)
    adv = np.zeros(n, dtype=np.float32)
    last_gae = 0.0
    next_value = last_value
    for t in range(n - 1, -1, -1):
        # a terminal step bootstraps nothing and cuts the trace coming from
        # the NEXT episode's steps (we iterate backwards)
        nonterminal = 0.0 if terminateds[t] else 1.0
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lambda_ * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    batch[SampleBatch.ADVANTAGES] = adv
    batch[SampleBatch.VALUE_TARGETS] = (adv + values).astype(np.float32)
    return batch
