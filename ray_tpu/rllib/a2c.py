"""A2C: synchronous advantage actor-critic in jax.

Analog of ``/root/reference/rllib/algorithms/a2c/a2c.py`` (A2C's
training_step: synchronous sampling → one gradient step on the full batch
with the vanilla policy-gradient loss) — PPO without the ratio clip and
without SGD epochs.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig, train_one_step
from ray_tpu.rllib.rl_module import Columns
from ray_tpu.rllib.sample_batch import SampleBatch


def make_a2c_loss(vf_loss_coeff: float, entropy_coeff: float):
    def loss(module, params, batch):
        out = module.forward_train(params, batch[SampleBatch.OBS])
        logits = out[Columns.ACTION_DIST_INPUTS]
        values = out[Columns.VF_PREDS]
        logp_all = jax.nn.log_softmax(logits)
        actions = batch[SampleBatch.ACTIONS].astype(jnp.int32)
        logp = jnp.take_along_axis(logp_all, actions[:, None], axis=-1)[:, 0]
        adv = batch[SampleBatch.ADVANTAGES]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)

        policy_loss = -jnp.mean(logp * adv)
        vf_loss = jnp.mean(jnp.square(values - batch[SampleBatch.VALUE_TARGETS]))
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = policy_loss + vf_loss_coeff * vf_loss - entropy_coeff * entropy
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }

    return loss


def _a2c_loss_factory(config: Dict[str, Any]):
    return make_a2c_loss(config["vf_loss_coeff"], config["entropy_coeff"])


class A2CConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=A2C)
        self._config.update(
            _loss_factory=_a2c_loss_factory,
            lr=1e-3,
            train_batch_size=1000,
            # None = one gradient step over the whole batch (true A2C);
            # setting it takes one optimizer step per microbatch instead —
            # an approximation, not gradient accumulation
            microbatch_size=None,
            vf_loss_coeff=0.5,
            entropy_coeff=0.01,
            lambda_=0.95,
            grad_clip=0.5,
        )


class A2C(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        self._sgd_rng = np.random.default_rng(self.config.get("seed", 0))

    def training_step(self) -> Dict[str, Any]:
        from ray_tpu.rllib.algorithm import synchronous_parallel_sample

        cfg = self.config
        self.workers.sync_weights()
        batch = synchronous_parallel_sample(
            self.workers, max_env_steps=cfg["train_batch_size"]
        )
        self._timesteps_total += batch.count
        learner_metrics = train_one_step(
            self.workers.local_worker.policy,
            batch,
            num_sgd_iter=1,
            sgd_minibatch_size=cfg["microbatch_size"] or batch.count,
            rng=self._sgd_rng,
            required_keys=(
                SampleBatch.OBS, SampleBatch.ACTIONS,
                SampleBatch.ADVANTAGES, SampleBatch.VALUE_TARGETS,
            ),
        )
        return {"info": {"learner": learner_metrics}}


A2C._default_config = A2CConfig().to_dict()
