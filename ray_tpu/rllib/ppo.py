"""PPO: clipped-surrogate policy optimization in jax.

Analog of ``/root/reference/rllib/algorithms/ppo/ppo.py:311``
(PPO.training_step: synchronous sampling → minibatch SGD with the clipped
objective) with the loss of ``ppo_torch_policy.py`` expressed as a pure
jax function, so one ``jax.jit`` covers forward, loss, backward, and the
optimizer update.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig, train_one_step
from ray_tpu.rllib.rl_module import Columns
from ray_tpu.rllib.sample_batch import SampleBatch


def make_ppo_loss(clip_param: float, vf_clip_param: float,
                  vf_loss_coeff: float, entropy_coeff: float):
    """Loss factory; the returned closure is jitted inside JaxPolicy,
    with the forward routed through the policy's RLModule."""

    def loss(module, params, batch):
        out = module.forward_train(params, batch[SampleBatch.OBS])
        logits = out[Columns.ACTION_DIST_INPUTS]
        values = out[Columns.VF_PREDS]
        logp_all = jax.nn.log_softmax(logits)
        actions = batch[SampleBatch.ACTIONS].astype(jnp.int32)
        logp = jnp.take_along_axis(logp_all, actions[:, None], axis=-1)[:, 0]
        old_logp = batch[SampleBatch.ACTION_LOGP]
        adv = batch[SampleBatch.ADVANTAGES]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)

        ratio = jnp.exp(logp - old_logp)
        surr1 = ratio * adv
        surr2 = jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv
        policy_loss = -jnp.mean(jnp.minimum(surr1, surr2))

        vf_err = jnp.square(values - batch[SampleBatch.VALUE_TARGETS])
        vf_loss = jnp.mean(jnp.minimum(vf_err, vf_clip_param ** 2))

        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = policy_loss + vf_loss_coeff * vf_loss - entropy_coeff * entropy
        metrics = {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "kl": jnp.mean(old_logp - logp),
        }
        return total, metrics

    return loss


def _ppo_loss_factory(config: Dict[str, Any]):
    """Module-level so configs stay picklable; RolloutWorker calls this to
    attach the loss at policy construction (one init, no learner rebuild)."""
    return make_ppo_loss(
        config["clip_param"], config["vf_clip_param"],
        config["vf_loss_coeff"], config["entropy_coeff"],
    )


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=PPO)
        self._config.update(
            _loss_factory=_ppo_loss_factory,
            lr=3e-4,
            train_batch_size=4000,
            sgd_minibatch_size=128,
            num_sgd_iter=10,
            clip_param=0.2,
            vf_clip_param=10.0,
            vf_loss_coeff=0.5,
            entropy_coeff=0.0,
            lambda_=0.95,
            grad_clip=0.5,
        )


class PPO(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        # the local worker's policy was built with _loss_factory attached,
        # so it IS the learner — no rebuild
        self._sgd_rng = np.random.default_rng(self.config.get("seed", 0))

    def training_step(self) -> Dict[str, Any]:
        """``ppo.py:311``: synchronous parallel sampling to
        ``train_batch_size``, then clipped-objective minibatch SGD, then
        weight broadcast."""
        from ray_tpu.rllib.algorithm import synchronous_parallel_sample

        cfg = self.config
        self.workers.sync_weights()
        batch = synchronous_parallel_sample(
            self.workers, max_env_steps=cfg["train_batch_size"]
        )
        self._timesteps_total += batch.count
        learner_metrics = train_one_step(
            self.workers.local_worker.policy,
            batch,
            num_sgd_iter=cfg["num_sgd_iter"],
            sgd_minibatch_size=cfg["sgd_minibatch_size"],
            rng=self._sgd_rng,
            required_keys=(
                SampleBatch.OBS, SampleBatch.ACTIONS, SampleBatch.ACTION_LOGP,
                SampleBatch.ADVANTAGES, SampleBatch.VALUE_TARGETS,
            ),
        )
        return {"info": {"learner": learner_metrics}}


# set after the class exists (PPOConfig's __init__ references PPO)
PPO._default_config = PPOConfig().to_dict()
