"""IMPALA: importance-weighted actor-learner with V-trace, in jax.

Analog of ``/root/reference/rllib/algorithms/impala/impala.py`` (and its
``vtrace_torch.py``): rollout actors run behavior policies that lag the
learner by up to one sync, and V-trace corrects the off-policyness with
clipped importance ratios (rho for value targets, c for the trace).  Our
WorkerSet samples synchronously, so the lag is exactly one training_step's
worth of SGD — small but nonzero, which is precisely what V-trace absorbs.

V-trace recursion (from the IMPALA paper, computed per episode segment):
  delta_t = rho_t (r_t + gamma V(x_{t+1}) - V(x_t))
  vs_t    = V(x_t) + delta_t + gamma c_t (vs_{t+1} - V(x_{t+1}))
  pg_adv  = rho_t (r_t + gamma vs_{t+1} - V(x_t))
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.rl_module import Columns
from ray_tpu.rllib.sample_batch import SampleBatch


def compute_vtrace(
    behavior_logp: np.ndarray,
    current_logp: np.ndarray,
    values: np.ndarray,          # V(x_t) under the CURRENT policy
    bootstrap_value: float,      # V(x_{T}) after the segment (0 if terminal)
    rewards: np.ndarray,
    gamma: float,
    rho_bar: float = 1.0,
    c_bar: float = 1.0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One contiguous segment -> (vs targets, pg advantages, clipped rho)."""
    T = len(rewards)
    rho = np.minimum(rho_bar, np.exp(current_logp - behavior_logp))
    c = np.minimum(c_bar, np.exp(current_logp - behavior_logp))
    v_next = np.append(values[1:], bootstrap_value)
    deltas = rho * (rewards + gamma * v_next - values)
    vs = np.zeros(T, np.float32)
    acc = 0.0  # vs_{t+1} - V(x_{t+1}), zero past the boundary
    for t in range(T - 1, -1, -1):
        acc = deltas[t] + gamma * c[t] * acc
        vs[t] = values[t] + acc
    vs_next = np.append(vs[1:], bootstrap_value)
    pg_adv = rho * (rewards + gamma * vs_next - values)
    return vs.astype(np.float32), pg_adv.astype(np.float32), rho.astype(np.float32)


def make_impala_loss(vf_loss_coeff: float, entropy_coeff: float):
    """Policy gradient with precomputed V-trace advantages (already
    rho-weighted, so NOT renormalized) + vs-target value loss."""

    def loss(module, params, batch):
        out = module.forward_train(params, batch[SampleBatch.OBS])
        logits = out[Columns.ACTION_DIST_INPUTS]
        values = out[Columns.VF_PREDS]
        logp_all = jax.nn.log_softmax(logits)
        actions = batch[SampleBatch.ACTIONS].astype(jnp.int32)
        logp = jnp.take_along_axis(logp_all, actions[:, None], axis=-1)[:, 0]
        pg_loss = -jnp.mean(logp * batch[SampleBatch.ADVANTAGES])
        vf_loss = jnp.mean(jnp.square(values - batch[SampleBatch.VALUE_TARGETS]))
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = pg_loss + vf_loss_coeff * vf_loss - entropy_coeff * entropy
        return total, {
            "policy_loss": pg_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }

    return loss


def _impala_loss_factory(config: Dict[str, Any]):
    return make_impala_loss(config["vf_loss_coeff"], config["entropy_coeff"])


class ImpalaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=Impala)
        self._config.update(
            _loss_factory=_impala_loss_factory,
            # V-trace needs raw transitions + the behavior policy's logp;
            # GAE columns would be recomputed wrong (stale values)
            _store_next_obs=True,
            _postprocess_gae=False,
            _keep_behavior_logp=True,
            lr=1e-3,
            train_batch_size=1000,
            minibatch_size=1000,
            vf_loss_coeff=0.5,
            entropy_coeff=0.01,
            vtrace_rho_clip=1.0,
            vtrace_c_clip=1.0,
            grad_clip=40.0,
        )


class Impala(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        self._sgd_rng = np.random.default_rng(self.config.get("seed", 0))

    def _vtrace_batch(self, batch: SampleBatch) -> SampleBatch:
        """Compute vs targets + pg advantages per contiguous segment with
        the CURRENT learner policy (one forward over the whole batch)."""
        cfg = self.config
        policy = self.workers.local_worker.policy
        obs = batch[SampleBatch.OBS]
        current_logp = policy.action_logp(obs, batch[SampleBatch.ACTIONS])
        values = policy.value(obs)
        terminateds = batch[SampleBatch.TERMINATEDS]
        truncateds = batch[SampleBatch.TRUNCATEDS]
        eps_id = batch[SampleBatch.EPS_ID]
        next_obs = batch[SampleBatch.NEXT_OBS]
        n = batch.count

        # segment boundaries: episode end or eps_id change (fragment seam)
        bounds: List[Tuple[int, int]] = []
        start = 0
        for t in range(n):
            end_here = (
                terminateds[t] or truncateds[t]
                or t == n - 1 or eps_id[t + 1] != eps_id[t]
            )
            if end_here:
                bounds.append((start, t + 1))
                start = t + 1

        vs = np.empty(n, np.float32)
        pg_adv = np.empty(n, np.float32)
        # bootstrap values for all segment ends in one forward pass
        last_idx = np.asarray([e - 1 for _, e in bounds])
        boot_all = policy.value(next_obs[last_idx])
        for (s, e), boot in zip(bounds, boot_all):
            bv = 0.0 if terminateds[e - 1] else float(boot)
            vs[s:e], pg_adv[s:e], _ = compute_vtrace(
                batch[SampleBatch.ACTION_LOGP][s:e],
                current_logp[s:e],
                values[s:e],
                bv,
                batch[SampleBatch.REWARDS][s:e],
                cfg["gamma"],
                cfg["vtrace_rho_clip"],
                cfg["vtrace_c_clip"],
            )
        out = SampleBatch({
            SampleBatch.OBS: obs,
            SampleBatch.ACTIONS: batch[SampleBatch.ACTIONS],
            SampleBatch.ADVANTAGES: pg_adv,
            SampleBatch.VALUE_TARGETS: vs,
            # behavior logp rides along for losses with an importance
            # ratio (APPO's clipped surrogate); IMPALA's required_keys
            # filter simply drops it
            SampleBatch.ACTION_LOGP: batch[SampleBatch.ACTION_LOGP],
        })
        return out

    def training_step(self) -> Dict[str, Any]:
        from ray_tpu.rllib.algorithm import synchronous_parallel_sample, train_one_step

        cfg = self.config
        self.workers.sync_weights()
        batch = synchronous_parallel_sample(
            self.workers, max_env_steps=cfg["train_batch_size"]
        )
        self._timesteps_total += batch.count
        train_batch = self._vtrace_batch(batch)
        learner_metrics = train_one_step(
            self.workers.local_worker.policy,
            train_batch,
            num_sgd_iter=1,
            sgd_minibatch_size=cfg["minibatch_size"],
            rng=self._sgd_rng,
            required_keys=(
                SampleBatch.OBS, SampleBatch.ACTIONS,
                SampleBatch.ADVANTAGES, SampleBatch.VALUE_TARGETS,
            ),
        )
        return {"info": {"learner": learner_metrics}}


Impala._default_config = ImpalaConfig().to_dict()
