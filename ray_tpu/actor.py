"""Actors: ``ActorClass``, ``ActorHandle``, ``ActorMethod``.

Analog of ``python/ray/actor.py`` (``ActorClass._remote`` at ``actor.py:657``,
``ActorMethod`` at ``:92``, ``ActorHandle`` at ``:1020``).  Creation goes
through the head's GCS-style actor FSM; method calls are ordered per-actor
(the reference orders per-caller via sequence numbers in
``CoreWorkerDirectActorTaskSubmitter``; routing everything through the head
gives a single total order, which is strictly stronger).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import cloudpickle

from ray_tpu._private import ray_option_utils
from ray_tpu._private.object_ref import ObjectRef, new_id
from ray_tpu._private.worker import global_worker


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1,
                 concurrency_group: Optional[str] = None):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(
            self._method_name, args, kwargs, self._num_returns,
            concurrency_group=self._concurrency_group,
        )

    def options(self, num_returns: int = 1,
                concurrency_group: Optional[str] = None, **_):
        if num_returns == "dynamic":
            raise ValueError('num_returns="dynamic" is not supported for '
                             "actor methods")
        if not isinstance(num_returns, int) or num_returns < 1:
            raise ValueError(f"num_returns must be an int >= 1, got {num_returns!r}")
        if concurrency_group is not None:
            declared = self._handle._concurrency_groups
            # validated only when the handle carries the declaration (a
            # deserialized handle may not); the worker routes unknown
            # groups to the default pool
            if declared and concurrency_group not in declared:
                raise ValueError(
                    f"unknown concurrency group {concurrency_group!r}; "
                    f"declared: {sorted(declared)}")
        return ActorMethod(self._handle, self._method_name, num_returns,
                           concurrency_group=concurrency_group)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._method_name!r} must be invoked with .remote()"
        )


class ActorHandle:
    def __init__(self, actor_id: bytes, class_name: str, method_num_returns: Optional[Dict[str, int]] = None,
                 concurrency_groups: Optional[Tuple[str, ...]] = None):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_num_returns = method_num_returns or {}
        # declared concurrency-group NAMES (for method.options validation);
        # the sizes live head-side in the creation spec
        self._concurrency_groups = tuple(concurrency_groups or ())

    @property
    def _id_hex(self) -> str:
        return self._actor_id.hex()

    def __getattr__(self, item: str) -> ActorMethod:
        if item.startswith("_"):
            raise AttributeError(item)
        return ActorMethod(self, item, self._method_num_returns.get(item, 1))

    def _submit_method(self, method_name: str, args, kwargs, num_returns: int,
                       concurrency_group: Optional[str] = None):
        w = global_worker
        spec, return_refs = w.build_task_spec(
            name=f"{self._class_name}.{method_name}",
            fn_id=None,
            args=args,
            kwargs=kwargs,
            num_returns=num_returns,
            resources={},
            actor_id=self._actor_id,
            method_name=method_name,
            concurrency_group=concurrency_group,
        )
        w.client.submit_actor_task(spec)
        return return_refs[0] if num_returns == 1 else return_refs

    def _submit_compiled_task(self, fn, args: tuple, name: str) -> ObjectRef:
        """Submit a compiled-graph control task: a module-level ``fn`` that
        the worker runs with the actor INSTANCE as first argument (spec flag
        ``compiled_graph``; see ``_private/worker.py``).  Rides the normal
        per-actor FIFO lane but returns fast — the graph's execution loop
        itself runs on a dedicated thread the installed op spawns, so
        repeated ``execute()`` calls never touch this lane again."""
        w = global_worker
        blob = cloudpickle.dumps(fn)
        fn_id = w.register_function(blob)
        spec, return_refs = w.build_task_spec(
            name=f"{self._class_name}.{name}",
            fn_id=fn_id,
            args=args,
            kwargs={},
            num_returns=1,
            resources={},
            actor_id=self._actor_id,
        )
        spec["compiled_graph"] = True
        w.client.submit_actor_task(spec)
        return return_refs[0]

    def __reduce__(self):
        return (_rebuild_handle, (self._actor_id, self._class_name,
                                  self._method_num_returns,
                                  self._concurrency_groups))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:8]})"


def _rebuild_handle(actor_id, class_name, mnr, concurrency_groups=()):
    return ActorHandle(actor_id, class_name, mnr,
                       concurrency_groups=concurrency_groups)


class ActorClass:
    def __init__(self, cls: type, default_options: Dict[str, Any]):
        self._cls = cls
        self._default_options = ray_option_utils.validate_options(default_options, for_actor=True)
        self._class_blob: Optional[bytes] = None
        self.__name__ = cls.__name__

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__} cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote(...)"
        )

    def options(self, **options) -> "_ActorClassWrapper":
        merged = dict(self._default_options)
        merged.update(ray_option_utils.validate_options(options, for_actor=True))
        return _ActorClassWrapper(self, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._default_options)

    def bind(self, *args, **kwargs):
        """Build a lazy actor DAG node (``ray.dag`` ClassNode)."""
        from ray_tpu.dag import ClassNode

        return ClassNode(self, args, kwargs)

    def _remote(self, args, kwargs, options: Dict[str, Any]) -> ActorHandle:
        from ray_tpu.remote_function import _strategy_to_dict

        w = global_worker
        if not w.connected:
            import threading

            if threading.current_thread() is not threading.main_thread():
                raise RuntimeError(
                    "ray_tpu is not initialized (auto-init only runs on "
                    "the main thread)")
            import ray_tpu

            ray_tpu.init()
        if self._class_blob is None:
            self._class_blob = cloudpickle.dumps(self._cls)
        fn_id = w.register_function(self._class_blob)
        actor_id = new_id()
        # async actors default to high concurrency (the reference's asyncio
        # actor default); sync actors serialize unless max_concurrency is set
        max_concurrency = options.get("max_concurrency")
        if max_concurrency is None:
            import inspect

            is_async = any(
                inspect.iscoroutinefunction(m)
                for _, m in inspect.getmembers(self._cls, inspect.isfunction)
            )
            max_concurrency = 1000 if is_async else 1
        # Actors default to 1 CPU for placement but occupy 0 once created
        # (reference semantics); an explicit num_cpus is held for life.
        cpu_defaulted = options.get("num_cpus") is None
        resources = ray_option_utils.resources_from_options(options, default_num_cpus=1)
        concurrency_groups = options.get("concurrency_groups")
        spec, return_refs = w.build_task_spec(
            name=f"{self._cls.__name__}.__init__",
            fn_id=fn_id,
            args=args,
            kwargs=kwargs,
            num_returns=1,
            resources=resources,
            scheduling_strategy=_strategy_to_dict(options.get("scheduling_strategy")),
            actor_id=actor_id,
            is_actor_creation=True,
            max_restarts=options.get("max_restarts", 0),
            max_task_retries=options.get("max_task_retries", 0),
            actor_name=options.get("name"),
            runtime_env=options.get("runtime_env"),
            max_concurrency=max_concurrency,
            release_cpu_after_start=cpu_defaulted,
            concurrency_groups=concurrency_groups,
            lifetime=options.get("lifetime"),
            namespace=options.get("namespace"),
        )
        w.client.create_actor(spec)
        return ActorHandle(
            actor_id, self._cls.__name__,
            concurrency_groups=tuple(concurrency_groups or ()),
        )


class _ActorClassWrapper:
    def __init__(self, ac: ActorClass, options: Dict[str, Any]):
        self._ac = ac
        self._options = options

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._ac._remote(args, kwargs, self._options)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode

        return ClassNode(self._ac, args, kwargs, self._options)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    """Look up a named actor (``ray.get_actor`` analog).

    Lookups are namespace-scoped: with no ``namespace`` the caller's own
    is used (the driver's, or — inside a task/actor — the submitting
    job's), so one tenant's names never resolve to another tenant's
    actors.  A name that only exists in a different namespace raises
    ``ValueError`` exactly like a missing one."""
    w = global_worker
    ns = (namespace or w.current_namespace or w.namespace or "default")
    aid, _ = w.client.get_actor_by_name(name, namespace=ns)
    if aid is None:
        raise ValueError(
            f"Failed to look up actor with name '{name}' in namespace "
            f"'{ns}'")
    return ActorHandle(aid, name)
