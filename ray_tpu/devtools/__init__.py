"""Developer tooling that ships with the repo but never runs on a
cluster hot path: the raylint static-analysis suite lives here."""
