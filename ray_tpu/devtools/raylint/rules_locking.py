"""R4 — lock-scope weight.

Blocking or table-sized work inside a ``with <lock>:`` body serializes
every other thread on that lock: PR 2 found ``import bisect`` executing
inside ``Histogram.observe``'s locked path, PR 5 found table scans under
the registry lock on the scrape path.  The rule recognizes a guard by
name (terminal component matching ``lock``/``mutex``/``mu``) and flags
the known-heavy operations in its body.  Work inside a nested ``def`` or
``lambda`` is NOT flagged — it runs later, when the lock is gone.
"""

from __future__ import annotations

import ast
import re
from typing import List

from ray_tpu.devtools.raylint.core import (
    Finding, LintConfig, Project, SourceFile, dotted_name, make_finding,
)

_LOCK_NAME = re.compile(r"(?:^|_)(?:lock|locks|mutex|mu)$", re.IGNORECASE)

# dotted call names that block (or can block) while held
_BLOCKING_CALLS = {
    "time.sleep": "sleeps while every waiter spins",
    "subprocess.run": "spawns a process under the lock",
    "subprocess.Popen": "spawns a process under the lock",
    "subprocess.check_output": "spawns a process under the lock",
    "subprocess.check_call": "spawns a process under the lock",
    "os.system": "spawns a shell under the lock",
    "os.popen": "spawns a shell under the lock",
    "open": "file I/O under the lock",
    "json.dump": "serializes (possibly unbounded) data under the lock",
    "json.dumps": "serializes (possibly unbounded) data under the lock",
}
# socket-ish method calls (terminal attr) that block on the network
_BLOCKING_METHODS = {
    "recv", "recv_into", "recvfrom", "accept", "connect", "sendall",
    "makefile", "getaddrinfo", "gethostbyname",
}
# iterable-producing methods that mark a `sorted()` as table-sized
_TABLE_ITER = {"values", "items", "keys"}


def _lock_guard_name(item: ast.withitem) -> str:
    """The guard's dotted name when the with-item looks like a lock."""
    expr = item.context_expr
    if isinstance(expr, ast.Call):  # e.g. `with self._lock_for(x):`
        expr = expr.func
    name = dotted_name(expr)
    terminal = name.rsplit(".", 1)[-1] if name else ""
    return name if terminal and _LOCK_NAME.search(terminal) else ""


def _visit_locked(sf: SourceFile, node: ast.AST, lock: str,
                  flagged: dict) -> None:
    """Flag heavy work at ``node`` and in its subtree; prune
    deferred-execution scopes (defs/lambdas) whose bodies run after the
    lock is released."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return
    if isinstance(node, (ast.Import, ast.ImportFrom)):
        if not sf.suppressed(node.lineno, "R4"):
            flagged.setdefault((node.lineno, "import"), make_finding(
                sf, "R4", node.lineno,
                f"import executed while holding {lock} (first import "
                f"takes the global import lock + disk I/O)",
                "hoist the import to module level",
                detail=f"import-under:{lock}"))
    elif isinstance(node, ast.Call):
        _flag_call(sf, node, lock, flagged)
    for child in ast.iter_child_nodes(node):
        _visit_locked(sf, child, lock, flagged)


def _flag_call(sf: SourceFile, node: ast.Call, lock: str,
               flagged: dict) -> None:
    name = dotted_name(node.func)
    terminal = name.rsplit(".", 1)[-1] if name else ""
    line = node.lineno
    if sf.suppressed(line, "R4"):
        return
    if name in _BLOCKING_CALLS:
        flagged.setdefault((line, name), make_finding(
            sf, "R4", line,
            f"{name}() inside `with {lock}:` — {_BLOCKING_CALLS[name]}",
            "move the call outside the locked region (snapshot under "
            "the lock, do the work after)",
            detail=f"blocking:{name}:under:{lock}"))
    elif terminal in _BLOCKING_METHODS and "." in name:
        flagged.setdefault((line, name), make_finding(
            sf, "R4", line,
            f"{name}() inside `with {lock}:` — network/socket I/O holds "
            f"the lock for a round trip",
            "move the I/O outside the locked region",
            detail=f"socket:{terminal}:under:{lock}"))
    elif name == "sorted" and node.args:
        arg = node.args[0]
        if (isinstance(arg, ast.Call)
                and isinstance(arg.func, ast.Attribute)
                and arg.func.attr in _TABLE_ITER):
            flagged.setdefault((line, "sorted"), make_finding(
                sf, "R4", line,
                f"sorted() over a table-sized iterable inside "
                f"`with {lock}:` — O(n log n) scan while held",
                "snapshot the rows under the lock, sort after release",
                detail=f"sorted-table:under:{lock}"))


def check_lock_scope_weight(project: Project,
                            config: LintConfig) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project:
        if sf.tree is None:
            continue
        # one flagged-map per file: a nested `with` under an outer lock
        # is visited for both guards — the first (outermost) wins
        flagged: dict = {}
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            locks = [n for n in (_lock_guard_name(i) for i in node.items)
                     if n]
            if not locks:
                continue
            for stmt in node.body:
                _visit_locked(sf, stmt, locks[0], flagged)
        findings.extend(flagged.values())
    return findings


check_lock_scope_weight.RULE_ID = "R4"
check_lock_scope_weight.RULE_NAME = "lock-scope-weight"
