"""raylint — the repo's invariant-enforcing static-analysis suite.

Eight AST rules distilled from five PRs of postmortems, plus a dynamic
lock-order witness (``RAY_TPU_LOCKWITNESS=1``).  ``ray_tpu lint`` runs
the static half; ``tests/test_raylint.py`` gates both in tier-1.

Rule registry (id -> check callable):

====  =======================  ================================================
R1    protocol-consistency     every sent wire frame has a dispatch arm (and
                               no dead arms), in both wire directions
R2    exception-shadow         broad ``except`` arms that kill narrower ones
R3    hot-path-entropy         uuid4/urandom/secrets on the dispatch path
R4    lock-scope-weight        blocking/table-sized work under a held lock
R5    unbounded-container      head-resident dict/list that grows forever
R6    event-source-registry    ``events.emit`` sources declared in
                               ``KNOWN_SOURCES``
R7    state-api-parity         ``list_*`` helpers with a head handler AND an
                               operator surface
R8    bare-thread-hygiene      ``threading.Thread`` with neither ``daemon=``
                               nor a join
====  =======================  ================================================
"""

from ray_tpu.devtools.raylint.core import (  # noqa: F401
    Finding, LintConfig, Project, baseline_path, load_baseline,
    save_baseline, split_new,
)
from ray_tpu.devtools.raylint.rules_protocol import (
    check_event_sources, check_protocol, check_state_parity,
)
from ray_tpu.devtools.raylint.rules_exceptions import check_exception_shadow
from ray_tpu.devtools.raylint.rules_hotpath import (
    check_bare_threads, check_hot_path_entropy,
)
from ray_tpu.devtools.raylint.rules_locking import check_lock_scope_weight
from ray_tpu.devtools.raylint.rules_containers import (
    check_unbounded_containers,
)

RULES = {
    "R1": check_protocol,
    "R2": check_exception_shadow,
    "R3": check_hot_path_entropy,
    "R4": check_lock_scope_weight,
    "R5": check_unbounded_containers,
    "R6": check_event_sources,
    "R7": check_state_parity,
    "R8": check_bare_threads,
}

from ray_tpu.devtools.raylint.runner import (  # noqa: E402,F401
    GateResult, analyze, run_gate,
)
