"""R2 — exception-shadow.

The PR 3 bug class, generalized: ``TimeoutError`` is a subclass of
``OSError``, so an ``except OSError`` arm that closes a channel also
eats the timeout that a caller upstream was supposed to see.  Two
shapes of the same mistake:

- **dead handler**: within one ``try``, a broad ``except`` lexically
  precedes a narrower one — the narrow arm can never run (CPython
  matches handlers top-down).
- **swallowed raise**: a ``raise Narrow(...)`` inside the try body whose
  own ``except Broad`` arm catches it (Narrow ⊂ Broad, strictly) and
  never re-raises — the raise was written to escape the function but
  can't.

Subclass facts come from the real builtin exception hierarchy (resolved
via ``builtins`` at analysis time), so ``TimeoutError ⊂ OSError ⊂
Exception`` needs no hand-maintained table.  Dotted or unresolvable
names (``socket.timeout``, project exceptions) fall back to exact-name
matching, which still catches duplicated arms.
"""

from __future__ import annotations

import ast
import builtins
from typing import List, Optional, Sequence, Tuple

from ray_tpu.devtools.raylint.core import (
    Finding, LintConfig, Project, SourceFile, dotted_name, make_finding,
)


def _resolve(name: str) -> Optional[type]:
    """The builtin exception class a handler name refers to, if any."""
    if "." in name:  # dotted (socket.timeout, project exc): name-match only
        return None
    obj = getattr(builtins, name, None)
    if isinstance(obj, type) and issubclass(obj, BaseException):
        return obj
    return None


def _handler_names(h: ast.ExceptHandler) -> List[str]:
    """The caught type names of one arm ([] for a bare ``except:``)."""
    if h.type is None:
        return ["BaseException"]
    elts = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    return [dotted_name(e) or "<dynamic>" for e in elts]


def _subsumes(broad: str, narrow: str) -> bool:
    """True when an ``except broad`` arm would catch ``narrow``."""
    if broad == narrow:
        return True
    b, n = _resolve(broad), _resolve(narrow)
    if b is not None and n is not None:
        return issubclass(n, b)
    return False


def _strictly_subsumes(broad: str, narrow: str) -> bool:
    b, n = _resolve(broad), _resolve(narrow)
    return (b is not None and n is not None and b is not n
            and issubclass(n, b))


def _reraises(h: ast.ExceptHandler) -> bool:
    """The arm lets the exception (or a replacement) escape.  A raise
    inside a def/lambda DEFINED in the arm doesn't count — it runs
    later, elsewhere; the caught exception is still swallowed here."""
    stack: List[ast.AST] = list(h.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Raise):
            return True
        stack.extend(ast.iter_child_nodes(node))
    return False


def _body_raises(try_node: ast.Try) -> List[Tuple[str, int]]:
    """(exc name, line) for every ``raise Name(...)`` directly protected
    by this try (nested trys and function defs shield their own)."""
    out: List[Tuple[str, int]] = []

    def walk_block(stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Try)):
                continue  # shielded by an inner scope / inner handlers
            if isinstance(stmt, ast.Raise) and stmt.exc is not None:
                target = stmt.exc
                if isinstance(target, ast.Call):
                    target = target.func
                name = dotted_name(target)
                if name:
                    out.append((name, stmt.lineno))
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list):
                    walk_block(sub)

    walk_block(try_node.body)
    return out


def check_exception_shadow(project: Project,
                           config: LintConfig) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Try) or not node.handlers:
                continue
            findings.extend(_check_try(sf, node))
    return findings


def _check_try(sf: SourceFile, node: ast.Try) -> List[Finding]:
    findings: List[Finding] = []
    arms = [(h, _handler_names(h)) for h in node.handlers]

    # (a) dead handler: an earlier arm subsumes a later one entirely
    for j in range(1, len(arms)):
        hj, names_j = arms[j]
        for i in range(j):
            hi, names_i = arms[i]
            if all(any(_subsumes(b, n) for b in names_i)
                   for n in names_j) and names_j != ["<dynamic>"]:
                if not sf.suppressed(hj.lineno, "R2"):
                    findings.append(make_finding(
                        sf, "R2", hj.lineno,
                        f'`except {"/".join(names_j)}` can never run: '
                        f'`except {"/".join(names_i)}` at line '
                        f'{hi.lineno} already catches it',
                        "reorder the handlers narrowest-first (or delete "
                        "the dead arm)",
                        detail=f'dead-arm:{"/".join(names_j)}'
                               f'<{"/".join(names_i)}'))
                break

    # (b) swallowed raise: the try body raises Narrow, an arm catches a
    # strict superclass and never re-raises — the raise cannot escape
    for exc_name, raise_line in _body_raises(node):
        for h, names in arms:
            caught = [b for b in names if _subsumes(b, exc_name)]
            if not caught:
                continue
            if any(b == exc_name or not _strictly_subsumes(b, exc_name)
                   for b in caught):
                break  # caught exactly / unresolvable: assume intended
            if not _reraises(h) and not sf.suppressed(raise_line, "R2"):
                findings.append(make_finding(
                    sf, "R2", raise_line,
                    f"`raise {exc_name}` is swallowed by the broader "
                    f'`except {"/".join(names)}` at line {h.lineno} '
                    f"(it never leaves this try)",
                    "move the raise outside the try, or re-raise "
                    f"{exc_name} from the broad arm "
                    "(the PR 3 TimeoutError-closes-channel bug class)",
                    detail=f'swallowed:{exc_name}<{"/".join(names)}'))
            break  # first matching arm wins in CPython
    return findings


check_exception_shadow.RULE_ID = "R2"
check_exception_shadow.RULE_NAME = "exception-shadow"
