"""The dynamic half of raylint: a lock acquisition-order witness.

Static rules can't prove lock ORDER.  With ``RAY_TPU_LOCKWITNESS=1``
the named locks in ``_private/node.py``, ``object_store.py``,
``util/metrics.py``, ``util/tsdb.py`` and ``dag/compiled.py`` are
wrapped (via :func:`ray_tpu._private.locks.make_lock`) so every acquire
records, per thread, the set of witness locks already held and adds
``held -> acquired`` edges to a global order graph.  A cycle in that
graph is a potential deadlock that needs only the right interleaving —
the witness reports it with BOTH closing stacks even when the run never
actually deadlocks (the lockdep/TSan idea; the reference gets this from
clang thread-safety annotations + TSan, SURVEY §7).

Reports go to stderr and — when ``RAY_TPU_LOCKWITNESS_DIR`` is set — to
``lockwitness-<pid>-<n>.json`` in that directory, so a multi-process
cluster test can assert the whole run stayed cycle-free by globbing one
directory.  Same-name edges are skipped: instances sharing a name (e.g.
per-connection locks) have no defined order between themselves.

Overhead is irrelevant by design: nothing here imports or runs unless
the env flag is set.
"""

from __future__ import annotations

import json
import os
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

_STACK_DEPTH = 14


class _Witness:
    """Global order graph + per-thread held stacks."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._tls = threading.local()
        # a -> {b}: lock a was held while b was acquired
        self._edges: Dict[str, Set[str]] = {}
        # (a, b) -> stack captured when the edge was first observed
        self._edge_stacks: Dict[Tuple[str, str], str] = {}
        self._cycles: List[dict] = []
        self._n_reports = 0

    # -- per-thread held stack --------------------------------------------
    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    # -- events ------------------------------------------------------------
    def acquired(self, name: str) -> None:
        held = self._held()
        if name in held:
            # re-entrant RLock acquire: it can never block (the thread
            # already owns the lock), so it must create NO order edges —
            # `with A: with B: with A:` would otherwise record a bogus
            # B->A edge and report a false A->B->A cycle
            held.append(name)
            return
        if held:
            with self._mu:
                fresh = [h for h in held
                         if h != name and name not in self._edges.get(h, ())]
                if fresh:
                    # capture the stack only for a first-seen edge: the
                    # steady state (same nesting, thousands of times in a
                    # live-cluster run) pays a set lookup, not a
                    # 14-frame format_stack
                    stack = "".join(
                        traceback.format_stack(limit=_STACK_DEPTH)[:-2])
                    for h in fresh:
                        self._add_edge(h, name, stack)
        held.append(name)

    def released(self, name: str) -> None:
        held = self._held()
        # release order need not be LIFO; drop the most recent occurrence
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break

    # -- graph (callers hold self._mu) -------------------------------------
    def _add_edge(self, a: str, b: str, stack: str) -> None:
        if b in self._edges.get(a, ()):
            return
        self._edges.setdefault(a, set()).add(b)
        self._edge_stacks[(a, b)] = stack
        path = self._find_path(b, a)
        if path is not None:
            cycle = {
                "locks": path + [b],
                "closing_edge": [a, b],
                "closing_stack": stack,
                "edges": {
                    f"{x}->{y}": self._edge_stacks.get((x, y), "")
                    for x, y in zip(path, path[1:] + [b])
                },
                "pid": os.getpid(),
                "thread": threading.current_thread().name,
            }
            self._cycles.append(cycle)
            self._report(cycle)

    def _find_path(self, start: str, goal: str) -> Optional[List[str]]:
        """DFS path start -> goal over the edge graph (None if absent)."""
        stack = [(start, [start])]
        seen = {start}
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _report(self, cycle: dict) -> None:
        import sys

        msg = (f"raylint lockwitness: POTENTIAL DEADLOCK — lock order "
               f"cycle {' -> '.join(cycle['locks'])} (pid {cycle['pid']}, "
               f"thread {cycle['thread']})")
        print(msg, file=sys.stderr)
        out_dir = os.environ.get("RAY_TPU_LOCKWITNESS_DIR")
        if out_dir:
            try:
                os.makedirs(out_dir, exist_ok=True)
                self._n_reports += 1
                path = os.path.join(
                    out_dir,
                    f"lockwitness-{os.getpid()}-{self._n_reports}.json")
                with open(path, "w") as f:
                    json.dump(cycle, f, indent=1)
            except OSError:
                pass  # the stderr line already carries the verdict

    # -- inspection ---------------------------------------------------------
    def snapshot(self) -> dict:
        with self._mu:
            return {
                "edges": sorted(f"{a}->{b}"
                                for a, bs in self._edges.items()
                                for b in bs),
                "cycles": list(self._cycles),
            }

    def assert_cycle_free(self) -> None:
        with self._mu:
            if self._cycles:
                locks = [" -> ".join(c["locks"]) for c in self._cycles]
                raise AssertionError(
                    f"lock-order cycles observed: {locks}")

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._edge_stacks.clear()
            self._cycles.clear()


WITNESS = _Witness()


class WitnessLock:
    """Transparent Lock/RLock proxy that reports to :data:`WITNESS`.

    Implements the private Condition protocol (``_release_save`` /
    ``_acquire_restore`` / ``_is_owned``) so ``threading.Condition``
    built over a wrapped lock keeps working — and keeps the held-set
    accurate across ``cond.wait()``'s release/reacquire."""

    def __init__(self, name: str, lock) -> None:
        self._name = name
        self._lock = lock

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            WITNESS.acquired(self._name)
        return got

    def release(self) -> None:
        self._lock.release()
        WITNESS.released(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    # Condition protocol ----------------------------------------------------
    def _release_save(self):
        state = self._lock._release_save() if hasattr(
            self._lock, "_release_save") else self._lock.release()
        WITNESS.released(self._name)
        return state

    def _acquire_restore(self, state) -> None:
        if hasattr(self._lock, "_acquire_restore"):
            self._lock._acquire_restore(state)
        else:
            self._lock.acquire()
        WITNESS.acquired(self._name)

    def _is_owned(self) -> bool:
        if hasattr(self._lock, "_is_owned"):
            return self._lock._is_owned()
        # plain Lock heuristic (what threading.Condition itself does)
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def __repr__(self) -> str:
        return f"<WitnessLock {self._name} {self._lock!r}>"


def wrap_lock(name: str, lock) -> WitnessLock:
    return WitnessLock(name, lock)
