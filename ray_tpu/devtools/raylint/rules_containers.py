"""R5 — unbounded-container.

Head-resident state lives as long as the cluster.  PR 5's root-cause
class: per-origin tables that gained rows on every push and dropped
them never — dead pushers stayed in ``/metrics`` forever.  The rule
finds instance/module-level dicts/lists/sets on the configured
head-resident modules that GROW somewhere but are never shrunk
(``pop``/``del``/``clear``/``popitem``/``remove``/``discard``/
reassignment outside ``__init__``) anywhere in the module.

``collections.deque(maxlen=...)`` and constructor-capped containers are
inherently bounded and never flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ray_tpu.devtools.raylint.core import (
    Finding, LintConfig, Project, SourceFile, dotted_name, make_finding,
)

_GROW_METHODS = {"append", "add", "insert", "extend", "update",
                 "setdefault", "appendleft"}
_SHRINK_METHODS = {"pop", "popitem", "popleft", "clear", "remove",
                   "discard"}


def _container_ctor(node: ast.AST) -> str:
    """'dict'/'list'/'set' when the value constructs an unbounded
    container, '' otherwise (deque(maxlen=), comprehensions from
    bounded sources, etc. are not flagged)."""
    if isinstance(node, ast.Dict) and not node.keys:
        return "dict"
    if isinstance(node, ast.List) and not node.elts:
        return "list"
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        terminal = name.rsplit(".", 1)[-1]
        if terminal in ("dict", "OrderedDict", "defaultdict"):
            return "dict"
        if terminal == "list":
            return "list"
        if terminal == "set":
            return "set"
        if terminal == "deque":
            has_maxlen = any(kw.arg == "maxlen" for kw in node.keywords)
            return "" if has_maxlen else "list"
    if isinstance(node, ast.Call) or isinstance(node, (ast.DictComp,
                                                       ast.ListComp,
                                                       ast.SetComp)):
        return ""
    return ""


def _attr_terminal(node: ast.AST) -> str:
    """'x' for self.x / obj.x / x (the per-module identity we track)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _scan_module(sf: SourceFile) -> Tuple[
        Dict[str, Tuple[int, str]], Set[str], Set[str]]:
    """(declared containers: name -> (line, kind), grown names,
    shrunk names) for one module."""
    declared: Dict[str, Tuple[int, str]] = {}
    grown: Set[str] = set()
    shrunk: Set[str] = set()
    tree = sf.tree
    if tree is None:
        return declared, grown, shrunk

    # declarations: `self.x = {}` inside __init__, or module-level `X = {}`
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "__init__":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    t = sub.targets[0]
                    if isinstance(t, ast.Attribute) and isinstance(
                            t.value, ast.Name) and t.value.id == "self":
                        kind = _container_ctor(sub.value)
                        if kind:
                            declared.setdefault(t.attr, (sub.lineno, kind))
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            kind = _container_ctor(node.value)
            if kind:
                declared.setdefault(node.targets[0].id,
                                    (node.lineno, kind))

    init_spans: List[Tuple[int, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "__init__":
            init_spans.append((node.lineno,
                               getattr(node, "end_lineno", node.lineno)))

    def in_init(line: int) -> bool:
        return any(a <= line <= b for a, b in init_spans)

    # growth / shrink sites
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    name = _attr_terminal(t.value)
                    if name:
                        grown.add(name)          # x[k] = v
                elif _attr_terminal(t) and not in_init(node.lineno):
                    # reassignment outside __init__ resets the container
                    shrunk.add(_attr_terminal(t))
        elif isinstance(node, ast.AugAssign):
            name = _attr_terminal(node.target)
            if name:
                grown.add(name)                   # x += [...]
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    name = _attr_terminal(t.value)
                    if name:
                        shrunk.add(name)          # del x[k]
        elif isinstance(node, ast.Call) and isinstance(node.func,
                                                       ast.Attribute):
            base = _attr_terminal(node.func.value)
            if not base:
                continue
            if node.func.attr in _GROW_METHODS:
                grown.add(base)
            elif node.func.attr in _SHRINK_METHODS:
                shrunk.add(base)
    return declared, grown, shrunk


def check_unbounded_containers(project: Project,
                               config: LintConfig) -> List[Finding]:
    findings: List[Finding] = []
    for rel in config.head_container_modules:
        sf = project.get(rel)
        if sf is None or sf.tree is None:
            continue
        declared, grown, shrunk = _scan_module(sf)
        for name, (line, kind) in sorted(declared.items()):
            if name not in grown or name in shrunk:
                continue
            if sf.suppressed(line, "R5"):
                continue
            findings.append(make_finding(
                sf, "R5", line,
                f"head-resident {kind} `{name}` grows in handlers but "
                f"nothing in this module ever removes from it "
                f"(slow head OOM; dead entries live forever)",
                "add a cap/LRU eviction, an expiry sweep, or explicit "
                "removal on the teardown path (PR 5's replacement-merge "
                "pattern)",
                detail=f"unbounded:{name}"))
    return findings


check_unbounded_containers.RULE_ID = "R5"
check_unbounded_containers.RULE_NAME = "unbounded-container"
