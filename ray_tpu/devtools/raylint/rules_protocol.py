"""Cross-module protocol rules: R1 (wire consistency), R6 (event sources),
R7 (state-API parity).

The control plane is a string-keyed wire: a frame is ``{"type": <mtype>}``
and the receiving side dispatches on ``mtype ==`` chains.  Nothing but
convention keeps the two sides in sync — a typo'd type string or a removed
handler silently drops messages (the reference gets this safety from typed
protobuf RPCs; here the linter supplies it).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.raylint.core import (
    Finding, LintConfig, Project, SourceFile, all_str_constants, dotted_name,
    make_finding, module_str_constants, str_const,
)

# call attrs that put a frame on the control wire; the frame dict may be
# any of the first two args (`_reply(conn, msg)` passes it second).
# ``outbox.append`` counts too: the head queues client-bound frames on
# per-connection outboxes that _flush_sends writes out.
_SEND_ATTRS = ("send", "request", "_send", "_reply", "agent_send",
               "safe_send")
_OUTBOX_NAMES = ("outbox",)


def _dict_type_value(node: ast.AST) -> Optional[str]:
    """The "type" value of a dict literal frame, if statically known."""
    if not isinstance(node, ast.Dict):
        return None
    for k, v in zip(node.keys, node.values):
        if k is not None and str_const(k) == "type":
            return str_const(v)
    return None


def _scope_walk(body_nodes: List[ast.stmt]):
    """BFS over one scope's nodes, PRUNING nested function bodies: their
    locals belong to them alone (each def gets its own ``scan_scope``),
    and walking into them here would attribute one function's frame
    variables to another's ``send`` — phantom sends that mask dead
    handlers."""
    queue = list(body_nodes)
    while queue:
        node = queue.pop(0)
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                queue.append(child)


def _collect_sends(sf: SourceFile) -> List[Tuple[str, int]]:
    """(mtype, line) for every frame this module puts on the wire.

    A frame counts when a dict literal with a constant "type" key is the
    first argument of a ``.send(...)``/``.request(...)``/``._send(...)``
    call, either inline or via a straight-line local variable within the
    same function (``msg = {...}; conn.send(msg)``).
    """
    out: List[Tuple[str, int]] = []
    if sf.tree is None:
        return out

    def scan_scope(body_nodes: List[ast.stmt]) -> None:
        # local name -> (mtype, line) for dict-literal assignments,
        # tracked per scope (nested defs are pruned by _scope_walk)
        local_frames: Dict[str, Tuple[str, int]] = {}
        for node in _scope_walk(body_nodes):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                t = _dict_type_value(node.value)
                if t is not None:
                    local_frames[node.targets[0].id] = (t, node.lineno)
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.args:
                attr = node.func.attr
                is_send = attr in _SEND_ATTRS
                if attr == "append":
                    base = node.func.value
                    terminal = (base.attr if isinstance(
                        base, ast.Attribute) else
                        base.id if isinstance(base, ast.Name) else "")
                    is_send = terminal in _OUTBOX_NAMES
                if not is_send:
                    continue
                for arg in node.args[:2]:
                    t = _dict_type_value(arg)
                    if t is not None:
                        out.append((t, node.lineno))
                        break
                    if isinstance(arg, ast.Name) \
                            and arg.id in local_frames:
                        t, line = local_frames[arg.id]
                        out.append((t, line))
                        break

    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_scope(node.body)
    scan_scope(sf.tree.body if isinstance(sf.tree, ast.Module) else [])
    # scopes are disjoint (nested defs pruned) but keep the site-dedupe
    # as a cheap invariant anyway
    return list(dict.fromkeys(out))


def _is_type_lookup(node: ast.AST) -> bool:
    """True for ``mtype``, ``msg["type"]`` and ``msg.get("type")``."""
    if isinstance(node, ast.Name) and node.id == "mtype":
        return True
    if isinstance(node, ast.Subscript):
        sl = node.slice
        return str_const(sl) == "type"
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "get" and node.args:
        return str_const(node.args[0]) == "type"
    return False


def _collect_handlers(sf: SourceFile) -> List[Tuple[str, int]]:
    """(mtype, line) for every ``mtype == "literal"`` dispatch comparison."""
    out: List[Tuple[str, int]] = []
    if sf.tree is None:
        return out
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1 \
                or not isinstance(node.ops[0], ast.Eq):
            continue
        sides = (node.left, node.comparators[0])
        for a, b in (sides, sides[::-1]):
            if _is_type_lookup(a):
                v = str_const(b)
                if v is not None:
                    out.append((v, node.lineno))
                break
    return out


def check_protocol(project: Project, config: LintConfig) -> List[Finding]:
    """R1: every sent frame type has a handler on the receiving side, and
    every handler arm has a live sender (no silently-dropped messages, no
    dead dispatch code — in BOTH wire directions)."""
    findings: List[Finding] = []

    head_handlers: Dict[str, Tuple[SourceFile, int]] = {}
    client_handlers: Dict[str, Tuple[SourceFile, int]] = {}
    for rel in config.head_handler_modules:
        sf = project.get(rel)
        if sf is None:
            continue
        for t, line in _collect_handlers(sf):
            head_handlers.setdefault(t, (sf, line))
    for rel in config.clientbound_handler_modules:
        sf = project.get(rel)
        if sf is None:
            continue
        for t, line in _collect_handlers(sf):
            client_handlers.setdefault(t, (sf, line))

    headbound_sends: List[Tuple[SourceFile, str, int]] = []
    clientbound_sends: List[Tuple[SourceFile, str, int]] = []
    excluded = set(config.protocol_exclude)
    for sf in project:
        if sf.relpath in excluded:
            continue
        sends = _collect_sends(sf)
        if sf.relpath in config.clientbound_sender_modules:
            clientbound_sends.extend((sf, t, line) for t, line in sends)
        else:
            headbound_sends.extend((sf, t, line) for t, line in sends)

    sent_to_head = {t for _, t, _ in headbound_sends}
    sent_to_client = {t for _, t, _ in clientbound_sends}

    for sf, t, line in headbound_sends:
        if t not in head_handlers and not sf.suppressed(line, "R1"):
            findings.append(make_finding(
                sf, "R1", line,
                f'frame type "{t}" is sent to the head but has no '
                f'dispatch arm in {" / ".join(config.head_handler_modules)}',
                "add an `elif mtype == ...` handler or delete the send",
                detail=f"unhandled-headbound:{t}"))
    for sf, t, line in clientbound_sends:
        if t not in client_handlers and not sf.suppressed(line, "R1"):
            findings.append(make_finding(
                sf, "R1", line,
                f'frame type "{t}" is sent to clients but no client/worker/'
                f'agent recv loop dispatches on it',
                "add a handler in the receiving loop or delete the send",
                detail=f"unhandled-clientbound:{t}"))
    for t, (sf, line) in sorted(head_handlers.items()):
        if t not in sent_to_head and not sf.suppressed(line, "R1"):
            findings.append(make_finding(
                sf, "R1", line,
                f'dead handler: no module sends frame type "{t}" to the head',
                "delete the dispatch arm (or the sender was lost — restore it)",
                detail=f"dead-head-handler:{t}"))
    for t, (sf, line) in sorted(client_handlers.items()):
        if t not in sent_to_client and not sf.suppressed(line, "R1"):
            findings.append(make_finding(
                sf, "R1", line,
                f'dead handler: the head never sends frame type "{t}" '
                f'to clients',
                "delete the dispatch arm (or the sender was lost — restore it)",
                detail=f"dead-client-handler:{t}"))

    # -- packed hot-frame codec (packed_wire.py) ------------------------
    # Same contract as the Envelope arms, applied to the struct-packed
    # codec: the _FRAME_IDS/_PACK/_UNPACK tables must agree key-for-key
    # (a type in the encoder but not the decoder is a silent wire break),
    # and every packed type needs a live sender and a dispatch arm.
    codec_sf = project.get(getattr(config, "packed_codec_module", "") or "")
    if codec_sf is not None and codec_sf.tree is not None:
        tables: Dict[str, Tuple[Set[str], int]] = {}
        for node in codec_sf.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id in ("_FRAME_IDS", "_PACK",
                                               "_UNPACK") \
                    and isinstance(node.value, ast.Dict):
                keys = {s for s in (str_const(k) for k in node.value.keys
                                    if k is not None) if s is not None}
                tables[node.targets[0].id] = (keys, node.lineno)
        if len(tables) == 3:
            all_types = set().union(*(k for k, _ in tables.values()))
            for t in sorted(all_types):
                for tname, (keys, line) in tables.items():
                    if t not in keys and not codec_sf.suppressed(line, "R1"):
                        findings.append(make_finding(
                            codec_sf, "R1", line,
                            f'packed frame type "{t}" is missing from '
                            f'{tname} (codec tables out of lockstep — a '
                            f'peer would drop or misdecode the frame)',
                            f"add the {tname} entry for it (or remove the "
                            f"type from the other tables)",
                            detail=f"packed-table-skew:{tname}:{t}"))
            ids, ids_line = tables["_FRAME_IDS"]
            handled = set(head_handlers) | set(client_handlers)
            sent = sent_to_head | sent_to_client
            for t in sorted(ids):
                if t not in handled and not codec_sf.suppressed(ids_line, "R1"):
                    findings.append(make_finding(
                        codec_sf, "R1", ids_line,
                        f'packed frame type "{t}" has no dispatch arm in '
                        f'any recv loop (either wire direction)',
                        "add the handler or drop the packed arm",
                        detail=f"packed-unhandled:{t}"))
                if t not in sent and not codec_sf.suppressed(ids_line, "R1"):
                    findings.append(make_finding(
                        codec_sf, "R1", ids_line,
                        f'dead packed arm: no module sends frame type '
                        f'"{t}" on either wire direction',
                        "delete the packed arm (or the sender was lost)",
                        detail=f"packed-dead:{t}"))
    return findings


check_protocol.RULE_ID = "R1"
check_protocol.RULE_NAME = "protocol-consistency"


# ---------------------------------------------------------------------------
# R6 — event-source registry
# ---------------------------------------------------------------------------

def _known_sources(sf: SourceFile) -> Set[str]:
    if sf.tree is None:
        return set()
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "KNOWN_SOURCES" \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            return {s for s in (str_const(e) for e in node.value.elts)
                    if s is not None}
    return set()


def check_event_sources(project: Project, config: LintConfig) -> List[Finding]:
    """R6: every ``events.emit(source, ...)`` literal is declared in
    ``KNOWN_SOURCES`` — an undeclared source is invisible to
    ``ray_tpu events --source`` and the doctor's per-source rules."""
    findings: List[Finding] = []
    events_sf = project.get(config.events_module)
    if events_sf is None:
        return findings
    known = _known_sources(events_sf)
    if not known:
        return findings
    for sf in project:
        if sf.relpath == config.events_module:
            continue
        consts = module_str_constants(sf)
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and fn.attr == "emit"):
                # also accept a bare `emit(...)` imported from events
                if not (isinstance(fn, ast.Name) and fn.id == "emit"):
                    continue
            else:
                # only emit() on an events-module alias — logging handlers
                # etc. also have .emit and must not be checked
                base = dotted_name(fn.value)
                if "events" not in base and base not in ("_events",):
                    continue
            src: Optional[str] = None
            if node.args:
                src = str_const(node.args[0])
                if src is None and isinstance(node.args[0], ast.Name):
                    src = consts.get(node.args[0].id)
            for kw in node.keywords:
                if kw.arg == "source":
                    src = str_const(kw.value)
                    if src is None and isinstance(kw.value, ast.Name):
                        src = consts.get(kw.value.id)
            if src is None:
                continue  # dynamic source: not statically checkable
            if src not in known and not sf.suppressed(node.lineno, "R6"):
                findings.append(make_finding(
                    sf, "R6", node.lineno,
                    f'event source "{src}" is not declared in '
                    f'{config.events_module} KNOWN_SOURCES',
                    "add it to KNOWN_SOURCES (keeps --source discoverable) "
                    "or fix the typo",
                    detail=f"unknown-source:{src}"))
    return findings


check_event_sources.RULE_ID = "R6"
check_event_sources.RULE_NAME = "event-source-registry"


# ---------------------------------------------------------------------------
# R7 — state-API parity
# ---------------------------------------------------------------------------

def check_state_parity(project: Project, config: LintConfig) -> List[Finding]:
    """R7: every ``list_*`` state-API helper resolves to a head-side
    handler AND has a CLI or dashboard surface — a listing nobody can
    reach (or that the head silently 404s) is an API-shaped lie."""
    findings: List[Finding] = []
    api_sf = project.get(config.state_api_module)
    if api_sf is None or api_sf.tree is None:
        return findings

    head_consts: Set[str] = set()
    for rel in config.head_handler_modules:
        sf = project.get(rel)
        if sf is not None:
            head_consts |= all_str_constants(sf)

    surface_consts: Set[str] = set()
    surface_attrs: Set[str] = set()
    for rel in config.state_surface_modules:
        sf = project.get(rel)
        if sf is None or sf.tree is None:
            continue
        surface_consts |= all_str_constants(sf)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Attribute):
                surface_attrs.add(node.attr)
            elif isinstance(node, ast.Name):
                surface_attrs.add(node.id)

    for node in api_sf.tree.body:
        if not isinstance(node, ast.FunctionDef) \
                or not node.name.startswith("list_"):
            continue
        line = node.lineno
        if api_sf.suppressed(line, "R7"):
            continue
        # head token: the "what" passed to the generic list_state page, or
        # the literal "type" of a direct request frame
        tokens: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                fn_name = dotted_name(sub.func)
                if fn_name.split(".")[-1] in ("_list", "list_state_page") \
                        and sub.args:
                    t = str_const(sub.args[0])
                    if t:
                        tokens.add(t)
                t = _dict_type_value(sub.args[0]) if sub.args else None
                if t:
                    tokens.add(t)
            t = _dict_type_value(sub)
            if t:
                tokens.add(t)
        if not tokens:
            continue  # helper delegates elsewhere; nothing checkable
        if not tokens & head_consts:
            findings.append(make_finding(
                api_sf, "R7", line,
                f"state helper {node.name}() requests "
                f"{sorted(tokens)} but the head handles none of them",
                "add the head-side handler (node.py dispatch / table) or "
                "remove the helper",
                detail=f"no-head-handler:{node.name}"))
        what = node.name[len("list_"):]
        if what not in surface_consts and node.name not in surface_attrs \
                and not (tokens & surface_consts):
            findings.append(make_finding(
                api_sf, "R7", line,
                f"state helper {node.name}() has no CLI or dashboard "
                f"surface (not reachable by an operator)",
                "wire it into scripts/cli.py (`ray_tpu list ...`) or a "
                "dashboard endpoint",
                detail=f"no-surface:{node.name}"))
    return findings


check_state_parity.RULE_ID = "R7"
check_state_parity.RULE_NAME = "state-api-parity"
