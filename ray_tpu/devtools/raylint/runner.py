"""raylint runner: parse once, run rules, apply suppressions + baseline.

The CLI (``ray_tpu lint``), the tier-1 gate (``tests/test_raylint.py``)
and ``ray_tpu doctor --static`` all call :func:`run_gate`; fixture tests
call :func:`analyze` with a custom :class:`LintConfig` pointing at a
miniature project.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ray_tpu.devtools.raylint.core import (
    Finding, LintConfig, Project, baseline_path, load_baseline,
    save_baseline, split_new,
)


def analyze(config: LintConfig,
            rules: Optional[Sequence[str]] = None,
            project: Optional[Project] = None) -> List[Finding]:
    """Run the selected rules (default: all) over the configured file
    set and return line-suppression-filtered findings, sorted."""
    from ray_tpu.devtools.raylint import RULES

    if project is None:
        project = Project(config.root, config.iter_paths())
    selected = list(rules) if rules else sorted(RULES)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ValueError(f"unknown rule id(s): {unknown} "
                         f"(have {sorted(RULES)})")
    findings: List[Finding] = []
    for rid in selected:
        findings.extend(RULES[rid](project, config))
    # rules are expected to honor suppressions themselves at the best
    # line; enforce centrally too so no rule can forget
    kept = []
    for f in findings:
        sf = project.get(f.path)
        if sf is not None and sf.suppressed(f.line, f.rule):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.detail))
    return kept


@dataclass
class GateResult:
    findings: List[Finding]      # everything the rules produced
    new: List[Finding]           # not covered by the baseline -> gate
    baselined: List[Finding]     # grandfathered (burn these down)
    stale_keys: List[str]        # baseline entries that no longer fire

    @property
    def ok(self) -> bool:
        # stale keys fail the gate too: the baseline only burns down —
        # a fixed finding must take its grandfather entry with it
        return not self.new and not self.stale_keys


def run_gate(root: str,
             rules: Optional[Sequence[str]] = None,
             config: Optional[LintConfig] = None,
             update_baseline: bool = False,
             project=None) -> GateResult:
    """The CI gate: findings beyond the checked-in baseline fail.

    With ``update_baseline`` the CURRENT full-rule findings become the
    new baseline (never run with a rule subset — a partial run would
    erase other rules' grandfathered entries).
    """
    if update_baseline and rules:
        raise ValueError(
            "--update-baseline requires a full-rule run (a subset "
            "would erase other rules' baseline entries)")
    config = config or LintConfig(root=root)
    findings = analyze(config, rules=rules, project=project)
    bpath = baseline_path(root)
    if update_baseline:
        save_baseline(bpath, findings)
        return GateResult(findings=findings, new=[], baselined=findings,
                          stale_keys=[])
    baseline = load_baseline(bpath)
    # with a rule subset, only compare against that subset's keys
    if rules:
        prefixes = tuple(f"{r}|" for r in rules)
        baseline = {k: v for k, v in baseline.items()
                    if k.startswith(prefixes)}
    new, old = split_new(findings, baseline)
    fired = {}
    for f in findings:
        fired[f.baseline_key()] = fired.get(f.baseline_key(), 0) + 1
    stale = sorted(k for k, n in baseline.items()
                   if fired.get(k, 0) < n)
    return GateResult(findings=findings, new=new, baselined=old,
                      stale_keys=stale)


def render_report(result: GateResult, verbose: bool = False) -> str:
    """Human-readable gate report (what ``ray_tpu lint`` prints)."""
    out: List[str] = []
    for f in result.new:
        out.append(f.render())
    if result.new:
        out.append("")
    out.append(
        f"raylint: {len(result.new)} new finding(s), "
        f"{len(result.baselined)} baselined, "
        f"{len(result.stale_keys)} stale baseline entr(y/ies)")
    if verbose and result.baselined:
        out.append("baselined (burn these down):")
        for f in result.baselined:
            out.append("  " + f.render().replace("\n", "\n  "))
    if result.stale_keys:
        out.append("stale baseline keys (fixed — remove with "
                   "--update-baseline):")
        for k in result.stale_keys:
            out.append(f"  {k}")
    return "\n".join(out)


def to_json(result: GateResult) -> Dict[str, object]:
    return {
        "new": [f.to_dict() for f in result.new],
        "baselined": [f.to_dict() for f in result.baselined],
        "stale_baseline_keys": list(result.stale_keys),
        "ok": result.ok,
    }
