"""raylint core: findings, the parsed-project model, suppressions, baseline.

The suite is an AST-based invariant checker distilled from this repo's own
postmortems (the analog of the reference's clang thread-safety annotations +
TSan wiring — mechanical enforcement of project invariants instead of
re-finding the same bug classes by hand every few PRs).  Everything here is
stdlib-only: ``ast`` for parsing, ``json`` for the baseline.

Vocabulary:

- A **rule** is a callable ``check(project, config) -> List[Finding]`` with
  ``RULE_ID``/``RULE_NAME`` attributes (registered in ``__init__.RULES``).
- A **Finding** carries ``file:line``, the rule id, a one-line message and a
  one-line remedy.  Its :meth:`Finding.baseline_key` intentionally excludes
  the line number so the checked-in baseline survives unrelated edits.
- ``# raylint: disable=R4`` on the flagged line (or alone on the line above)
  suppresses a finding at the source; ``raylint_baseline.json`` grandfathers
  existing findings so the CI gate only fails on NEW ones.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_SUPPRESS_MARK = "# raylint: disable"


@dataclass
class Finding:
    rule: str          # "R1".."R8"
    path: str          # repo-relative, forward slashes
    line: int
    message: str       # one line: what is wrong, with names
    remedy: str        # one line: how to fix it
    # stable identity for the baseline: defaults to the message, but rules
    # set it to something line-number- and phrasing-free when the message
    # embeds positions of OTHER code (e.g. "shadowed by handler at :114")
    detail: str = ""
    scope: str = ""    # enclosing "Class.method" (or "<module>")

    def baseline_key(self) -> str:
        return "|".join(
            (self.rule, self.path, self.scope, self.detail or self.message))

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def render(self) -> str:
        return (f"{self.location()}: {self.rule} {self.message}\n"
                f"    remedy: {self.remedy}")

    def to_dict(self) -> dict:
        return {"rule": self.rule, "file": self.path, "line": self.line,
                "message": self.message, "remedy": self.remedy,
                "scope": self.scope, "key": self.baseline_key()}


class SourceFile:
    """One parsed module: source lines, AST, per-line suppressions."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(source, filename=relpath)
        except SyntaxError as e:
            self.parse_error = e
        # line -> set of suppressed rule ids ("*" = all)
        self.suppressions: Dict[int, Set[str]] = self._scan_suppressions()
        self._scopes: Optional[List[Tuple[int, int, str]]] = None

    # -- suppressions ------------------------------------------------------
    def _scan_suppressions(self) -> Dict[int, Set[str]]:
        # real COMMENT tokens only: the marker inside a string literal or
        # docstring (e.g. documentation QUOTING the syntax) must not
        # register a suppression — a phantom "*" entry would silently
        # mask genuine findings on that line
        out: Dict[int, Set[str]] = {}
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return out  # unparseable file: no tree, nothing to suppress
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            idx = tok.string.find(_SUPPRESS_MARK)
            if idx < 0:
                continue
            spec = tok.string[idx + len(_SUPPRESS_MARK):].strip()
            if spec.startswith("="):
                # "=R3,R4" — a trailing rationale is allowed and ignored:
                # "# raylint: disable=R3 (one-shot path)".  The rationale
                # starts at the first "(" and ids stop at the first token
                # that isn't R<n>/"*" — a comma inside the rationale must
                # not register prose words (or an R<n> the rationale
                # merely MENTIONS) as extra suppressed rules
                rules = set()
                for part in spec[1:].split("(", 1)[0].split(","):
                    m = re.match(r"(R\d+|\*)(?:\s+(.*))?$", part.strip())
                    if not m:
                        if part.strip():
                            break
                        continue
                    rules.add(m.group(1))
                    if m.group(2):
                        break  # id then prose: bare rationale — stop
            else:
                rules = {"*"}
            row, col = tok.start
            target = row
            # a directive alone on its own line covers the NEXT line
            if self.lines[row - 1][:col].strip() == "":
                target = row + 1
            out.setdefault(target, set()).update(rules)
        return out

    def suppressed(self, line: int, rule: str) -> bool:
        rules = self.suppressions.get(line)
        return bool(rules) and (rule in rules or "*" in rules)

    # -- scopes ------------------------------------------------------------
    def scope_at(self, line: int) -> str:
        """Innermost ``Class.method`` enclosing ``line`` (baseline keys)."""
        if self._scopes is None:
            spans: List[Tuple[int, int, str]] = []

            def walk(node: ast.AST, prefix: str) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        name = (prefix + "." if prefix else "") + child.name
                        end = getattr(child, "end_lineno", child.lineno)
                        spans.append((child.lineno, end, name))
                        walk(child, name)
                    else:
                        walk(child, prefix)

            if self.tree is not None:
                walk(self.tree, "")
            spans.sort(key=lambda s: (s[0], -s[1]))
            self._scopes = spans
        best = "<module>"
        for start, end, name in self._scopes:
            if start <= line <= end:
                best = name  # later entries are inner scopes
        return best


class Project:
    """The analyzed file set, parsed once and shared by every rule."""

    def __init__(self, root: str, relpaths: Sequence[str]):
        self.root = os.path.abspath(root)
        self.files: Dict[str, SourceFile] = {}
        for rel in relpaths:
            full = os.path.join(self.root, rel)
            try:
                with tokenize.open(full) as f:   # honors coding cookies
                    src = f.read()
            except (OSError, UnicodeDecodeError, SyntaxError):
                continue
            self.files[rel.replace(os.sep, "/")] = SourceFile(
                rel.replace(os.sep, "/"), src)

    def get(self, relpath: str) -> Optional[SourceFile]:
        return self.files.get(relpath)

    def __iter__(self) -> Iterable[SourceFile]:
        return iter(self.files.values())


@dataclass
class LintConfig:
    """Where the project-specific invariants live.

    The defaults describe THIS repo (module roles for the protocol rule,
    hot-path membership for the entropy rule, ...).  Fixture tests build
    configs pointing at miniature projects instead.
    """

    root: str
    package: str = "ray_tpu"
    # R1 — protocol consistency.  The control wire has two directions:
    # head-bound frames (everyone -> node.py's dispatch chains) and
    # client-bound frames (node.py/dashboard -> the client/worker/agent
    # recv loops).  Modules listed as clientbound senders have their sends
    # checked against the clientbound handler chains; everything else's
    # sends are checked against the head's chains.
    head_handler_modules: Tuple[str, ...] = (
        "ray_tpu/_private/node.py",
        # the client proxy is a server on the same direction: clients send
        # proxy_hello AT it and it dispatches like the head does
        "ray_tpu/util/client/proxier.py",
    )
    clientbound_handler_modules: Tuple[str, ...] = (
        "ray_tpu/_private/client.py",
        "ray_tpu/_private/worker.py",
        "ray_tpu/_private/node_agent.py",
    )
    clientbound_sender_modules: Tuple[str, ...] = (
        "ray_tpu/_private/node.py",
        "ray_tpu/dashboard/dashboard.py",
        # the chaos harness runs IN the head process and injects faults
        # over the agents' control connections (agent_send) — its frames
        # go head -> agent, same direction as node.py's
        "ray_tpu/devtools/chaos/harness.py",
        # the proxy answers the client's handshake: proxy_ready/proxy_error
        # flow proxy -> client and are dispatched in client.py (the tenant
        # relay in util/client/driver.py forwards only variable frames)
        "ray_tpu/util/client/proxier.py",
    )
    # the codecs rebuild frames from the wire — their dict literals are
    # not send sites, and their tables must not count as senders
    protocol_exclude: Tuple[str, ...] = (
        "ray_tpu/_private/wire.py",
        "ray_tpu/_private/packed_wire.py",
    )
    # R1 also checks the packed hot-frame codec: its _FRAME_IDS/_PACK/
    # _UNPACK tables must stay in lockstep (a frame type added to the
    # encoder but not the decoder is a silent wire break) and every
    # packed type must have live send sites and dispatch arms in BOTH
    # wire directions, exactly like the Envelope arms
    packed_codec_module: str = "ray_tpu/_private/packed_wire.py"
    # R3 — modules on the task submit/dispatch path where per-task entropy
    # (uuid4/urandom ~200us on this kernel) costs whole-percent throughput
    hot_path_modules: Tuple[str, ...] = (
        "ray_tpu/_private/node.py",
        "ray_tpu/_private/worker.py",
        "ray_tpu/_private/client.py",
        "ray_tpu/_private/object_ref.py",
        "ray_tpu/_private/object_store.py",
        "ray_tpu/_private/events.py",
        "ray_tpu/util/tracing.py",
        "ray_tpu/util/metrics.py",
        "ray_tpu/dag/compiled.py",
        "ray_tpu/dag/channel.py",
        "ray_tpu/serve/_private/router.py",
    )
    # R5 — head-resident modules whose containers live as long as the
    # cluster: growth without a cap/expiry/eviction is a slow head OOM
    head_container_modules: Tuple[str, ...] = (
        "ray_tpu/_private/node.py",
        "ray_tpu/_private/events.py",
        "ray_tpu/_private/object_store.py",
        "ray_tpu/util/tsdb.py",
        "ray_tpu/util/metrics.py",
    )
    # R6 — the flight-recorder source registry
    events_module: str = "ray_tpu/_private/events.py"
    # R7 — state API parity
    state_api_module: str = "ray_tpu/experimental/state/api.py"
    state_surface_modules: Tuple[str, ...] = (
        "ray_tpu/scripts/cli.py",
        "ray_tpu/dashboard/dashboard.py",
    )
    # extra per-config knobs rules may consult
    extras: Dict[str, object] = field(default_factory=dict)

    def iter_paths(self) -> List[str]:
        """Repo-relative .py paths to lint (the package, minus caches)."""
        out: List[str] = []
        pkg_root = os.path.join(self.root, self.package)
        for dirpath, dirnames, filenames in os.walk(pkg_root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".pytest_cache")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, fn), self.root))
        return sorted(out)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def baseline_path(root: str) -> str:
    return os.path.join(root, "raylint_baseline.json")


def load_baseline(path: str) -> Dict[str, int]:
    """key -> allowed count (the multiset of grandfathered findings)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    counts: Dict[str, int] = {}
    for key in data.get("findings", []):
        counts[key] = counts.get(key, 0) + 1
    return counts


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    keys = sorted(f.baseline_key() for f in findings)
    with open(path, "w") as f:
        json.dump({"version": 1,
                   "comment": ("grandfathered raylint findings; burn this "
                               "down — new findings always gate"),
                   "findings": keys}, f, indent=1)
        f.write("\n")


def split_new(findings: Sequence[Finding],
              baseline: Dict[str, int]) -> Tuple[List[Finding], List[Finding]]:
    """(new, baselined): occurrences beyond a key's baseline count are new."""
    remaining = dict(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        k = f.baseline_key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


# ---------------------------------------------------------------------------
# shared AST helpers used by several rules
# ---------------------------------------------------------------------------

def call_name(node: ast.Call) -> str:
    """Dotted name of a call target: ``time.sleep`` / ``sorted`` / ``.wait``
    (leading dot = method on a non-Name object)."""
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return (base + "." + node.attr) if base else "." + node.attr
    return ""


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def module_str_constants(sf: SourceFile) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` assignments (constant resolution
    for e.g. ``_SOURCE = "compiled_dag"`` passed to ``events.emit``)."""
    out: Dict[str, str] = {}
    if sf.tree is None:
        return out
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v = str_const(node.value)
            if v is not None:
                out[node.targets[0].id] = v
    return out


def all_str_constants(sf: SourceFile) -> Set[str]:
    out: Set[str] = set()
    if sf.tree is None:
        return out
    for node in ast.walk(sf.tree):
        v = str_const(node)
        if v is not None:
            out.add(v)
    return out


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def make_finding(sf: SourceFile, rule: str, line: int, message: str,
                 remedy: str, detail: str = "") -> Finding:
    return Finding(rule=rule, path=sf.relpath, line=line, message=message,
                   remedy=remedy, detail=detail, scope=sf.scope_at(line))
