"""R3 — hot-path entropy, R8 — bare-thread hygiene.

R3: on this kernel one ``os.urandom`` read costs ~200us, so
``uuid4()``-per-task cost ~30% of task throughput before PR 4 replaced
ids with process-prefix counters.  The rule keeps entropy calls out of
the modules on the submit/dispatch path; one-shot module-level seeding
(import time) is explicitly fine.

R8: a ``threading.Thread`` with neither ``daemon=`` nor a ``.join()``
anywhere in the module is a shutdown hang (non-daemon default) waiting
for its first unlucky teardown ordering.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from ray_tpu.devtools.raylint.core import (
    Finding, LintConfig, Project, SourceFile, dotted_name, make_finding,
    parent_map,
)

# call targets that read kernel entropy (directly or transitively)
_ENTROPY_CALLS = {
    "uuid.uuid4", "uuid4", "uuid.uuid1", "uuid1",
    "os.urandom", "urandom",
    "secrets.token_bytes", "secrets.token_hex", "secrets.token_urlsafe",
    "secrets.randbits", "secrets.choice",
    "random.SystemRandom",
}


def _enclosing_function_lines(tree: ast.AST) -> Set[int]:
    """Line numbers that live inside some function body (module-level
    lines — one-shot import-time work — are the complement)."""
    lines: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            lines.update(range(node.lineno, end + 1))
    return lines


def check_hot_path_entropy(project: Project,
                           config: LintConfig) -> List[Finding]:
    findings: List[Finding] = []
    for rel in config.hot_path_modules:
        sf = project.get(rel)
        if sf is None or sf.tree is None:
            continue
        fn_lines = _enclosing_function_lines(sf.tree)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name not in _ENTROPY_CALLS:
                continue
            if node.lineno not in fn_lines:
                continue  # module-level: runs once at import, fine
            if sf.suppressed(node.lineno, "R3"):
                continue
            findings.append(make_finding(
                sf, "R3", node.lineno,
                f"{name}() on the task submit/dispatch path "
                f"(~200us/urandom on this kernel; uuid4-per-task cost "
                f"~30% of throughput before PR 4)",
                "use a process-prefix counter id (util/tracing.py "
                "pattern) or hoist the entropy to import time",
                detail=f"entropy:{name}"))
    return findings


check_hot_path_entropy.RULE_ID = "R3"
check_hot_path_entropy.RULE_NAME = "hot-path-entropy"


# ---------------------------------------------------------------------------
# R8 — bare-thread hygiene
# ---------------------------------------------------------------------------

def _is_thread_ctor(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    return name == "threading.Thread" or name == "Thread"


def _joined_or_daemoned_names(tree: ast.AST) -> Set[str]:
    """Terminal attribute/variable names X for which ``X.join(...)`` or
    ``X.daemon = ...`` appears anywhere in the module."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr == "join":
            base = node.func.value
            if isinstance(base, ast.Name):
                out.add(base.id)
            elif isinstance(base, ast.Attribute):
                out.add(base.attr)
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon":
                    base = t.value
                    if isinstance(base, ast.Name):
                        out.add(base.id)
                    elif isinstance(base, ast.Attribute):
                        out.add(base.attr)
    # `for t in threads: t.join()` — a join on any loop variable also
    # blesses the list it iterates (conservative: collect loop targets)
    return out


def _assign_target_name(parents: Dict[ast.AST, ast.AST],
                        node: ast.AST) -> str:
    """Terminal name the Thread() result is bound to ('' if unbound)."""
    p = parents.get(node)
    while p is not None and isinstance(p, (ast.Await,)):
        node, p = p, parents.get(p)
    if isinstance(p, ast.Assign) and len(p.targets) == 1:
        t = p.targets[0]
        if isinstance(t, ast.Name):
            return t.id
        if isinstance(t, ast.Attribute):
            return t.attr
    if isinstance(p, (ast.List, ast.Tuple)):
        # thread appended into a literal list: bless via the list name
        pp = parents.get(p)
        if isinstance(pp, ast.Assign) and len(pp.targets) == 1:
            t = pp.targets[0]
            if isinstance(t, ast.Name):
                return t.id
            if isinstance(t, ast.Attribute):
                return t.attr
    if isinstance(p, ast.Call) and isinstance(p.func, ast.Attribute) \
            and p.func.attr == "append":
        base = p.func.value
        if isinstance(base, ast.Name):
            return base.id
        if isinstance(base, ast.Attribute):
            return base.attr
    return ""


def check_bare_threads(project: Project, config: LintConfig) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project:
        if sf.tree is None:
            continue
        blessed = _joined_or_daemoned_names(sf.tree)
        # `for t in ts: t.join()` blesses ts too
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.For) and isinstance(node.target,
                                                        ast.Name):
                loopvar = node.target.id
                if loopvar in blessed:
                    it = node.iter
                    if isinstance(it, ast.Name):
                        blessed.add(it.id)
                    elif isinstance(it, ast.Attribute):
                        blessed.add(it.attr)
        parents = parent_map(sf.tree)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not _is_thread_ctor(node):
                continue
            if any(kw.arg == "daemon" for kw in node.keywords):
                continue
            target = _assign_target_name(parents, node)
            if target and target in blessed:
                continue
            if sf.suppressed(node.lineno, "R8"):
                continue
            findings.append(make_finding(
                sf, "R8", node.lineno,
                "threading.Thread without daemon= and without a .join() "
                "in this module (non-daemon default = shutdown hang)",
                "pass daemon=True, or join it on the teardown path",
                detail=f"bare-thread:{target or '<unbound>'}"))
    return findings


check_bare_threads.RULE_ID = "R8"
check_bare_threads.RULE_NAME = "bare-thread-hygiene"
