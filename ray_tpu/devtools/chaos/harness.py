"""ChaosMonkey: host-level fault injection against emulated node agents.

Runs IN the head process (it signals local agent subprocesses and uses the
head's tables to resolve slice membership).  Four ops:

``sigkill``
    SIGKILL the agent process — the canonical slice-member death.  The
    kernel closes its sockets: the head sees the control-connection EOF,
    and mesh peers see connection-refused (both detection paths fire).
``pause``
    SIGSTOP for ``duration_s`` then SIGCONT — a hung host.  TCP stays
    open, so ONLY the timeout paths (missed pongs, peer suspect quorum)
    can catch it.
``drop``
    Ask the agent to drop a fraction of its *outbound* control messages
    for a window (the agent's ``chaos_drop`` arm) — a lossy/partitioned
    head link while the P2P mesh stays healthy.
``slow``
    Duty-cycled SIGSTOP/SIGCONT for ``duration_s`` — a straggler host
    (doctor's slow_node_skew food).
``kill_replica``
    SIGKILL one serve replica's worker process (resolved through the
    serve controller's routing table + the replica's own pid) — the
    serving-failure-domain injection: the ingress must retry idempotent
    in-flight requests to a live replica and the controller must
    replace the dead one, with zero client-visible 500s.

Every injection lands in the flight recorder under source ``chaos`` with
the op, target, slice and seed, so a post-mortem reads "what did the
harness do and when" next to "what did the runtime see".
"""

from __future__ import annotations

import os
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu._private import events as events_mod


@dataclass
class Injection:
    """One scheduled fault: fire ``op`` at ``at_s`` (relative to
    ``ChaosMonkey.start``) on ``target`` — or on a seeded-random alive
    member of ``slice_id`` when ``target`` is None."""

    at_s: float
    op: str  # sigkill | pause | drop | slow | kill_replica
    target: Optional[str] = None
    slice_id: Optional[str] = None
    duration_s: float = 5.0
    frac: float = 1.0   # drop only
    duty: float = 0.5   # slow only: fraction of each 100ms period stopped
    params: Dict = field(default_factory=dict)


class ChaosMonkey:
    """Injects faults into node-agent processes by pid.

    ``procs`` maps node_id -> a Popen-like object (``.pid``/``.poll()``)
    or a bare pid; pass ``cluster.agents`` (cluster_utils) or
    ``provider.procs`` (LocalNodeProvider).  ``node`` is the head Node
    (defaults to the connected driver's) — used for slice-membership
    targeting and the ``drop`` op's control message.
    """

    def __init__(self, node=None, procs: Optional[Dict] = None,
                 seed: int = 0, schedule: Optional[List[Injection]] = None):
        if node is None:
            from ray_tpu._private.worker import global_worker

            node = global_worker.node
        self.node = node
        self.procs = procs or {}
        self.seed = seed
        import random

        self._rng = random.Random(seed)
        self.schedule = sorted(schedule or [], key=lambda i: i.at_s)
        self.injections: List[dict] = []  # what actually fired, in order
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._timers: List[threading.Thread] = []

    # -- targeting -----------------------------------------------------
    def members_of_slice(self, slice_id: str,
                         alive_only: bool = True) -> List[str]:
        with self.node.lock:
            return sorted(
                ns.node_id for ns in self.node.nodes.values()
                if ns.slice_id == slice_id and (ns.alive or not alive_only))

    def pick(self, slice_id: Optional[str] = None) -> str:
        """A seeded-random target: an alive member of ``slice_id``, or any
        alive node present in the pid map."""
        if slice_id is not None:
            cands = [n for n in self.members_of_slice(slice_id)
                     if self._pid(n) is not None]
        else:
            with self.node.lock:
                alive = {ns.node_id for ns in self.node.nodes.values()
                         if ns.alive}
            cands = sorted(n for n in self.procs if n in alive
                           and self._pid(n) is not None)
        if not cands:
            raise RuntimeError(
                f"chaos: no targetable node (slice={slice_id!r})")
        return self._rng.choice(cands)

    def _pid(self, node_id: str) -> Optional[int]:
        proc = self.procs.get(node_id)
        if proc is None:
            return None
        pid = getattr(proc, "pid", proc)
        poll = getattr(proc, "poll", None)
        if poll is not None and poll() is not None:
            return None  # already dead
        return int(pid)

    def _record(self, op: str, target: str, **data) -> dict:
        rec = {"op": op, "target": target, "ts": time.time(), **data}
        self.injections.append(rec)
        events_mod.emit(
            "chaos", f"inject {op}", severity="WARNING", entity_id=target,
            op=op, seed=self.seed, **data)
        return rec

    # -- ops -----------------------------------------------------------
    def sigkill(self, node_id: str,
                slice_id: Optional[str] = None) -> dict:
        pid = self._pid(node_id)
        if pid is None:
            raise RuntimeError(f"chaos: no live process for {node_id}")
        os.kill(pid, signal.SIGKILL)
        return self._record("sigkill", node_id, pid=pid,
                            slice_id=slice_id or self._slice_of(node_id))

    def pause(self, node_id: str, duration_s: float = 5.0) -> dict:
        pid = self._pid(node_id)
        if pid is None:
            raise RuntimeError(f"chaos: no live process for {node_id}")
        os.kill(pid, signal.SIGSTOP)
        rec = self._record("pause", node_id, pid=pid, duration_s=duration_s,
                           slice_id=self._slice_of(node_id))
        self._after(duration_s, lambda: self._resume(pid, node_id))
        return rec

    def _resume(self, pid: int, node_id: str) -> None:
        try:
            os.kill(pid, signal.SIGCONT)
            self._record("resume", node_id, pid=pid)
        except ProcessLookupError:
            pass  # died (or was removed+killed) while paused

    def drop_messages(self, node_id: str, frac: float = 1.0,
                      duration_s: float = 5.0) -> dict:
        with self.node.lock:
            ns = self.node.nodes.get(node_id)
            if ns is None or ns.agent_conn is None:
                raise RuntimeError(
                    f"chaos: {node_id} has no agent connection to drop on")
        ns.agent_send({"type": "chaos_drop", "frac": float(frac),
                       "duration_s": float(duration_s), "seed": self.seed})
        return self._record("drop", node_id, frac=frac,
                            duration_s=duration_s,
                            slice_id=self._slice_of(node_id))

    def slow_node(self, node_id: str, duration_s: float = 5.0,
                  duty: float = 0.5) -> dict:
        pid = self._pid(node_id)
        if pid is None:
            raise RuntimeError(f"chaos: no live process for {node_id}")
        rec = self._record("slow", node_id, pid=pid, duration_s=duration_s,
                           duty=duty, slice_id=self._slice_of(node_id))

        def cycle():
            period = 0.1
            deadline = time.monotonic() + duration_s
            try:
                while time.monotonic() < deadline and not self._stop.is_set():
                    os.kill(pid, signal.SIGSTOP)
                    time.sleep(period * duty)
                    os.kill(pid, signal.SIGCONT)
                    time.sleep(period * (1.0 - duty))
            except ProcessLookupError:
                return
            finally:
                try:
                    os.kill(pid, signal.SIGCONT)
                except ProcessLookupError:
                    pass

        self._spawn(cycle)
        return rec

    def kill_serve_replica(self, deployment: str,
                           controller=None,
                           replica_tag: Optional[str] = None) -> dict:
        """SIGKILL one replica of a serve deployment (seeded-random among
        RUNNING replicas unless ``replica_tag`` pins one).  The pid comes
        from the replica itself (``stats()``), so this works for local
        and emulated-multihost replicas alike — the worker process just
        dies, exactly like a preempted host."""
        import ray_tpu
        from ray_tpu.serve._private.controller import (
            CONTROLLER_NAME, SERVE_NAMESPACE)

        if controller is None:
            controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                           namespace=SERVE_NAMESPACE)
        info = ray_tpu.get(
            controller.get_routing_info.remote(deployment), timeout=10)
        if not info or not info["replicas"]:
            raise RuntimeError(
                f"chaos: deployment {deployment!r} has no RUNNING replica")
        replicas = sorted(info["replicas"], key=lambda rh: rh[0])
        if replica_tag is not None:
            cands = [rh for rh in replicas if rh[0] == replica_tag]
            if not cands:
                raise RuntimeError(
                    f"chaos: no RUNNING replica {replica_tag!r}")
            tag, handle = cands[0]
        else:
            tag, handle = self._rng.choice(replicas)
        stats = ray_tpu.get(handle.stats.remote(), timeout=10)
        pid = int(stats["pid"])
        os.kill(pid, signal.SIGKILL)
        return self._record("kill_replica", tag, pid=pid,
                            deployment=deployment)

    def kill_tenant_driver(self, job_id: Optional[str] = None,
                           namespace: Optional[str] = None) -> dict:
        """SIGKILL one tenant's proxied driver subprocess (the isolated
        per-connection driver the client proxy spawned).  The pid comes
        from the head's tenant directory — the driver registered it at
        connect — so the kill is indistinguishable from the tenant's
        driver host dying: the head sees the client connection drop and
        reaps everything the job owned while other tenants keep running."""
        with self.node.lock:
            cands = [dict(rec) for rec in self.node._jobs.values()
                     if rec["alive"] and rec.get("proxied") and rec.get("pid")
                     and (job_id is None or rec["job_id"] == job_id)
                     and (namespace is None
                          or rec["namespace"] == namespace)]
        if not cands:
            raise RuntimeError(
                f"chaos: no live proxied tenant driver "
                f"(job_id={job_id!r}, namespace={namespace!r})")
        rec = (cands[0] if job_id or namespace
               else self._rng.choice(sorted(cands, key=lambda r: r["job_id"])))
        os.kill(int(rec["pid"]), signal.SIGKILL)
        return self._record("kill_tenant_driver", rec["job_id"],
                            pid=rec["pid"], namespace=rec["namespace"])

    def _slice_of(self, node_id: str) -> Optional[str]:
        with self.node.lock:
            ns = self.node.nodes.get(node_id)
            return ns.slice_id if ns is not None else None

    # -- schedule execution --------------------------------------------
    def start(self) -> "ChaosMonkey":
        self._thread = threading.Thread(target=self._run_schedule,
                                        daemon=True, name="chaos-monkey")
        self._thread.start()
        return self

    def _run_schedule(self) -> None:
        t0 = time.monotonic()
        for inj in self.schedule:
            delay = inj.at_s - (time.monotonic() - t0)
            if delay > 0 and self._stop.wait(delay):
                return
            try:
                self.inject(inj)
            except Exception as e:  # noqa: BLE001 — a missed injection
                # (target already dead) must not abort the schedule
                events_mod.emit("chaos", "injection failed",
                                severity="WARNING", entity_id=inj.target,
                                op=inj.op, error=str(e)[:200])

    def inject(self, inj: Injection) -> dict:
        if inj.op == "kill_replica":
            # target names the DEPLOYMENT; the replica is seeded-random
            return self.kill_serve_replica(
                inj.target, replica_tag=inj.params.get("replica_tag"))
        if inj.op == "kill_tenant_driver":
            # target names the tenant JOB (empty = seeded-random tenant)
            return self.kill_tenant_driver(
                job_id=inj.target or None,
                namespace=inj.params.get("namespace"))
        target = inj.target or self.pick(inj.slice_id)
        if inj.op == "sigkill":
            return self.sigkill(target, slice_id=inj.slice_id)
        if inj.op == "pause":
            return self.pause(target, inj.duration_s)
        if inj.op == "drop":
            return self.drop_messages(target, inj.frac, inj.duration_s)
        if inj.op == "slow":
            return self.slow_node(target, inj.duration_s, inj.duty)
        raise ValueError(f"unknown chaos op {inj.op!r}")

    def _after(self, delay: float, fn) -> None:
        def run():
            if not self._stop.wait(delay):
                fn()

        self._spawn(run)

    def _spawn(self, fn) -> None:
        t = threading.Thread(target=fn, daemon=True, name="chaos-op")
        t.start()
        self._timers.append(t)

    def stop(self) -> None:
        """Cancel pending schedule entries and resume anything paused
        (a SIGSTOPPED child outliving the test wedges process teardown)."""
        self._stop.set()
        for rec in self.injections:
            if rec["op"] == "pause":
                try:
                    os.kill(rec["pid"], signal.SIGCONT)
                except (ProcessLookupError, PermissionError):
                    pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        for t in self._timers:
            t.join(timeout=1)
