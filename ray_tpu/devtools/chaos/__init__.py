"""Seeded, schedulable fault injection for emulated multi-node clusters.

The slice failure domain can only be *proven* by killing things on
purpose: this package injects host-level faults (SIGKILL, SIGSTOP pause,
outbound message drop, duty-cycled slow node) into real node-agent
processes, targeted by node id or by slice membership, on a reproducible
seeded schedule — and emits every injection to the flight recorder
(source ``chaos``) so ``ray_tpu doctor``, ``ray_tpu events`` and the
timeline can correlate cause with symptom.

Reference analog: ``python/ray/_private/test_utils.py`` NodeKillerActor
family, grown into a harness (``get_and_run_resource_killer``).
"""

from ray_tpu.devtools.chaos.harness import (  # noqa: F401
    ChaosMonkey,
    Injection,
)

__all__ = ["ChaosMonkey", "Injection"]
