"""Versioned wire IDL for the control plane (reference:
``src/ray/protobuf/`` — SURVEY L0).

``ray_tpu.proto`` defines the Envelope every control-plane frame
serializes to; ``ray_tpu_pb2.py`` is the checked-in protoc output
(regenerate with ``make``).  The dict<->proto translation and the
connection wrapper live in ``ray_tpu._private.wire``.
"""

from ray_tpu.protocol import ray_tpu_pb2  # noqa: F401
