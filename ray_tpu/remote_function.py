"""``@remote`` functions (analog of ``python/ray/remote_function.py``).

``RemoteFunction._remote`` (reference ``remote_function.py:239``) builds a
task spec and submits it through the core client; ``.options(...)`` returns
a shallow override wrapper, same surface as the reference.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

import cloudpickle

from ray_tpu._private import ray_option_utils
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.worker import global_worker


class RemoteFunction:
    def __init__(self, fn, default_options: Dict[str, Any]):
        self._function = fn
        self._default_options = ray_option_utils.validate_options(default_options, for_actor=False)
        self._fn_blob: Optional[bytes] = None
        self._fn_id: Optional[bytes] = None
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._function.__name__} cannot be called directly; "
            f"use {self._function.__name__}.remote(...)"
        )

    def options(self, **options) -> "_RemoteFunctionWrapper":
        merged = dict(self._default_options)
        merged.update(ray_option_utils.validate_options(options, for_actor=False))
        return _RemoteFunctionWrapper(self, merged)

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._default_options)

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node instead of submitting (``ray.dag``)."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def _remote(self, args, kwargs, options: Dict[str, Any]):
        w = global_worker
        if not w.connected:
            import threading

            if threading.current_thread() is not threading.main_thread():
                # a BACKGROUND thread submitting after shutdown (e.g. a
                # stale poller from a torn-down session) must never boot a
                # fresh default session — that zombie head silently absorbs
                # every later init() in the process
                raise RuntimeError(
                    "ray_tpu is not initialized (auto-init only runs on "
                    "the main thread)")
            import ray_tpu

            ray_tpu.init()
        if self._fn_id is None:
            self._fn_blob = cloudpickle.dumps(self._function)
        self._fn_id = w.register_function(self._fn_blob)
        num_returns = options.get("num_returns", 1)
        dynamic = num_returns == "dynamic"
        resources = ray_option_utils.resources_from_options(options, default_num_cpus=1)
        strategy = _strategy_to_dict(options.get("scheduling_strategy"))
        spec, return_refs = w.build_task_spec(
            name=options.get("name") or self._function.__name__,
            fn_id=self._fn_id,
            args=args,
            kwargs=kwargs,
            num_returns=1 if dynamic else num_returns,
            resources=resources,
            scheduling_strategy=strategy,
            max_retries=options.get("max_retries", 3),
            runtime_env=options.get("runtime_env"),
        )
        if dynamic:
            spec["dynamic_returns"] = True
        w.client.submit_task(spec)
        if dynamic:
            from ray_tpu._private.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(
                task_id=spec["task_id"], primary=return_refs[0])
        if num_returns == 1:
            return return_refs[0]
        return return_refs


class _RemoteFunctionWrapper:
    def __init__(self, rf: RemoteFunction, options: Dict[str, Any]):
        self._rf = rf
        self._options = options

    def remote(self, *args, **kwargs):
        return self._rf._remote(args, kwargs, self._options)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self._rf, args, kwargs, self._options)


def _strategy_to_dict(strategy) -> Optional[dict]:
    """Convert public scheduling-strategy objects to the wire dict."""
    if strategy is None:
        return None
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )

    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        return {
            "kind": "placement_group",
            "pg_id": strategy.placement_group.id,
            "bundle_index": strategy.placement_group_bundle_index,
        }
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return {"kind": "node_affinity", "node_id": strategy.node_id, "soft": strategy.soft}
    if isinstance(strategy, str):
        return {"kind": strategy}
    raise ValueError(f"Unknown scheduling strategy: {strategy!r}")
