"""Dataset: distributed data over object-store blocks, lazily planned.

Analog of ``python/ray/data/dataset.py:139``: a Dataset is an
:class:`~ray_tpu.data.plan.ExecutionPlan` — input block refs plus
recorded stages.  Transforms are lazy; chains of per-block stages fuse
into one task per block (``_internal/plan.py:74``); global ops
(``random_shuffle``/``sort``/``repartition``) run as distributed
map-partition/reduce shuffles (``_internal/push_based_shuffle.py``) that
never materialize rows on the driver.  Stateful batch transforms run on
an actor pool (``ActorPoolStrategy``, ``_internal/compute.py:176``) —
e.g. a jitted model on ``num_tpus=1`` actors for batch inference.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.plan import ActorPoolStage, ExecutionPlan, OneToOneStage


def _apply_batches(block: Block, fn: Callable, batch_size: Optional[int],
                   batch_format: str) -> Block:
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return block
    size = batch_size or n
    outs = []
    for start in range(0, n, size):
        sub = BlockAccessor(acc.slice(start, min(start + size, n)))
        if batch_format == "numpy":
            batch = sub.to_batch()
            if set(batch) == {"value"}:
                batch = batch["value"]
        elif batch_format == "rows":
            batch = sub.to_rows()
        else:
            raise ValueError(f"unknown batch_format {batch_format!r}")
        outs.append(BlockAccessor.from_batch(fn(batch)))
    return BlockAccessor.concat(outs)


def _map_rows(block: Block, fn: Callable) -> Block:
    return [fn(r) for r in BlockAccessor(block).iter_rows()]


def _flat_map(block: Block, fn: Callable) -> Block:
    out: List[Any] = []
    for r in BlockAccessor(block).iter_rows():
        out.extend(fn(r))
    return out


def _filter(block: Block, fn: Callable) -> Block:
    return [r for r in BlockAccessor(block).iter_rows() if fn(r)]


def _partial_agg(block: Block, on: Optional[str]):
    """Per-block partial aggregate: (count, sum, min, max, sumsq)."""
    batch = BlockAccessor(block).to_batch()
    if not batch:
        return (0, 0.0, None, None, 0.0)
    col = on or ("value" if "value" in batch else next(iter(batch)))
    arr = np.asarray(batch[col], dtype=np.float64)
    if arr.size == 0:
        return (0, 0.0, None, None, 0.0)
    return (int(arr.size), float(arr.sum()), float(arr.min()),
            float(arr.max()), float((arr ** 2).sum()))


class _BatchWorker:
    """ActorPoolStrategy worker: holds a callable-class instance."""

    def __init__(self, fn_cls_blob: bytes, args: tuple, kwargs: dict):
        import cloudpickle

        cls = cloudpickle.loads(fn_cls_blob)
        self.fn = cls(*args, **kwargs)

    def apply(self, block: Block, batch_size: Optional[int], batch_format: str) -> Block:
        return _apply_batches(block, self.fn, batch_size, batch_format)


class ActorPoolStrategy:
    def __init__(self, size: int = 2, min_size: Optional[int] = None,
                 max_size: Optional[int] = None):
        self.size = max_size or size


class Dataset:
    def __init__(self, blocks_or_plan, num_rows: Optional[List[int]] = None):
        from ray_tpu._private.object_ref import ObjectRefGenerator

        if isinstance(blocks_or_plan, ExecutionPlan):
            self._plan = blocks_or_plan
        elif isinstance(blocks_or_plan, ObjectRefGenerator):
            # blocks stream from a num_returns="dynamic" producer task;
            # iter_batches consumes them as yielded (listing would block
            # until the producer finishes)
            self._plan = ExecutionPlan(blocks_or_plan, None)
        else:
            self._plan = ExecutionPlan(list(blocks_or_plan), num_rows)

    # -- plan plumbing -------------------------------------------------
    @property
    def _blocks(self) -> List[Any]:
        """Realized block refs (executes the plan)."""
        return self._plan.execute()[0]

    @property
    def _counts(self) -> Optional[List[int]]:
        return self._plan.execute()[1]

    def _with_stage(self, stage) -> "Dataset":
        return Dataset(self._plan.with_stage(stage))

    def _iter_block_refs(self):
        """Block refs in order, through the streaming executor
        (``data/_streaming`` — the streaming_executor analog):

        - a stage-free plan over an ObjectRefGenerator yields refs AS THE
          PRODUCER TASK YIELDS THEM (never materializing the block list)
        - a plan whose trailing stages are all one-to-one streams them:
          the pump submits fused map tasks up to a bounded in-flight block
          budget ahead of consumption (backpressure), so reads/transforms
          overlap training ingest instead of materializing stage-by-stage
        - a trailing shuffle/actor-pool stage executes eagerly first (and
          is cached on the plan), then the remainder streams

        Fully draining the iterator caches the refs as the plan's result,
        so re-iteration and count()/take() reuse them.
        """
        from ray_tpu.data._streaming import StreamingExecutor

        executor = StreamingExecutor(self._plan)
        try:
            yield from executor.iter_refs()
        finally:
            executor.shutdown()

    def stats(self) -> List[Dict[str, Any]]:
        """Per-stage execution stats (the _internal/stats.py analog)."""
        return self._plan.stats()

    # -- basics --------------------------------------------------------
    def num_blocks(self) -> int:
        return len(self._blocks)

    def count(self) -> int:
        from ray_tpu.data.shuffle import compute_counts

        refs, counts = self._plan.execute()
        if counts is None:
            counts = compute_counts(refs, None)
            self._plan._out = (refs, counts)
        return sum(counts)

    def schema(self) -> Optional[Dict[str, str]]:
        for b in self._blocks:
            s = BlockAccessor(ray_tpu.get(b)).schema()
            if s:
                return s
        return None

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for ref in self._blocks:
            out.extend(BlockAccessor(ray_tpu.get(ref)).to_rows())
            if len(out) >= limit:
                break
        return out[:limit]

    def take_all(self) -> List[Any]:
        out: List[Any] = []
        for ref in self._blocks:
            out.extend(BlockAccessor(ray_tpu.get(ref)).to_rows())
        return out

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            print(row)

    # -- transforms (lazy one-to-one stages; fused at execution) -------
    def map(self, fn: Callable) -> "Dataset":
        return self._with_stage(OneToOneStage("map", lambda b, fn=fn: _map_rows(b, fn)))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._with_stage(OneToOneStage("flat_map", lambda b, fn=fn: _flat_map(b, fn)))

    def filter(self, fn: Callable) -> "Dataset":
        return self._with_stage(OneToOneStage("filter", lambda b, fn=fn: _filter(b, fn)))

    def map_batches(
        self,
        fn: Union[Callable, type],
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        compute: Optional[ActorPoolStrategy] = None,
        fn_constructor_args: tuple = (),
        fn_constructor_kwargs: Optional[dict] = None,
        num_tpus: float = 0,
    ) -> "Dataset":
        """Batch transform (dataset.py:323).  Pass a class + ActorPoolStrategy
        for stateful fns (model inference on num_tpus=1 actors)."""
        if isinstance(fn, type):
            if compute is None:
                compute = ActorPoolStrategy()
            import cloudpickle

            blob = cloudpickle.dumps(fn)
            opts = {"num_cpus": 1}
            if num_tpus:
                opts["num_tpus"] = num_tpus
            size = compute.size
            ctor_args = (blob, fn_constructor_args, fn_constructor_kwargs or {})

            def submit(refs: List[Any]) -> List[Any]:
                Worker = ray_tpu.remote(**opts)(_BatchWorker)
                pool = [Worker.remote(*ctor_args)
                        for _ in range(min(size, len(refs) or 1))]
                return [pool[i % len(pool)].apply.remote(ref, batch_size, batch_format)
                        for i, ref in enumerate(refs)]

            return self._with_stage(ActorPoolStage("map_batches(actors)", submit))
        return self._with_stage(OneToOneStage(
            "map_batches",
            lambda b, fn=fn: _apply_batches(b, fn, batch_size, batch_format),
        ))

    # -- global reorgs (distributed shuffles; driver touches refs only) -
    def repartition(self, num_blocks: int) -> "Dataset":
        from ray_tpu.data.shuffle import repartition_stage

        return self._with_stage(repartition_stage(num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        from ray_tpu.data.shuffle import random_shuffle_stage

        return self._with_stage(random_shuffle_stage(seed, num_blocks))

    def sort(self, key: Optional[Union[str, Callable]] = None,
             descending: bool = False) -> "Dataset":
        from ray_tpu.data.shuffle import sort_stage

        return self._with_stage(sort_stage(key, descending))

    def split(self, n: int, *, equal: bool = True) -> List["Dataset"]:
        """n shards for n training workers (dataset.py:1017) — block-level
        re-slicing through tasks; rows never surface on the driver."""
        from ray_tpu.data.shuffle import _reduce_concat, compute_counts, range_partition

        refs, counts = self._plan.execute()
        if n == 1:
            return [Dataset(refs, counts)]
        counts = compute_counts(refs, counts)
        total = sum(counts)
        per = total // n
        if equal:
            bounds_all = [per * j for j in range(1, n)]
            if per * n < total:
                bounds_all.append(per * n)  # remainder goes to a dropped part
        else:
            base = [per + (1 if j < total % n else 0) for j in range(n)]
            bounds_all = list(np.cumsum(base)[:-1])
        parts = range_partition(refs, counts, bounds_all)
        reducer = ray_tpu.remote(num_cpus=1)(_reduce_concat)
        return [Dataset([reducer.remote(None, False, *parts[j])]) for j in range(n)]

    def split_at_indices(self, indices: Sequence[int]) -> List["Dataset"]:
        rows = self.take_all()
        out, prev = [], 0
        for idx in list(indices) + [len(rows)]:
            chunk = rows[prev:idx]
            out.append(Dataset([ray_tpu.put(chunk)], [len(chunk)]))
            prev = idx
        return out

    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._blocks)
        counts = self._counts
        all_counts: Optional[List[int]] = list(counts) if counts is not None else None
        for o in others:
            refs.extend(o._blocks)
            oc = o._counts
            if all_counts is not None and oc is not None:
                all_counts.extend(oc)
            else:
                all_counts = None
        return Dataset(refs, all_counts)

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-aligned zip: both datasets are sliced at the SAME global row
        boundaries (truncated to the shorter), so row i always pairs with
        row i regardless of each side's block layout."""
        from ray_tpu.data.shuffle import _reduce_concat, compute_counts, range_partition

        a_refs, a_counts = self._plan.execute()
        b_refs, b_counts = other._plan.execute()
        a_counts = compute_counts(a_refs, a_counts)
        b_counts = compute_counts(b_refs, b_counts)
        total = min(sum(a_counts), sum(b_counts))
        n = max(1, max(len(a_refs), len(b_refs)))
        per = [total // n + (1 if j < total % n else 0) for j in range(n)]
        bounds = list(np.cumsum(per)[:-1]) + ([total] if total < max(sum(a_counts), sum(b_counts)) else [])
        reducer = ray_tpu.remote(num_cpus=1)(_reduce_concat)
        a_parts = range_partition(a_refs, a_counts, bounds)
        b_parts = range_partition(b_refs, b_counts, bounds)
        a_refs = [reducer.remote(None, False, *a_parts[j]) for j in range(n)]
        b_refs = [reducer.remote(None, False, *b_parts[j]) for j in range(n)]

        def zip_blocks(x: Block, y: Block) -> Block:
            rows = []
            for rx, ry in zip(BlockAccessor(x).iter_rows(), BlockAccessor(y).iter_rows()):
                dx = rx if isinstance(rx, dict) else {"left": rx}
                dy = ry if isinstance(ry, dict) else {"right": ry}
                rows.append({**dx, **{(f"right_{k}" if k in dx else k): v
                                      for k, v in dy.items()}})
            return rows

        task = ray_tpu.remote(num_cpus=1)(zip_blocks)
        return Dataset([task.remote(x, y) for x, y in zip(a_refs, b_refs)])

    # -- aggregates (per-block partials; only scalars reach the driver) -
    def _agg(self, on: Optional[str]):
        task = ray_tpu.remote(num_cpus=1)(_partial_agg)
        parts = ray_tpu.get([task.remote(r, on) for r in self._blocks])
        count = sum(p[0] for p in parts)
        if count == 0:
            return None
        total = sum(p[1] for p in parts)
        mn = min(p[2] for p in parts if p[0])
        mx = max(p[3] for p in parts if p[0])
        sumsq = sum(p[4] for p in parts)
        return count, total, mn, mx, sumsq

    def _agg_nonempty(self, on: Optional[str], op: str):
        agg = self._agg(on)
        if agg is None:
            raise ValueError(f"cannot compute {op}() of an empty dataset")
        return agg

    def sum(self, on: Optional[str] = None):
        agg = self._agg(on)
        return agg[1] if agg else 0

    def min(self, on: Optional[str] = None):
        return self._agg_nonempty(on, "min")[2]

    def max(self, on: Optional[str] = None):
        return self._agg_nonempty(on, "max")[3]

    def mean(self, on: Optional[str] = None):
        count, total, *_ = self._agg_nonempty(on, "mean")
        return total / count

    def std(self, on: Optional[str] = None):
        count, total, _, _, sumsq = self._agg_nonempty(on, "std")
        return float(np.sqrt(max(0.0, sumsq / count - (total / count) ** 2)))

    def groupby(self, key: Union[str, Callable]) -> "GroupedData":
        return GroupedData(self, key)

    # -- consumption ---------------------------------------------------
    def iter_rows(self) -> Iterator[Any]:
        for ref in self._blocks:
            yield from BlockAccessor(ray_tpu.get(ref)).iter_rows()

    def iter_batches(
        self, *, batch_size: int = 256, batch_format: str = "numpy",
        drop_last: bool = False, prefetch_blocks: int = 2,
    ) -> Iterator[Any]:
        """Stream batches (dataset.py:2624) through the streaming executor:
        trailing map stages run as a backpressured pipeline overlapping
        consumption, a background thread keeps up to ``prefetch_blocks``
        blocks materialized ahead, and batch slicing is zero-copy over the
        fetched blocks' sealed store segments."""
        from ray_tpu.data._streaming import (
            StreamingExecutor,
            batches_from_block_iter,
        )

        # the executor is created here (not inside a ref generator) so the
        # batch iterator can shut it down on abandonment even while the
        # prefetch thread is suspended inside the generator frame
        executor = StreamingExecutor(self._plan)
        return batches_from_block_iter(
            executor.iter_refs(), batch_size=batch_size,
            batch_format=batch_format, drop_last=drop_last,
            prefetch_blocks=prefetch_blocks, on_abandon=executor.shutdown,
        )

    def streaming_split(
        self, n: int, *, equal: bool = True,
        locality_hints: Optional[List[Optional[str]]] = None,
        max_in_flight_blocks: Optional[int] = None,
    ) -> List[Any]:
        """``n`` disjoint streaming shards over ONE shared pipeline
        (``Dataset.streaming_split`` analog): each returned
        ``StreamSplitDataIterator`` is picklable and is iterated from its
        consumer's own process; the coordinator assigns blocks to shards
        as they are produced (row-balanced with ``equal``), dispatches
        each shard's map tasks toward ``locality_hints[i]`` (a node id —
        blocks materialize on the consuming trainer's node), and bounds
        in-flight blocks per shard (backpressure).  Contrast ``split()``:
        no eager plan execution, no reducer tasks, no per-batch head
        round trip."""
        from ray_tpu.data._streaming import make_split_iterators

        return make_split_iterators(
            self, n, equal=equal, locality_hints=locality_hints,
            max_in_flight_blocks=max_in_flight_blocks)

    @staticmethod
    def _format_batch(block: Block, batch_format: str):
        from ray_tpu.data._streaming.iterator import format_batch

        return format_batch(block, batch_format)

    def to_numpy(self, column: Optional[str] = None) -> np.ndarray:
        vals: List[np.ndarray] = []
        for ref in self._blocks:
            batch = BlockAccessor(ray_tpu.get(ref)).to_batch()
            if not batch:
                continue
            col = column or ("value" if "value" in batch else next(iter(batch)))
            vals.append(np.asarray(batch[col]))
        return np.concatenate(vals) if vals else np.asarray([])

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(self.take_all())

    def materialize(self) -> "Dataset":
        ray_tpu.get(self._blocks)
        return self

    def to_torch(
        self,
        *,
        label_column: Optional[str] = None,
        feature_columns: Optional[List[str]] = None,
        batch_size: int = 1,
        prefetch_blocks: int = 1,
        drop_last: bool = False,
        unsqueeze_label_tensor: bool = True,
    ):
        """Torch IterableDataset over this Dataset (``dataset.py:2835``
        analog) — each item is ``(features, label)`` (or just features with
        no ``label_column``), batched to ``batch_size``."""
        import torch
        from torch.utils.data import IterableDataset

        outer = self

        class _TorchIterable(IterableDataset):
            def __iter__(self):
                for batch in outer.iter_batches(
                    batch_size=batch_size,
                    batch_format="numpy",
                    prefetch_blocks=prefetch_blocks,
                    drop_last=drop_last,
                ):
                    if isinstance(batch, dict):
                        if label_column is not None:
                            label = torch.as_tensor(batch[label_column])
                            if unsqueeze_label_tensor and label.dim() == 1:
                                label = label.unsqueeze(1)
                            cols = feature_columns or [
                                c for c in batch if c != label_column
                            ]
                            if not cols:
                                raise ValueError(
                                    "to_torch: no feature columns left after "
                                    f"excluding label {label_column!r}"
                                )
                            # always (N, C) float32 — shape/dtype must not
                            # flip when the feature list grows past one
                            flat = [
                                torch.as_tensor(
                                    np.asarray(batch[c], np.float32)
                                ).reshape(len(label), -1)
                                for c in cols
                            ]
                            feats = torch.cat(flat, dim=1)
                            yield feats, label
                        else:
                            if feature_columns is not None:
                                batch = {c: batch[c] for c in feature_columns}
                            yield {k: torch.as_tensor(np.asarray(v))
                                   for k, v in batch.items()}
                    else:
                        if label_column is not None or feature_columns is not None:
                            raise ValueError(
                                "to_torch: label_column/feature_columns need "
                                "named columns, but this dataset yields plain "
                                "arrays (e.g. from_numpy)"
                            )
                        yield torch.as_tensor(np.asarray(batch))

        return _TorchIterable()

    def iter_torch_batches(
        self, *, batch_size: Optional[int] = None, prefetch_blocks: int = 1,
        drop_last: bool = False,
    ) -> Iterator[Any]:
        """Batches as torch tensors (``iter_torch_batches`` analog)."""
        import torch

        for batch in self.iter_batches(
            batch_size=batch_size or 256, batch_format="numpy",
            prefetch_blocks=prefetch_blocks, drop_last=drop_last,
        ):
            if isinstance(batch, dict):
                yield {k: torch.as_tensor(np.asarray(v)) for k, v in batch.items()}
            else:
                yield torch.as_tensor(np.asarray(batch))

    # -- pipeline ------------------------------------------------------
    def window(self, *, blocks_per_window: int = 1) -> "DatasetPipeline":
        from ray_tpu.data.dataset_pipeline import DatasetPipeline

        # one pass over the data; .repeat() is the API for more epochs
        return DatasetPipeline.from_dataset(self, blocks_per_window, repeat=1)

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        from ray_tpu.data.dataset_pipeline import DatasetPipeline

        return DatasetPipeline.from_dataset(self, self.num_blocks() or 1, repeat=times)

    # -- io (one write task per block -> part files) -------------------
    def _write(self, datasource_cls, path: str, **kw) -> List[str]:
        import os

        os.makedirs(path, exist_ok=True)
        ds = datasource_cls([])

        def write_one(block: Block, index: int) -> str:
            return ds.write_block(block, path, index, **kw)

        task = ray_tpu.remote(num_cpus=1)(write_one)
        return ray_tpu.get([task.remote(r, i) for i, r in enumerate(self._blocks)])

    def write_csv(self, path: str) -> List[str]:
        from ray_tpu.data.datasource import CSVDatasource

        return self._write(CSVDatasource, path)

    def write_json(self, path: str) -> List[str]:
        from ray_tpu.data.datasource import JSONDatasource

        return self._write(JSONDatasource, path)

    def write_parquet(self, path: str) -> List[str]:
        from ray_tpu.data.datasource import ParquetDatasource

        return self._write(ParquetDatasource, path)

    def write_numpy(self, path: str) -> List[str]:
        from ray_tpu.data.datasource import NumpyDatasource

        return self._write(NumpyDatasource, path)

    def __repr__(self):
        n_stages = len(self._plan.stages)
        if self._plan._out is None and n_stages:
            return f"Dataset(num_stages={n_stages}, unexecuted)"
        return f"Dataset(num_blocks={self.num_blocks()}, num_rows={self.count()})"


def _group_block(block: Block, key) -> Dict[Any, List[Any]]:
    from ray_tpu.data.shuffle import _key_fn

    kf = _key_fn(key)
    out: Dict[Any, List[Any]] = {}
    for r in BlockAccessor(block).iter_rows():
        out.setdefault(kf(r), []).append(r)
    return out


class GroupedData:
    """Minimal groupby: count/sum/mean over a key (reference
    ``grouped_dataset.py``); per-block grouping tasks + driver combine of
    the (small) per-key partials."""

    def __init__(self, ds: Dataset, key):
        self._ds = ds
        self._key = key

    def _partials(self, value_of: Callable[[List[Any]], Any]) -> Dict[Any, Any]:
        task = ray_tpu.remote(num_cpus=1)(_group_block)
        merged: Dict[Any, List[Any]] = {}
        for part in ray_tpu.get([task.remote(r, self._key) for r in self._ds._blocks]):
            for k, rows in part.items():
                merged.setdefault(k, []).append(value_of(rows))
        return merged

    def count(self) -> Dataset:
        merged = self._partials(len)
        rows = [{"key": k, "count": sum(v)} for k, v in sorted(merged.items())]
        return Dataset([ray_tpu.put(rows)], [len(rows)])

    def sum(self, on: str) -> Dataset:
        merged = self._partials(lambda rows: sum(r[on] for r in rows))
        rows = [{"key": k, "sum": sum(v)} for k, v in sorted(merged.items())]
        return Dataset([ray_tpu.put(rows)], [len(rows)])

    def mean(self, on: str) -> Dataset:
        task = ray_tpu.remote(num_cpus=1)(_group_block)
        sums: Dict[Any, float] = {}
        counts: Dict[Any, int] = {}
        for part in ray_tpu.get([task.remote(r, self._key) for r in self._ds._blocks]):
            for k, rows in part.items():
                sums[k] = sums.get(k, 0.0) + sum(r[on] for r in rows)
                counts[k] = counts.get(k, 0) + len(rows)
        rows = [{"key": k, "mean": sums[k] / counts[k]} for k in sorted(sums)]
        return Dataset([ray_tpu.put(rows)], [len(rows)])
