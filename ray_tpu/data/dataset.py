"""Dataset: distributed data over object-store blocks.

Analog of ``python/ray/data/dataset.py:139``: a Dataset is a list of
object refs to blocks; transforms run as parallel tasks over blocks
(``TaskPoolStrategy``, ``_internal/compute.py:58``) or through a pool of
reusable actors (``ActorPoolStrategy``, ``:176``) for stateful/expensive
setup (e.g. a jax model for batch inference).  Eager execution per stage —
the reference's lazy ExecutionPlan optimizations (stage fusion) are
deferred; on TPU the heavy compute belongs in jitted batch fns, so the
per-stage overhead is the small part.
"""

from __future__ import annotations

import math
import random
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor


def _apply_batches(block: Block, fn: Callable, batch_size: Optional[int],
                   batch_format: str) -> Block:
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return block
    size = batch_size or n
    outs = []
    for start in range(0, n, size):
        sub = BlockAccessor(acc.slice(start, min(start + size, n)))
        if batch_format == "numpy":
            batch = sub.to_batch()
            if set(batch) == {"value"}:
                batch = batch["value"]
        elif batch_format == "rows":
            batch = sub.to_rows()
        else:
            raise ValueError(f"unknown batch_format {batch_format!r}")
        outs.append(BlockAccessor.from_batch(fn(batch)))
    return BlockAccessor.concat(outs)


def _map_rows(block: Block, fn: Callable) -> Block:
    return [fn(r) for r in BlockAccessor(block).iter_rows()]


def _flat_map(block: Block, fn: Callable) -> Block:
    out: List[Any] = []
    for r in BlockAccessor(block).iter_rows():
        out.extend(fn(r))
    return out


def _filter(block: Block, fn: Callable) -> Block:
    return [r for r in BlockAccessor(block).iter_rows() if fn(r)]


class _BatchWorker:
    """ActorPoolStrategy worker: holds a callable-class instance."""

    def __init__(self, fn_cls_blob: bytes, args: tuple, kwargs: dict):
        import cloudpickle

        cls = cloudpickle.loads(fn_cls_blob)
        self.fn = cls(*args, **kwargs)

    def apply(self, block: Block, batch_size: Optional[int], batch_format: str) -> Block:
        return _apply_batches(block, self.fn, batch_size, batch_format)


class ActorPoolStrategy:
    def __init__(self, size: int = 2, min_size: Optional[int] = None,
                 max_size: Optional[int] = None):
        self.size = max_size or size


class Dataset:
    def __init__(self, block_refs: List[Any], num_rows: Optional[List[int]] = None):
        self._blocks = list(block_refs)
        self._num_rows = num_rows

    # -- basics --------------------------------------------------------
    def num_blocks(self) -> int:
        return len(self._blocks)

    def count(self) -> int:
        if self._num_rows is None:
            self._num_rows = [
                BlockAccessor(b).num_rows() for b in ray_tpu.get(self._blocks)
            ]
        return sum(self._num_rows)

    def schema(self) -> Optional[Dict[str, str]]:
        for b in self._blocks:
            s = BlockAccessor(ray_tpu.get(b)).schema()
            if s:
                return s
        return None

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for ref in self._blocks:
            out.extend(BlockAccessor(ray_tpu.get(ref)).to_rows())
            if len(out) >= limit:
                break
        return out[:limit]

    def take_all(self) -> List[Any]:
        out: List[Any] = []
        for ref in self._blocks:
            out.extend(BlockAccessor(ray_tpu.get(ref)).to_rows())
        return out

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            print(row)

    # -- transforms (TaskPool by default) ------------------------------
    def _transform(self, remote_fn: Callable, *args) -> "Dataset":
        task = ray_tpu.remote(num_cpus=1)(remote_fn)
        new_refs = [task.remote(ref, *args) for ref in self._blocks]
        return Dataset(new_refs)

    def map(self, fn: Callable) -> "Dataset":
        return self._transform(_map_rows, fn)

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._transform(_flat_map, fn)

    def filter(self, fn: Callable) -> "Dataset":
        return self._transform(_filter, fn)

    def map_batches(
        self,
        fn: Union[Callable, type],
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        compute: Optional[ActorPoolStrategy] = None,
        fn_constructor_args: tuple = (),
        fn_constructor_kwargs: Optional[dict] = None,
        num_tpus: float = 0,
    ) -> "Dataset":
        """Batch transform (dataset.py:323).  Pass a class + ActorPoolStrategy
        for stateful fns (model inference on num_tpus=1 actors)."""
        if isinstance(fn, type):
            if compute is None:
                compute = ActorPoolStrategy()
            import cloudpickle

            blob = cloudpickle.dumps(fn)
            opts = {"num_cpus": 1}
            if num_tpus:
                opts["num_tpus"] = num_tpus
            Worker = ray_tpu.remote(**opts)(_BatchWorker)
            pool = [
                Worker.remote(blob, fn_constructor_args, fn_constructor_kwargs or {})
                for _ in range(min(compute.size, len(self._blocks) or 1))
            ]
            refs = [
                pool[i % len(pool)].apply.remote(ref, batch_size, batch_format)
                for i, ref in enumerate(self._blocks)
            ]
            return Dataset(refs)
        return self._transform(_apply_batches, fn, batch_size, batch_format)

    # -- reorg ---------------------------------------------------------
    def repartition(self, num_blocks: int) -> "Dataset":
        rows = self.take_all()
        per = math.ceil(len(rows) / num_blocks) if rows else 0
        blocks = [rows[i * per:(i + 1) * per] for i in range(num_blocks)]
        return Dataset([ray_tpu.put(b) for b in blocks],
                       [len(b) for b in blocks])

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """All-to-all shuffle (the reference's push-based shuffle collapses
        to a local pass on the fake cluster)."""
        rows = self.take_all()
        random.Random(seed).shuffle(rows)
        n = max(1, self.num_blocks())
        per = math.ceil(len(rows) / n)
        blocks = [rows[i * per:(i + 1) * per] for i in range(n)]
        return Dataset([ray_tpu.put(b) for b in blocks], [len(b) for b in blocks])

    def sort(self, key: Optional[Union[str, Callable]] = None, descending: bool = False) -> "Dataset":
        rows = self.take_all()
        if isinstance(key, str):
            keyfn = lambda r: r[key]
        else:
            keyfn = key
        rows.sort(key=keyfn, reverse=descending)
        n = max(1, self.num_blocks())
        per = math.ceil(len(rows) / n)
        blocks = [rows[i * per:(i + 1) * per] for i in range(n)]
        return Dataset([ray_tpu.put(b) for b in blocks], [len(b) for b in blocks])

    def split(self, n: int, *, equal: bool = True) -> List["Dataset"]:
        """n shards for n training workers (dataset.py:1017)."""
        rows = self.take_all()
        per = len(rows) // n
        shards = []
        for i in range(n):
            end = (i + 1) * per if (equal or i < n - 1) else len(rows)
            shard_rows = rows[i * per:end]
            shards.append(Dataset([ray_tpu.put(shard_rows)], [len(shard_rows)]))
        return shards

    def split_at_indices(self, indices: Sequence[int]) -> List["Dataset"]:
        rows = self.take_all()
        out, prev = [], 0
        for idx in list(indices) + [len(rows)]:
            chunk = rows[prev:idx]
            out.append(Dataset([ray_tpu.put(chunk)], [len(chunk)]))
            prev = idx
        return out

    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._blocks)
        for o in others:
            refs.extend(o._blocks)
        return Dataset(refs)

    def zip(self, other: "Dataset") -> "Dataset":
        a, b = self.take_all(), other.take_all()
        rows = [
            {**(x if isinstance(x, dict) else {"left": x}),
             **({f"right_{k}" if k in (x if isinstance(x, dict) else {}) else k: v
                 for k, v in (y if isinstance(y, dict) else {"right": y}).items()})}
            for x, y in zip(a, b)
        ]
        return Dataset([ray_tpu.put(rows)], [len(rows)])

    # -- aggregates ----------------------------------------------------
    def _column(self, on: Optional[str]) -> np.ndarray:
        vals: List[Any] = []
        for ref in self._blocks:
            batch = BlockAccessor(ray_tpu.get(ref)).to_batch()
            if not batch:
                continue
            col = on or ("value" if "value" in batch else next(iter(batch)))
            vals.append(np.asarray(batch[col]))
        return np.concatenate(vals) if vals else np.asarray([])

    def sum(self, on: Optional[str] = None):
        col = self._column(on)
        return col.sum().item() if col.size else 0

    def min(self, on: Optional[str] = None):
        return self._column(on).min().item()

    def max(self, on: Optional[str] = None):
        return self._column(on).max().item()

    def mean(self, on: Optional[str] = None):
        return self._column(on).mean().item()

    def std(self, on: Optional[str] = None):
        return self._column(on).std().item()

    # -- consumption ---------------------------------------------------
    def iter_rows(self) -> Iterator[Any]:
        for ref in self._blocks:
            yield from BlockAccessor(ray_tpu.get(ref)).iter_rows()

    def iter_batches(
        self, *, batch_size: int = 256, batch_format: str = "numpy",
        drop_last: bool = False,
    ) -> Iterator[Any]:
        """Stream batches (dataset.py:2624); block fetches overlap consumption
        by prefetching the next block ref."""
        carry: List[Any] = []
        for ref in self._blocks:
            rows = BlockAccessor(ray_tpu.get(ref)).to_rows()
            carry.extend(rows)
            while len(carry) >= batch_size:
                chunk, carry = carry[:batch_size], carry[batch_size:]
                yield self._format_batch(chunk, batch_format)
        if carry and not drop_last:
            yield self._format_batch(carry, batch_format)

    @staticmethod
    def _format_batch(rows: List[Any], batch_format: str):
        if batch_format == "rows":
            return rows
        batch = BlockAccessor(rows).to_batch()
        if batch_format == "numpy":
            if set(batch) == {"value"}:
                return batch["value"]
            return batch
        if batch_format == "pandas":
            import pandas as pd

            return pd.DataFrame(rows)
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def to_numpy(self, column: Optional[str] = None) -> np.ndarray:
        return self._column(column)

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(self.take_all())

    def materialize(self) -> "Dataset":
        ray_tpu.get(self._blocks)
        return self

    # -- pipeline ------------------------------------------------------
    def window(self, *, blocks_per_window: int = 1) -> "DatasetPipeline":
        from ray_tpu.data.dataset_pipeline import DatasetPipeline

        # one pass over the data; .repeat() is the API for more epochs
        return DatasetPipeline.from_dataset(self, blocks_per_window, repeat=1)

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        from ray_tpu.data.dataset_pipeline import DatasetPipeline

        return DatasetPipeline.from_dataset(self, self.num_blocks() or 1, repeat=times)

    # -- io ------------------------------------------------------------
    def write_csv(self, path: str) -> None:
        self.to_pandas().to_csv(path, index=False)

    def write_json(self, path: str) -> None:
        self.to_pandas().to_json(path, orient="records", lines=True)

    def write_parquet(self, path: str) -> None:
        self.to_pandas().to_parquet(path)

    def __repr__(self):
        return f"Dataset(num_blocks={self.num_blocks()}, num_rows={self.count()})"
