"""ray_tpu.data — distributed datasets over object-store blocks.

Analog of ``python/ray/data`` (``Dataset`` ``data/dataset.py:139``): read
connectors fan out one task per file, transforms run as tasks or actor
pools over blocks, and ``iter_batches``/``split`` feed training workers.
"""

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.dataset import ActorPoolStrategy, Dataset, GroupedData
from ray_tpu.data.dataset_pipeline import DatasetPipeline
from ray_tpu.data.datasource import Datasource, FileBasedDatasource, ReadTask
from ray_tpu.data.read_api import (
    from_items,
    from_block_generator,
    from_arrow,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    read_binary_files,
    read_csv,
    read_datasource,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
)

__all__ = [
    "Dataset",
    "DatasetPipeline",
    "ActorPoolStrategy",
    "GroupedData",
    "Datasource",
    "FileBasedDatasource",
    "ReadTask",
    "read_datasource",
    "Block",
    "BlockAccessor",
    "from_items",
    "from_block_generator",
    "from_arrow",
    "from_numpy",
    "from_pandas",
    "range",
    "range_tensor",
    "read_csv",
    "read_json",
    "read_parquet",
    "read_numpy",
    "read_text",
    "read_binary_files",
]
