"""Dataset creation / read connectors (``python/ray/data/read_api.py``).

All file/range reads go through :func:`read_datasource`
(``read_api.py:233``): the datasource splits into ReadTasks, each runs as
one remote task producing one block.
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Union

import numpy as np

import ray_tpu
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.datasource import (
    BinaryDatasource,
    CSVDatasource,
    Datasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    TextDatasource,
)

DEFAULT_BLOCKS = 4
# Size-aware splitting for in-memory arrays (the reference's
# target_max_block_size): blocks near this size keep the streaming
# pipeline's overlap granularity fine enough that the first batch is
# ready after ONE block's transform, not the whole dataset's.
TARGET_BLOCK_BYTES = 32 << 20
_MAX_AUTO_BLOCKS = 512


def read_datasource(datasource: Datasource, *, parallelism: int = DEFAULT_BLOCKS,
                    **read_args) -> Dataset:
    """One remote task per ReadTask; returns a lazy Dataset over the
    resulting blocks."""
    from ray_tpu._private.usage import record_feature
    record_feature("data")
    tasks = datasource.prepare_read(parallelism, **read_args)
    runner = ray_tpu.remote(num_cpus=1)(lambda t: t())
    refs = [runner.remote(t) for t in tasks]
    counts = [t.num_rows for t in tasks]
    return Dataset(refs, None if any(c is None for c in counts) else counts)


def _put_blocks(rows: List[Any], parallelism: int) -> Dataset:
    import builtins

    parallelism = max(1, min(parallelism, len(rows) or 1))
    per = math.ceil(len(rows) / parallelism)
    # builtins.range: this module exports a `range` Dataset constructor
    blocks = [rows[i * per:(i + 1) * per] for i in builtins.range(parallelism)]
    blocks = [b for b in blocks if b] or [[]]
    return Dataset([ray_tpu.put(b) for b in blocks], [len(b) for b in blocks])


def from_items(items: Sequence[Any], *, parallelism: int = DEFAULT_BLOCKS) -> Dataset:
    return _put_blocks(list(items), parallelism)


def range(n: int, *, parallelism: int = DEFAULT_BLOCKS) -> Dataset:  # noqa: A001
    return read_datasource(RangeDatasource(n), parallelism=parallelism)


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = DEFAULT_BLOCKS) -> Dataset:
    return read_datasource(RangeDatasource(n, tensor_shape=shape), parallelism=parallelism)


def from_numpy(arr: Union[np.ndarray, List[np.ndarray]], *,
               parallelism: Optional[int] = None) -> Dataset:
    if isinstance(arr, list):
        refs = [ray_tpu.put({"value": a}) for a in arr]
        return Dataset(refs, [len(a) for a in arr])
    if parallelism is None:
        # size-aware default: ~TARGET_BLOCK_BYTES blocks (floor
        # DEFAULT_BLOCKS) so big arrays stream at fine granularity
        parallelism = max(DEFAULT_BLOCKS,
                          min(_MAX_AUTO_BLOCKS,
                              int(arr.nbytes // TARGET_BLOCK_BYTES)))
    chunks = np.array_split(arr, min(parallelism, max(1, len(arr))))
    refs = [ray_tpu.put({"value": c}) for c in chunks if len(c)]
    return Dataset(refs, [len(c) for c in chunks if len(c)])


def from_block_generator(gen) -> Dataset:
    """Dataset over blocks streamed by a ``num_returns="dynamic"`` task:
    ``iter_batches`` consumes each block AS THE PRODUCER YIELDS IT —
    the full block list is never materialized (reference counterpart:
    streaming Data blocks over ObjectRefGenerator, ``worker.py:2924``)."""
    from ray_tpu._private.object_ref import ObjectRefGenerator

    if not isinstance(gen, ObjectRefGenerator):
        raise TypeError(
            f"from_block_generator expects an ObjectRefGenerator "
            f"(a num_returns=\"dynamic\" task's handle), got {type(gen)}")
    return Dataset(gen)


def from_pandas(df) -> Dataset:
    block = {c: df[c].to_numpy() for c in df.columns}
    return Dataset([ray_tpu.put(block)], [len(df)])


def from_arrow(tables) -> Dataset:
    """Dataset over Arrow table block(s) — zero-copy into the store
    (``from_arrow``, ``python/ray/data/read_api.py`` analog)."""
    import pyarrow as pa

    if isinstance(tables, pa.Table):
        tables = [tables]
    for t in tables:
        if not isinstance(t, pa.Table):
            raise TypeError(f"from_arrow expects pyarrow.Table(s), got {type(t)}")
    return Dataset([ray_tpu.put(t) for t in tables],
                   [t.num_rows for t in tables])


def read_csv(paths: Union[str, List[str]], *, parallelism: int = DEFAULT_BLOCKS, **kw) -> Dataset:
    return read_datasource(CSVDatasource(paths, **kw), parallelism=parallelism)


def read_json(paths: Union[str, List[str]], *, parallelism: int = DEFAULT_BLOCKS, **kw) -> Dataset:
    return read_datasource(JSONDatasource(paths, **kw), parallelism=parallelism)


def read_parquet(paths: Union[str, List[str]], *, parallelism: int = DEFAULT_BLOCKS, **kw) -> Dataset:
    return read_datasource(ParquetDatasource(paths, **kw), parallelism=parallelism)


def read_numpy(paths: Union[str, List[str]], *, parallelism: int = DEFAULT_BLOCKS) -> Dataset:
    return read_datasource(NumpyDatasource(paths), parallelism=parallelism)


def read_text(paths: Union[str, List[str]], *, parallelism: int = DEFAULT_BLOCKS) -> Dataset:
    return read_datasource(TextDatasource(paths), parallelism=parallelism)


def read_binary_files(paths: Union[str, List[str]], *, parallelism: int = DEFAULT_BLOCKS) -> Dataset:
    return read_datasource(BinaryDatasource(paths), parallelism=parallelism)
