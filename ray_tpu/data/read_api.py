"""Dataset creation / read connectors (``python/ray/data/read_api.py``)."""

from __future__ import annotations

import math
import os
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.dataset import Dataset

DEFAULT_BLOCKS = 4


def _put_blocks(rows: List[Any], parallelism: int) -> Dataset:
    import builtins

    parallelism = max(1, min(parallelism, len(rows) or 1))
    per = math.ceil(len(rows) / parallelism)
    # builtins.range: this module exports a `range` Dataset constructor
    blocks = [rows[i * per:(i + 1) * per] for i in builtins.range(parallelism)]
    blocks = [b for b in blocks if b] or [[]]
    return Dataset([ray_tpu.put(b) for b in blocks], [len(b) for b in blocks])


def from_items(items: Sequence[Any], *, parallelism: int = DEFAULT_BLOCKS) -> Dataset:
    return _put_blocks(list(items), parallelism)


def range(n: int, *, parallelism: int = DEFAULT_BLOCKS) -> Dataset:  # noqa: A001
    import builtins

    parallelism = max(1, min(parallelism, n or 1))
    per = math.ceil(n / parallelism)
    refs, counts = [], []
    for i in builtins.range(parallelism):
        lo, hi = i * per, min((i + 1) * per, n)
        if lo >= hi:
            continue
        refs.append(ray_tpu.put({"value": np.arange(lo, hi)}))
        counts.append(hi - lo)
    return Dataset(refs, counts)


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = DEFAULT_BLOCKS) -> Dataset:
    import builtins

    parallelism = max(1, min(parallelism, n or 1))
    per = math.ceil(n / parallelism)
    refs, counts = [], []
    for i in builtins.range(parallelism):
        lo, hi = i * per, min((i + 1) * per, n)
        if lo >= hi:
            continue
        data = np.arange(lo, hi).reshape(-1, *([1] * len(shape))) * np.ones(shape)
        refs.append(ray_tpu.put({"data": data}))
        counts.append(hi - lo)
    return Dataset(refs, counts)


def from_numpy(arr: Union[np.ndarray, List[np.ndarray]], *,
               parallelism: int = DEFAULT_BLOCKS) -> Dataset:
    if isinstance(arr, list):
        refs = [ray_tpu.put({"value": a}) for a in arr]
        return Dataset(refs, [len(a) for a in arr])
    chunks = np.array_split(arr, min(parallelism, max(1, len(arr))))
    refs = [ray_tpu.put({"value": c}) for c in chunks if len(c)]
    return Dataset(refs, [len(c) for c in chunks if len(c)])


def from_pandas(df) -> Dataset:
    block = {c: df[c].to_numpy() for c in df.columns}
    return Dataset([ray_tpu.put(block)], [len(df)])


def _expand_paths(paths: Union[str, List[str]], suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                if suffix is None or name.endswith(suffix):
                    out.append(os.path.join(p, name))
        else:
            out.append(p)
    return out


def _read_files(paths: List[str], reader) -> Dataset:
    """One read task per file — parallel IO (read_api.py:233 pattern)."""
    task = ray_tpu.remote(num_cpus=1)(reader)
    refs = [task.remote(p) for p in paths]
    return Dataset(refs)


def read_csv(paths: Union[str, List[str]], **kw) -> Dataset:
    def reader(path):
        import pandas as pd

        df = pd.read_csv(path, **kw)
        return {c: df[c].to_numpy() for c in df.columns}

    return _read_files(_expand_paths(paths, ".csv"), reader)


def read_json(paths: Union[str, List[str]], **kw) -> Dataset:
    def reader(path):
        import pandas as pd

        df = pd.read_json(path, orient="records", lines=True, **kw)
        return {c: df[c].to_numpy() for c in df.columns}

    return _read_files(_expand_paths(paths, ".json"), reader)


def read_parquet(paths: Union[str, List[str]], **kw) -> Dataset:
    def reader(path):
        import pandas as pd

        df = pd.read_parquet(path, **kw)
        return {c: df[c].to_numpy() for c in df.columns}

    return _read_files(_expand_paths(paths, ".parquet"), reader)


def read_numpy(paths: Union[str, List[str]]) -> Dataset:
    def reader(path):
        return {"value": np.load(path)}

    return _read_files(_expand_paths(paths, ".npy"), reader)


def read_text(paths: Union[str, List[str]]) -> Dataset:
    def reader(path):
        with open(path) as f:
            return [line.rstrip("\n") for line in f]

    return _read_files(_expand_paths(paths), reader)


def read_binary_files(paths: Union[str, List[str]]) -> Dataset:
    def reader(path):
        with open(path, "rb") as f:
            return [{"path": path, "bytes": f.read()}]

    return _read_files(_expand_paths(paths), reader)
