"""DatasetPipeline: windowed/repeating streaming over a Dataset.

Analog of ``python/ray/data/dataset_pipeline.py`` — *data* pipelining
(overlap ingest with consumption), the reference's tool for
bulk-ingest-while-training.
"""

from __future__ import annotations

import itertools
from typing import Callable, Iterator, Optional

from ray_tpu.data.dataset import Dataset


class DatasetPipeline:
    def __init__(self, windows_fn: Callable[[], Iterator[Dataset]]):
        self._windows_fn = windows_fn
        self._transforms = []

    @classmethod
    def from_dataset(cls, ds: Dataset, blocks_per_window: int,
                     repeat: Optional[int] = None) -> "DatasetPipeline":
        def windows() -> Iterator[Dataset]:
            epochs = itertools.count() if repeat is None else range(repeat)
            for _ in epochs:
                for i in range(0, ds.num_blocks(), blocks_per_window):
                    yield Dataset(ds._blocks[i:i + blocks_per_window])

        return cls(windows)

    def map_batches(self, fn, **kw) -> "DatasetPipeline":
        pipe = DatasetPipeline(self._windows_fn)
        pipe._transforms = self._transforms + [lambda ds: ds.map_batches(fn, **kw)]
        return pipe

    def map(self, fn) -> "DatasetPipeline":
        pipe = DatasetPipeline(self._windows_fn)
        pipe._transforms = self._transforms + [lambda ds: ds.map(fn)]
        return pipe

    def iter_windows(self) -> Iterator[Dataset]:
        for ds in self._windows_fn():
            for t in self._transforms:
                ds = t(ds)
            yield ds

    def iter_batches(self, *, batch_size: int = 256, batch_format: str = "numpy"):
        for ds in self.iter_windows():
            yield from ds.iter_batches(batch_size=batch_size, batch_format=batch_format)

    def iter_rows(self):
        for ds in self.iter_windows():
            yield from ds.iter_rows()

    def take(self, limit: int = 20):
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out
