"""Datasource framework: pluggable parallel readers/writers.

Analog of ``python/ray/data/datasource/`` (``Datasource.prepare_read`` ->
``ReadTask`` list, ``read_datasource`` at ``read_api.py:233``): a
datasource splits itself into independent read tasks; each runs as a
remote task producing one block, so reads parallelize across the cluster
and compose with the lazy plan.  Writes mirror it: one write task per
block producing part files.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional, Union

import numpy as np

from ray_tpu.data.block import Block

# A ReadTask is a zero-arg callable returning one block, plus optional
# row-count metadata known up front.


@dataclass
class ReadTask:
    read_fn: Callable[[], Block]
    num_rows: Optional[int] = None

    def __call__(self) -> Block:
        return self.read_fn()


class Datasource:
    """Subclass and implement ``prepare_read``; optionally ``write_block``."""

    def prepare_read(self, parallelism: int, **read_args) -> List[ReadTask]:
        raise NotImplementedError

    def write_block(self, block: Block, path: str, index: int, **write_args) -> str:
        raise NotImplementedError(f"{type(self).__name__} does not support writes")


class RangeDatasource(Datasource):
    """ds.range / range_tensor backing (reference range_datasource.py)."""

    def __init__(self, n: int, tensor_shape: Optional[tuple] = None):
        self.n = n
        self.tensor_shape = tensor_shape

    def prepare_read(self, parallelism: int, **_) -> List[ReadTask]:
        n = self.n
        parallelism = max(1, min(parallelism, n or 1))
        per = math.ceil(n / parallelism) if n else 0
        tasks = []
        for i in range(parallelism):
            lo, hi = i * per, min((i + 1) * per, n)
            if lo >= hi:
                continue
            shape = self.tensor_shape

            def read(lo=lo, hi=hi, shape=shape) -> Block:
                if shape is None:
                    return {"value": np.arange(lo, hi)}
                data = np.arange(lo, hi).reshape(-1, *([1] * len(shape))) * np.ones(shape)
                return {"data": data}

            tasks.append(ReadTask(read, num_rows=hi - lo))
        return tasks or [ReadTask(lambda: {"value": np.asarray([])}, num_rows=0)]


def _expand_paths(paths: Union[str, List[str]], suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                if suffix is None or name.endswith(suffix):
                    out.append(os.path.join(p, name))
        else:
            out.append(p)
    return out


class FileBasedDatasource(Datasource):
    """One read task per file (the reference's FileBasedDatasource)."""

    suffix: Optional[str] = None

    def __init__(self, paths: Union[str, List[str]], **read_args):
        self.paths = _expand_paths(paths, self.suffix)
        self.read_args = read_args

    def read_file(self, path: str, **read_args) -> Block:
        raise NotImplementedError

    def prepare_read(self, parallelism: int, **_) -> List[ReadTask]:
        return [
            ReadTask(lambda p=p: self.read_file(p, **self.read_args))
            for p in self.paths
        ]


class CSVDatasource(FileBasedDatasource):
    suffix = ".csv"

    def read_file(self, path: str, **kw) -> Block:
        import pandas as pd

        df = pd.read_csv(path, **kw)
        return {c: df[c].to_numpy() for c in df.columns}

    def write_block(self, block: Block, path: str, index: int, **kw) -> str:
        import pandas as pd

        from ray_tpu.data.block import BlockAccessor

        out = os.path.join(path, f"part-{index:05d}.csv")
        pd.DataFrame(BlockAccessor(block).to_batch()).to_csv(out, index=False, **kw)
        return out


class JSONDatasource(FileBasedDatasource):
    suffix = ".json"

    def read_file(self, path: str, **kw) -> Block:
        import pandas as pd

        df = pd.read_json(path, orient="records", lines=True, **kw)
        return {c: df[c].to_numpy() for c in df.columns}

    def write_block(self, block: Block, path: str, index: int, **kw) -> str:
        import pandas as pd

        from ray_tpu.data.block import BlockAccessor

        out = os.path.join(path, f"part-{index:05d}.json")
        pd.DataFrame(BlockAccessor(block).to_batch()).to_json(
            out, orient="records", lines=True, **kw)
        return out


class ParquetDatasource(FileBasedDatasource):
    suffix = ".parquet"

    def read_file(self, path: str, **kw) -> Block:
        try:
            import pyarrow.parquet as pq

            # native Arrow blocks: zero-copy into the store (pickle-5
            # out-of-band buffers), zero-copy slicing downstream
            return pq.read_table(path, **kw)
        except ImportError:
            import pandas as pd

            df = pd.read_parquet(path, **kw)
            return {c: df[c].to_numpy() for c in df.columns}

    def write_block(self, block: Block, path: str, index: int, **kw) -> str:
        from ray_tpu.data.block import BlockAccessor

        out = os.path.join(path, f"part-{index:05d}.parquet")
        try:
            import pyarrow.parquet as pq

            pq.write_table(BlockAccessor(block).to_arrow(), out, **kw)
        except ImportError:
            import pandas as pd

            pd.DataFrame(BlockAccessor(block).to_batch()).to_parquet(out, **kw)
        return out


class NumpyDatasource(FileBasedDatasource):
    suffix = ".npy"

    def read_file(self, path: str, **kw) -> Block:
        return {"value": np.load(path, **kw)}

    def write_block(self, block: Block, path: str, index: int, **kw) -> str:
        from ray_tpu.data.block import BlockAccessor

        out = os.path.join(path, f"part-{index:05d}.npy")
        batch = BlockAccessor(block).to_batch()
        col = batch.get("value", next(iter(batch.values())) if batch else np.asarray([]))
        np.save(out, col)
        return out


class TextDatasource(FileBasedDatasource):
    def read_file(self, path: str, **kw) -> Block:
        with open(path) as f:
            return [line.rstrip("\n") for line in f]


class BinaryDatasource(FileBasedDatasource):
    def read_file(self, path: str, **kw) -> Block:
        with open(path, "rb") as f:
            return [{"path": path, "bytes": f.read()}]
