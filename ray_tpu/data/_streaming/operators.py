"""Physical operators for the streaming executor.

The logical plan records stages; this module lowers the streamable part of
a plan into three physical pieces (the reference's
``_internal/execution/operators`` reduced to its load-bearing core):

- an **input source**: an iterator of upstream block refs.  A barrier
  prefix (shuffle/sort/actor-pool) executes eagerly ONCE and is cached on
  the plan, exactly like the eager engine; an ``ObjectRefGenerator`` input
  streams refs as the producer task yields them.
- a **MapOperator**: the maximal fused run of trailing one-to-one stages,
  submitted one task per block.  Submission accepts a locality hint — the
  task dispatches with a soft node affinity toward the node that will
  consume the block, so the output materializes next to its consumer.
- an **output splitter policy**: which split the next block belongs to
  (row-balanced when counts are known, round-robin otherwise).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data.plan import (
    ExecutionPlan,
    OneToOneStage,
    _run_fused,
)


class MapOperator:
    """Fused one-to-one transform: one remote task per input block."""

    def __init__(self, stages: List[OneToOneStage]):
        self.fns: List[Callable] = [s.fn for s in stages]
        self.name = "+".join(s.name for s in stages)
        self.num_cpus = max(s.num_cpus for s in stages)
        self._task = ray_tpu.remote(num_cpus=self.num_cpus)(_run_fused)

    def submit(self, ref: Any, locality_hint: Optional[str] = None) -> Any:
        """Launch the fused task for one block; returns the output ref.

        ``locality_hint`` dispatches the task with SOFT node affinity: the
        block materializes on the consumer's node when it has capacity, and
        falls back to the default policy (rather than queueing) when not —
        a hint, never a constraint, matching the reference's locality-aware
        output splitting.
        """
        if locality_hint:
            from ray_tpu.util.scheduling_strategies import (
                NodeAffinitySchedulingStrategy,
            )

            return self._task.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    locality_hint, soft=True)
            ).remote(ref, self.fns)
        return self._task.remote(ref, self.fns)


def resolve_streaming_input(
    plan: ExecutionPlan,
) -> Tuple[Any, Optional[List[int]], List[OneToOneStage]]:
    """Split ``plan`` at its last barrier: returns (input refs — a list or
    an ObjectRefGenerator, row counts when known, streamable one-to-one
    suffix stages).  The barrier prefix executes eagerly ONCE and is
    cached on the plan (a second epoch must not redo the shuffle)."""
    if plan._out is not None:
        refs, counts = plan._out
        return list(refs), counts, []
    barrier = -1
    for i, s in enumerate(plan.stages):
        if not isinstance(s, OneToOneStage):
            barrier = i
    suffix = list(plan.stages[barrier + 1:])
    if barrier >= 0:
        cached = getattr(plan, "_stream_prefix_out", None)
        if cached is None:
            prefix_plan = ExecutionPlan(
                plan.input_refs, plan.input_counts,
                plan.stages[:barrier + 1])
            cached = prefix_plan.execute()
            plan._stream_prefix_out = cached
            plan._stats.extend(prefix_plan.stats())
        refs_in, counts_in = cached
        if not suffix:
            # preserve the prefix's row counts in the cache: count() sums
            # them instead of launching a per-block count task
            plan._out = (list(refs_in), counts_in)
        # counts_in describes refs_in (the suffix's INPUT blocks) — the
        # right row weights for equal-mode split assignment either way
        return list(refs_in), counts_in, suffix
    return plan.input_refs, plan.input_counts, suffix


def build_streaming_topology(
    plan: ExecutionPlan,
) -> Tuple[Iterator[Any], Optional[List[int]], Optional[MapOperator]]:
    """Lower ``plan`` into (input ref iterator, input row counts if known,
    map operator or None).

    Mirrors the split the eager engine makes: everything up to the LAST
    barrier stage (AllToAll / actor pool) executes eagerly — and is cached
    on the plan so a second epoch does not redo the shuffle — while the
    trailing one-to-one suffix streams.  A plan with a cached result
    degenerates to a passthrough over its output refs.
    """
    from ray_tpu._private.object_ref import ObjectRefGenerator

    refs_in, counts, suffix = resolve_streaming_input(plan)
    if isinstance(refs_in, ObjectRefGenerator):
        # blocks stream from a num_returns="dynamic" producer task; refs
        # are consumed AS THE PRODUCER YIELDS THEM (listing would block
        # until the producer finishes)
        source: Any = iter(refs_in)
        counts = None
    else:
        if not suffix and not plan.stages and plan._out is None:
            # stage-free list plan: executing just caches (refs, counts)
            refs_in, counts = plan.execute()
        # a LIST (not a bare iterator) tells the executor the source is
        # static, so equal-mode splits can be pre-assigned up front even
        # when row counts are unknown (e.g. after a barrier prefix)
        source = list(refs_in)
    return source, counts, MapOperator(suffix) if suffix else None


def pick_split(
    assigned_rows: List[int],
    assigned_blocks: List[int],
    open_splits: List[int],
    block_rows: Optional[int],
) -> int:
    """Output-splitter policy: the next block goes to the open split with
    the fewest assigned rows (row-balanced when counts are known), blocks
    otherwise — the ``equal``-ish block-granular assignment of the
    reference's OutputSplitter."""
    if block_rows is not None:
        return min(open_splits, key=lambda i: (assigned_rows[i],
                                               assigned_blocks[i], i))
    return min(open_splits, key=lambda i: (assigned_blocks[i], i))
