"""StreamingExecutor: pump a plan's operator pipeline under a block budget.

The executor owns a background pump thread that walks the input source,
submits the fused map task for each block (with a locality hint toward the
split that will consume it), and routes output refs into per-split
queues.  Backpressure is the core contract: per split, at most
``max_in_flight_blocks`` blocks may be submitted-but-unconsumed at any
moment — a slow consumer stalls its own submissions (and only its own; a
multi-split pump skips stalled splits) instead of flooding the cluster
with materialized blocks, the bounded-resource loop of the reference's
``streaming_executor.py``.

Consumption can begin as soon as the FIRST task is submitted — the
consumer's ``get`` blocks on the seal, so transform execution overlaps
batch consumption end to end.
"""

from __future__ import annotations

import os
import queue as queue_mod
import threading
import time
from typing import Any, Iterator, List, Optional

from ray_tpu._private import events as _events
from ray_tpu.data._streaming.operators import (
    build_streaming_topology,
    pick_split,
)

# Lazy streaming metric singletons (Counter tags: op).
_STREAM_METRICS = None
# flight-recorder stall events are throttled to one per this window per
# executor — the stall-time counter carries the exact accounting
_STALL_EVENT_MIN_INTERVAL_S = 1.0


def _stream_metrics():
    global _STREAM_METRICS
    if _STREAM_METRICS is None:
        from ray_tpu.util.metrics import Counter

        _STREAM_METRICS = {
            "blocks": Counter("ray_tpu_streaming_blocks_total",
                              "blocks submitted per operator",
                              tag_keys=("op",)),
            "stall": Counter("ray_tpu_streaming_stall_s_total",
                             "pump seconds stalled on backpressure",
                             tag_keys=("op",)),
            "starved": Counter("ray_tpu_streaming_consumer_wait_s_total",
                               "consumer seconds blocked on an empty split",
                               tag_keys=("op",)),
        }
    return _STREAM_METRICS

# Per-split in-flight block budget.  8 blocks of a typical 32 MB block is
# a 256 MB window per consumer: deep enough to hide task latency, bounded
# enough that a stalled trainer pins O(window), not O(dataset).
DEFAULT_BLOCK_BUDGET = 8

_EOF = object()


def _budget_default() -> int:
    try:
        return max(1, int(os.environ.get("RAY_TPU_STREAMING_BLOCK_BUDGET",
                                         DEFAULT_BLOCK_BUDGET)))
    except ValueError:
        return DEFAULT_BLOCK_BUDGET


class StreamingExecutor:
    """Run one plan as a streaming pipeline feeding ``num_splits`` consumers."""

    def __init__(
        self,
        plan,
        *,
        num_splits: int = 1,
        locality_hints: Optional[List[Optional[str]]] = None,
        max_in_flight_blocks: Optional[int] = None,
        preassign: bool = True,
    ):
        self._plan = plan
        self._n = max(1, num_splits)
        # equal-mode splits pre-assign blocks up front (deterministic,
        # consumption-speed-independent); preassign=False (equal=False)
        # keeps drain-rate assignment to whichever split has room
        self._preassign = preassign
        self._hints = list(locality_hints or [])
        if self._hints and len(self._hints) != self._n:
            raise ValueError(
                f"locality_hints has {len(self._hints)} entries for "
                f"{self._n} splits")
        self._budget = max_in_flight_blocks or _budget_default()
        # topology (incl. any barrier-prefix execution) is built LAZILY on
        # the pump thread: constructing an executor — e.g. calling
        # iter_batches() on a shuffled dataset — must not run the shuffle;
        # that happens on first consumption, and build errors surface on
        # the consumer like any stream error
        self._source: Any = None
        self._counts: Optional[List[int]] = None
        self._map_op = None
        self._queues = [queue_mod.Queue() for _ in range(self._n)]
        self._in_flight = [0] * self._n
        self._assigned_rows = [0] * self._n
        self._assigned_blocks = [0] * self._n
        self._out_refs: List[List[Any]] = [[] for _ in range(self._n)]
        self._delivered = [0] * self._n
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._started = False
        self._t0 = 0.0
        # set ONLY when the full source was produced, strictly BEFORE the
        # final _EOF is queued: _maybe_finalize keys off it, so a partial
        # (abandoned) run can never cache itself as the plan's result
        self._produced_all = threading.Event()
        self._finalized = False
        # observability: the largest in-flight total ever observed, so the
        # backpressure contract is assertable from the outside
        self.max_in_flight_observed = 0
        # flight recorder: per-ref submit times (operator span = submit ->
        # consumer delivery), bounded by the in-flight budget; plus stall
        # accounting and event throttling
        self._span_t0: dict = {}
        self._stall_s = 0.0
        self._last_stall_event = 0.0
        # a consumer inside a trace() block: the pump thread adopts the
        # context (map-task submissions chain under it) and operator/stall
        # spans carry the trace lineage
        self._trace_ctx = None
        if _events.ENABLED:
            from ray_tpu.util import tracing

            self._trace_ctx = tracing.current_context()

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "StreamingExecutor":
        with self._cond:
            # check-and-set under the lock: the coordinator actor's first
            # get_next can arrive on N threads at once, and two pumps
            # would race each other over the one source iterator
            if self._started:
                return self
            self._started = True
            self._t0 = time.perf_counter()
        threading.Thread(target=self._pump, daemon=True,
                         name="streaming-executor-pump").start()
        return self

    def shutdown(self) -> None:
        """Stop the pump (consumer abandoned the stream).  Idempotent;
        also wakes any consumer blocked in ``get_next`` (it sees end of
        stream) so abandonment never strands a blocked thread."""
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        for q in self._queues:
            q.put(_EOF)

    # -- consumer side -------------------------------------------------
    def _op_name(self) -> str:
        return self._map_op.name if self._map_op is not None else "source"

    def get_next(self, split: int = 0, timeout: Optional[float] = None):
        """Next output ref for ``split``; ``None`` at end of stream."""
        self.start()
        # captured ONCE: ENABLED is mutable module state (the overhead
        # bench flips it at runtime) and an off->on flip mid-get must not
        # turn t0==0.0 into hours of bogus recorded wait
        enabled = _events.ENABLED
        t0 = time.perf_counter() if enabled else 0.0
        item = self._queues[split].get(timeout=timeout)
        if enabled:
            waited = time.perf_counter() - t0
            if waited > 0.05:
                # split starvation: the consumer outran the pipeline
                _stream_metrics()["starved"].inc(
                    waited, tags={"op": self._op_name()})
                _events.emit("streaming", "split starved",
                             severity="DEBUG", entity_id=str(split),
                             wait_s=round(waited, 4), op=self._op_name())
        if item is _EOF:
            self._queues[split].put(_EOF)  # repeated polls stay terminal
            self._maybe_finalize()
            return None
        if isinstance(item, BaseException):
            self._queues[split].put(item)  # stays terminal, like _EOF
            raise item
        with self._cond:
            self._in_flight[split] -= 1
            self._delivered[split] += 1
            self._cond.notify_all()
        if enabled:
            sub_t = self._span_t0.pop(id(item), None)
            if sub_t is not None:
                # operator span: submit -> delivery, a timeline slice
                # (trace-tagged when the consumer runs inside a trace)
                _events.emit("streaming", self._op_name(), severity="DEBUG",
                             entity_id=str(split),
                             span_dur=time.perf_counter() - sub_t,
                             **self._trace_fields("operator"))
        return item

    def _trace_fields(self, phase: str) -> dict:
        """Span-lineage kwargs for an emit when a trace context was
        captured (else empty — untraced emits stay byte-identical)."""
        from ray_tpu.util.tracing import span_fields

        return span_fields(self._trace_ctx, phase)

    def iter_refs(self, split: int = 0) -> Iterator[Any]:
        """Blocking iterator over one split's output refs."""
        self.start()
        try:
            while True:
                ref = self.get_next(split)
                if ref is None:
                    return
                yield ref
        finally:
            self.shutdown()

    # -- pump ----------------------------------------------------------
    def _acquire_split(self, block_rows: Optional[int]) -> Optional[int]:
        """Block until some split has budget room; returns it (or None on
        stop).  A stalled split never blocks the others."""
        t0 = time.perf_counter()
        with self._cond:
            while not self._stop.is_set():
                room = [i for i in range(self._n)
                        if self._in_flight[i] < self._budget]
                if room:
                    split = pick_split(self._assigned_rows,
                                       self._assigned_blocks, room,
                                       block_rows)
                    self._in_flight[split] += 1
                    self._assigned_blocks[split] += 1
                    if block_rows is not None:
                        self._assigned_rows[split] += block_rows
                    total = sum(self._in_flight)
                    if total > self.max_in_flight_observed:
                        self.max_in_flight_observed = total
                    if _events.ENABLED:
                        waited = time.perf_counter() - t0
                        if waited > 0.001:
                            self._record_stall(waited)
                    return split
                self._cond.wait(timeout=0.2)
        return None

    def _record_stall(self, waited: float) -> None:
        """Backpressure accounting: the pump sat blocked on every split's
        budget for ``waited`` seconds (cond lock held)."""
        self._stall_s += waited
        _stream_metrics()["stall"].inc(waited, tags={"op": self._op_name()})
        now = time.perf_counter()
        if now - self._last_stall_event >= _STALL_EVENT_MIN_INTERVAL_S:
            self._last_stall_event = now
            _events.emit(
                "streaming", "backpressure stall", severity="DEBUG",
                op=self._op_name(), stalled_s=round(waited, 4),
                total_stalled_s=round(self._stall_s, 3),
                in_flight=list(self._in_flight), budget=self._budget,
                **self._trace_fields("backpressure"))

    def _pump(self) -> None:
        try:
            if self._trace_ctx is not None:
                # the pump thread submits the map tasks: adopting the
                # consumer's context makes their specs (and so the task
                # table) part of the trace
                from ray_tpu.util import tracing

                tracing.adopt(self._trace_ctx)
            self._source, self._counts, self._map_op = \
                build_streaming_topology(self._plan)
            # preassignment needs a static source; a generator source
            # (unknown length) falls back to dynamic assignment
            if self._n > 1 and self._preassign \
                    and isinstance(self._source, list):
                self._pump_preassigned()
            else:
                if not self._pump_dynamic():
                    return  # abandoned
        except BaseException as e:  # surfaced on every consumer
            for q in self._queues:
                q.put(e)

    def _submit(self, split: int, ref) -> None:
        hint = self._hints[split] if self._hints else None
        out = (self._map_op.submit(ref, hint)
               if self._map_op is not None else ref)
        if _events.ENABLED:
            _stream_metrics()["blocks"].inc(tags={"op": self._op_name()})
            self._span_t0[id(out)] = time.perf_counter()
        self._out_refs[split].append(out)
        self._queues[split].put(out)

    def _pump_dynamic(self) -> bool:
        """Arrival-order assignment to whichever split has budget room —
        the single-split and unknown-row-count (generator / ``equal=False``)
        path.  Returns False if the stream was abandoned mid-pump."""
        for idx, ref in enumerate(self._source):
            rows = None
            if self._counts is not None and idx < len(self._counts):
                rows = self._counts[idx]
            split = self._acquire_split(rows)
            if split is None:
                return False  # abandoned
            self._submit(split, ref)
        self._produced_all.set()
        for q in self._queues:
            q.put(_EOF)
        return True

    def _pump_preassigned(self) -> None:
        """Deterministic row-balanced assignment, decided UP FRONT over all
        splits — never by which consumer drains fastest.  Equal-mode gangs
        run a collective per batch: if a rank that stalls at its budget
        (checkpointing, say) lost its blocks to faster ranks, the ranks
        would finish the epoch with different batch counts and deadlock.
        Each split's submissions still stall independently on its own
        budget, so a slow split never blocks a fast one."""
        from collections import deque

        refs = list(self._source)
        counts = self._counts or []
        pending = [deque() for _ in range(self._n)]
        rows = [0] * self._n
        blocks = [0] * self._n
        for idx, ref in enumerate(refs):
            r = counts[idx] if idx < len(counts) else 0
            s = min(range(self._n), key=lambda i: (rows[i], blocks[i], i))
            pending[s].append(ref)
            rows[s] += r
            blocks[s] += 1
        with self._cond:
            self._assigned_rows[:] = rows
            self._assigned_blocks[:] = blocks
        if not any(pending):  # empty source
            self._produced_all.set()
        for i in range(self._n):
            if not pending[i]:
                self._queues[i].put(_EOF)  # more splits than blocks
        while True:
            with self._cond:
                while True:
                    if self._stop.is_set():
                        return  # abandoned
                    ready = [i for i in range(self._n)
                             if pending[i]
                             and self._in_flight[i] < self._budget]
                    if ready or not any(pending):
                        break
                    self._cond.wait(timeout=0.2)
                if not ready:
                    return  # fully drained; per-split _EOFs already sent
                picks = [(i, pending[i].popleft()) for i in ready]
                for i, _ in picks:
                    self._in_flight[i] += 1
                total = sum(self._in_flight)
                if total > self.max_in_flight_observed:
                    self.max_in_flight_observed = total
            for i, ref in picks:
                self._submit(i, ref)
                if not pending[i]:
                    if not any(pending):  # that was the final block
                        self._produced_all.set()
                    self._queues[i].put(_EOF)

    # -- completion bookkeeping ----------------------------------------
    def _maybe_finalize(self) -> None:
        """After a FULL single-split drain, cache the result on the plan
        (re-iteration / count() reuse these refs instead of re-running)
        and record the streamed stage's stats."""
        if not self._produced_all.is_set():
            return
        with self._cond:
            # check-and-set under the lock: multiple splits' consumers can
            # hit their _EOF simultaneously on separate actor threads
            if self._finalized:
                return
            self._finalized = True
        produced = sum(len(r) for r in self._out_refs)
        name = self._map_op.name if self._map_op is not None else None
        if self._n == 1:
            if sum(self._delivered) == produced and name is not None \
                    and self._plan._out is None:
                self._plan._out = (self._out_refs[0], None)
        if name is not None:
            suffix = ("streamed" if self._n == 1
                      else f"streaming_split={self._n}")
            self._plan._stats.append({
                "stage": f"{name} ({suffix})",
                "wall_s": round(time.perf_counter() - self._t0, 4),
                "blocks": produced,
            })

    def stats(self) -> dict:
        return {
            "num_splits": self._n,
            "budget_per_split": self._budget,
            "max_in_flight_observed": self.max_in_flight_observed,
            "produced_blocks": sum(len(r) for r in self._out_refs),
            "delivered_blocks": sum(self._delivered),
            "stalled_s": round(self._stall_s, 4),
        }
