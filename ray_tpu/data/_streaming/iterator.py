"""Shared batch-iteration engine over a stream of block refs.

One implementation feeds every consumption surface — ``Dataset.iter_batches``,
``StreamSplitDataIterator.iter_batches`` (trainer shards), and the bench's
ingest loop — so batching, prefetch, and zero-copy slicing semantics can
never diverge between the driver path and the per-worker shard path.

Zero-copy contract: a fetched block is a deserialized view over its sealed
store segment (mmap/arena slice); batch slicing stays columnar
(``BlockAccessor.slice`` — numpy views / ``pa.Table.slice``), so the bytes
of a batch are never copied between the producing task's seal and the
training loop, except at the block-boundary carry concat.
"""

from __future__ import annotations

import queue as queue_mod
import threading
from typing import Any, Callable, Iterator, Optional

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor


def format_batch(block: Block, batch_format: str):
    acc = BlockAccessor(block)
    if batch_format == "rows":
        return acc.to_rows()
    if batch_format == "pandas":
        import pandas as pd

        return pd.DataFrame(acc.to_rows())
    if batch_format in ("pyarrow", "arrow"):
        return acc.to_arrow()
    if batch_format != "numpy":
        raise ValueError(f"unknown batch_format {batch_format!r}")
    batch = acc.to_batch()
    if set(batch) == {"value"}:
        return batch["value"]
    return batch


def batches_from_block_iter(
    refs: Iterator[Any],
    *,
    batch_size: int = 256,
    batch_format: str = "numpy",
    drop_last: bool = False,
    prefetch_blocks: int = 2,
    on_abandon: Optional[Callable[[], None]] = None,
) -> Iterator[Any]:
    """Stream batches from an iterator of block refs.

    A background thread keeps up to ``prefetch_blocks`` blocks materialized
    ahead of consumption, so object fetch (incl. cross-node pulls) overlaps
    compute; abandoning the iterator stops the fetcher promptly.

    ``on_abandon`` (e.g. the producing executor's ``shutdown``) runs at
    cleanup: while the fetcher thread is suspended INSIDE the ``refs``
    generator frame, ``refs.close()`` cannot run (``ValueError: generator
    already executing``), so the producer must be stopped out-of-band —
    shutdown wakes the fetcher, the generator exits, and nothing leaks.
    """
    q: "queue_mod.Queue" = queue_mod.Queue(maxsize=max(1, prefetch_blocks))
    SENTINEL = object()
    stop = threading.Event()

    def put_or_stop(item) -> bool:
        """Stop-aware put; True if delivered."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue_mod.Full:
                continue
        return False

    def fetcher():
        from ray_tpu._private.worker import global_worker

        if global_worker.mode == "worker":
            # task_depth is THREAD-local; without inheriting it here this
            # thread's blocking gets never notify the head, so the worker's
            # CPU lease is not released and tasks pipelined behind the
            # consuming task cannot be reclaimed — if one of those produces
            # the very block this get waits on, that's a deadlock
            global_worker.task_depth = 1
        try:
            for ref in refs:
                block = ray_tpu.get(ref)
                if not put_or_stop(block):
                    return  # consumer abandoned the iterator
        except BaseException as e:  # surfaced on the consumer side
            put_or_stop(e)
            return
        put_or_stop(SENTINEL)

    t = threading.Thread(target=fetcher, daemon=True,
                         name="iter-batches-prefetch")
    t.start()
    from ray_tpu._private import events as _events

    ingest_wait_counter = None
    if _events.ENABLED:
        from ray_tpu.util.metrics import Counter

        ingest_wait_counter = Counter(
            "ray_tpu_data_ingest_wait_s_total",
            "consumer seconds blocked waiting for the next block "
            "(train ingest-wait)")
    try:
        # the carry and all slicing stay columnar for table blocks —
        # numpy views, no per-row python objects on the hot path
        carry: Optional[Block] = None
        import time as _time

        while True:
            t0 = _time.perf_counter() if ingest_wait_counter else 0.0
            item = q.get()
            if ingest_wait_counter is not None:
                waited = _time.perf_counter() - t0
                if waited > 1e-4:
                    ingest_wait_counter.inc(waited)
            if item is SENTINEL:
                break
            if isinstance(item, BaseException):
                raise item
            block = item if carry is None else BlockAccessor.concat([carry, item])
            carry = None
            acc = BlockAccessor(block)
            n, pos = acc.num_rows(), 0
            while n - pos >= batch_size:
                yield format_batch(acc.slice(pos, pos + batch_size), batch_format)
                pos += batch_size
            if pos < n:
                carry = acc.slice(pos, n)
        if carry is not None and BlockAccessor(carry).num_rows() and not drop_last:
            yield format_batch(carry, batch_format)
    finally:
        # unblocks (and ends) the fetcher if the consumer broke early
        stop.set()
        if on_abandon is not None:
            on_abandon()  # stop the producer first so the fetcher wakes
        close = getattr(refs, "close", None)
        if close is not None:
            try:
                close()
            except ValueError:
                # the fetcher is mid-next() inside the generator frame;
                # on_abandon already stopped the producer, so the frame
                # unwinds on its own and close() is unnecessary
                pass
