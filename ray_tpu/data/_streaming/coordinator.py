"""Split coordinator: streaming shards for multiple consumer processes.

``Dataset.streaming_split(n)`` must hand each trainer worker a handle it
can iterate from ITS OWN process while one pipeline feeds all of them.
The reference solves this with a ``SplitCoordinator`` actor
(``_internal/execution/streaming_executor`` + ``split_coordinator.py``);
this is the same shape:

- a head-scheduled ``_SplitCoordinator`` actor owns the
  :class:`~ray_tpu.data._streaming.executor.StreamingExecutor` for the
  plan's streamable suffix.  Map tasks dispatch with a soft node-affinity
  hint toward the consuming split's node, so blocks materialize on the
  node that eats them and the consumer's ``get`` is a local zero-copy
  attach — ONE coordinator round trip per block, none per batch.
- each consumer holds a picklable :class:`StreamSplitDataIterator`
  (actor handle + split index) exposing the same ``iter_batches`` surface
  as a Dataset.

Epoch contract (same as the reference): every consumer drains its split
fully per epoch.  The first epoch streams; the coordinator records each
split's block refs (and keeps them pinned), so later epochs replay the
recorded refs in one round trip without re-running the map tasks.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterator, List, Optional

import ray_tpu
from ray_tpu.data._streaming.executor import StreamingExecutor
from ray_tpu.data._streaming.iterator import batches_from_block_iter


class _SplitCoordinator:
    """Actor owning one streaming run fanned out to N splits."""

    def __init__(self, refs: List[Any], counts: Optional[List[int]],
                 stages_blob: bytes, num_splits: int,
                 locality_hints: Optional[List[Optional[str]]],
                 max_in_flight_blocks: Optional[int],
                 equal: bool = True):
        import cloudpickle

        from ray_tpu.data.plan import ExecutionPlan, OneToOneStage

        stages = [OneToOneStage(name, fn, num_cpus)
                  for name, fn, num_cpus in cloudpickle.loads(stages_blob)]
        self._plan = ExecutionPlan(list(refs), counts, stages)
        self._n = num_splits
        self._exec = StreamingExecutor(
            self._plan, num_splits=num_splits,
            locality_hints=locality_hints,
            max_in_flight_blocks=max_in_flight_blocks,
            preassign=equal,
        )
        self._lock = threading.Lock()
        self._recorded: List[List[Any]] = [[] for _ in range(num_splits)]
        self._finished = [False] * num_splits

    def get_block_at(self, split: int, i: int):
        """The split's ``i``-th block (pulling the pipeline forward as
        needed), or None past the end.  INDEX-based on purpose: every
        consumer iteration walks i = 0, 1, 2, ... over the recorded list,
        so a re-iteration after a mid-epoch abandonment replays the full
        shard, and a stale abandoned prefetch thread's concurrent call can
        never make a block vanish — whatever it pulls lands in
        ``_recorded`` where the live iteration's index reaches it."""
        while True:
            with self._lock:
                if i < len(self._recorded[split]):
                    return self._recorded[split][i]
                if self._finished[split]:
                    return None
            ref = self._exec.get_next(split)  # blocking; outside the lock
            with self._lock:
                if ref is None:
                    self._finished[split] = True
                else:
                    self._recorded[split].append(ref)

    def get_replay(self, split: int) -> Optional[List[Any]]:
        """The split's full block list once its first epoch finished
        (later epochs iterate these refs with zero coordinator round
        trips per block), else None."""
        with self._lock:
            if self._finished[split]:
                return list(self._recorded[split])
        return None

    def stats(self) -> Dict[str, Any]:
        return self._exec.stats()


class StreamSplitDataIterator:
    """One consumer's shard of a streaming split (picklable: an actor
    handle plus a split index).  The ``DataIterator`` analog
    (``python/ray/data/iterator.py``): iterate-only — batches stream
    through the coordinator's pipeline; there is no plan to mutate."""

    def __init__(self, coordinator, split: int, world_size: int):
        self._coord = coordinator
        self._split = split
        self._world = world_size

    # -- block plumbing ------------------------------------------------
    def _iter_block_refs(self) -> Iterator[Any]:
        replay = ray_tpu.get(self._coord.get_replay.remote(self._split))
        if replay is not None:
            yield from replay
            return
        # index-walk from 0: a fresh iteration always sees the FULL shard,
        # even if a previous iteration of this split abandoned mid-epoch
        i = 0
        while True:
            ref = ray_tpu.get(
                self._coord.get_block_at.remote(self._split, i))
            if ref is None:
                return
            yield ref
            i += 1

    # -- the Dataset-compatible consumption surface --------------------
    def iter_batches(
        self, *, batch_size: int = 256, batch_format: str = "numpy",
        drop_last: bool = False, prefetch_blocks: int = 2,
    ) -> Iterator[Any]:
        return batches_from_block_iter(
            self._iter_block_refs(), batch_size=batch_size,
            batch_format=batch_format, drop_last=drop_last,
            prefetch_blocks=prefetch_blocks,
        )

    def iter_rows(self) -> Iterator[Any]:
        from ray_tpu.data.block import BlockAccessor

        for ref in self._iter_block_refs():
            yield from BlockAccessor(ray_tpu.get(ref)).iter_rows()

    def iter_torch_batches(self, *, batch_size: Optional[int] = None,
                           prefetch_blocks: int = 1, drop_last: bool = False):
        import numpy as np
        import torch

        for batch in self.iter_batches(
            batch_size=batch_size or 256, batch_format="numpy",
            prefetch_blocks=prefetch_blocks, drop_last=drop_last,
        ):
            if isinstance(batch, dict):
                yield {k: torch.as_tensor(np.asarray(v)) for k, v in batch.items()}
            else:
                yield torch.as_tensor(np.asarray(batch))

    def count(self) -> int:
        from ray_tpu.data.block import BlockAccessor

        return sum(BlockAccessor(ray_tpu.get(ref)).num_rows()
                   for ref in self._iter_block_refs())

    def world_size(self) -> int:
        return self._world

    def stats(self) -> Dict[str, Any]:
        return ray_tpu.get(self._coord.stats.remote())

    def __repr__(self):
        return (f"StreamSplitDataIterator(split={self._split}, "
                f"world_size={self._world})")


def make_split_iterators(
    ds,
    n: int,
    *,
    equal: bool = True,
    locality_hints: Optional[List[Optional[str]]] = None,
    max_in_flight_blocks: Optional[int] = None,
) -> List[StreamSplitDataIterator]:
    """Build the coordinator actor + per-consumer iterators for
    ``Dataset.streaming_split``.

    The barrier prefix (shuffle/sort/actor-pool stages) executes in the
    CALLING process first — driver-side caching applies — and only block
    refs plus the picklable one-to-one suffix ship to the coordinator.
    ``equal`` balances splits at block granularity (row-weighted when
    counts are known); rows are never re-sliced, so splits differ by at
    most one block's rows.
    """
    import cloudpickle

    from ray_tpu._private.object_ref import ObjectRefGenerator
    from ray_tpu.data._streaming.operators import resolve_streaming_input

    if n < 1:
        raise ValueError(f"streaming_split needs n >= 1, got {n}")
    if locality_hints is not None and len(locality_hints) != n:
        raise ValueError(
            f"locality_hints has {len(locality_hints)} entries for {n} splits")
    refs, counts, suffix = resolve_streaming_input(ds._plan)
    if isinstance(refs, ObjectRefGenerator):
        # a dynamic-generator input cannot ship to another process; drain
        # it (the producer's blocks are materialized either way once every
        # split must see a stable assignment)
        refs = list(refs)
        counts = None
    stages_blob = cloudpickle.dumps(
        [(s.name, s.fn, s.num_cpus) for s in suffix])
    Coordinator = ray_tpu.remote(
        num_cpus=0, max_concurrency=n + 2)(_SplitCoordinator)
    coord = Coordinator.remote(list(refs), counts, stages_blob, n,
                               locality_hints, max_in_flight_blocks, equal)
    return [StreamSplitDataIterator(coord, i, n) for i in range(n)]
