"""Streaming data-plane executor (the ``_internal/streaming_executor``
analog).

An execution layer between the lazy :class:`~ray_tpu.data.plan.ExecutionPlan`
and the object plane: the plan's one-to-one suffix runs as a pipeline of
operators with a bounded in-flight block budget (backpressure), blocks are
assigned to output splits locality-aware (map tasks dispatch with a soft
node-affinity hint toward the consuming trainer's node, so blocks
materialize where they are eaten and ``get`` attaches them zero-copy
instead of pulling), and batches slice sealed store segments without
copying.

Layers:

- :mod:`.operators` — the physical operator descriptors built from a plan
  (input source, fused map operator, output splitter policy).
- :mod:`.executor` — ``StreamingExecutor``: the driver-side pump that runs
  the operator pipeline under a block budget and feeds per-split queues.
- :mod:`.coordinator` — the head-scheduled coordinator actor behind
  ``Dataset.streaming_split`` plus the picklable per-consumer
  ``StreamSplitDataIterator`` handed to trainer workers.
"""

from ray_tpu.data._streaming.executor import StreamingExecutor
from ray_tpu.data._streaming.iterator import batches_from_block_iter
from ray_tpu.data._streaming.coordinator import (
    StreamSplitDataIterator,
    make_split_iterators,
)

__all__ = [
    "StreamingExecutor",
    "StreamSplitDataIterator",
    "batches_from_block_iter",
    "make_split_iterators",
]
