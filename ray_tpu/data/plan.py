"""Lazy execution plan over blocks — the ExecutionPlan analog.

The reference builds a deferred graph of stages and fuses compatible ones
before running tasks (``python/ray/data/_internal/plan.py:74``,
``_OneToOneStage``/``_AllToAllStage`` fusion): transforms on a Dataset
only record stages; execution happens once, at consumption.  Chains of
one-to-one stages (map/filter/flat_map/map_batches) are fused into a
single remote task per block — one serialization boundary and one
scheduling round-trip for the whole chain instead of one per stage.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.data.block import Block

# An AllToAll stage takes the realized block refs (+ row counts when
# known) and returns new refs (+ counts when known).
AllToAllFn = Callable[[List[Any], Optional[List[int]]],
                      Tuple[List[Any], Optional[List[int]]]]


@dataclass
class OneToOneStage:
    """block -> block transform; fusable with neighbors of the same kind."""

    name: str
    fn: Callable[[Block], Block]
    num_cpus: float = 1.0


@dataclass
class AllToAllStage:
    """Global reorganization (shuffle/sort/repartition): sees all refs."""

    name: str
    fn: AllToAllFn


@dataclass
class ActorPoolStage:
    """map_batches over a pool of stateful actors; not fusable."""

    name: str
    submit: Callable[[List[Any]], List[Any]]  # refs -> refs


Stage = Any  # OneToOneStage | AllToAllStage | ActorPoolStage


def _run_fused(block: Block, fns: List[Callable[[Block], Block]]) -> Block:
    for f in fns:
        block = f(block)
    return block


def fuse_one_to_one(stages: List["OneToOneStage"]):
    """(remote task, fns, fused name) for a run of one-to-one stages —
    shared by eager execution and the streaming iterator so fusion
    semantics can never diverge."""
    fns = [s.fn for s in stages]
    task = ray_tpu.remote(num_cpus=max(s.num_cpus for s in stages))(_run_fused)
    return task, fns, "+".join(s.name for s in stages)


@dataclass
class ExecutionPlan:
    """Input block refs + recorded stages; executes at most once."""

    input_refs: List[Any]
    input_counts: Optional[List[int]] = None
    stages: List[Stage] = field(default_factory=list)
    _out: Optional[Tuple[List[Any], Optional[List[int]]]] = None
    _stats: List[Dict[str, Any]] = field(default_factory=list)

    def with_stage(self, stage: Stage) -> "ExecutionPlan":
        """New plan sharing this plan's prefix (and its cached result)."""
        child = ExecutionPlan(self.input_refs, self.input_counts,
                              self.stages + [stage])
        # share the cache of the executed prefix through the parent
        child._parent = self  # type: ignore[attr-defined]
        return child

    def execute(self) -> Tuple[List[Any], Optional[List[int]]]:
        if self._out is not None:
            return self._out
        parent = getattr(self, "_parent", None)
        if parent is not None and parent._out is not None and \
                self.stages[:-1] == parent.stages:
            refs, counts = parent._out
            start = len(parent.stages)
        else:
            refs, counts = self.input_refs, self.input_counts
            if not isinstance(refs, list):
                # streaming (ObjectRefGenerator) input forced by a stage or
                # a full materialization: drain the producer
                refs = list(refs)
                self.input_refs = refs
            start = 0
        i = start
        while i < len(self.stages):
            t0 = time.perf_counter()
            stage = self.stages[i]
            if isinstance(stage, OneToOneStage):
                # fuse the maximal run of one-to-one stages
                run = [stage]
                while i + 1 < len(self.stages) and isinstance(self.stages[i + 1], OneToOneStage):
                    i += 1
                    run.append(self.stages[i])
                task, fns, name = fuse_one_to_one(run)
                refs = [task.remote(r, fns) for r in refs]
                counts = None  # row counts unknown after a transform
            elif isinstance(stage, ActorPoolStage):
                refs = stage.submit(refs)
                counts = None
                name = stage.name
            else:
                refs, counts = stage.fn(refs, counts)
                name = stage.name
            self._stats.append({"stage": name,
                                "wall_s": round(time.perf_counter() - t0, 4),
                                "blocks": len(refs)})
            i += 1
        self._out = (refs, counts)
        return self._out

    def stats(self) -> List[Dict[str, Any]]:
        return list(self._stats)
