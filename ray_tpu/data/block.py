"""Blocks: the unit of distributed data.

Analog of ``python/ray/data/block.py``: a block is an object-store value
holding a batch of rows — a list of rows, a dict-of-numpy column table,
or an Arrow table (``pyarrow.Table``, the reference's native layout —
``python/ray/data/block.py:1`` + ``_internal/arrow_block.py``).
``BlockAccessor`` normalizes the three layouts.

Arrow blocks ride the object store zero-copy: serialization uses
pickle-5 out-of-band buffers (``_private/serialization.py``), and Arrow
tables expose their column buffers through that protocol, so a put/get
round trip never copies the column data into pickle bytes.  Slicing an
Arrow block (``Table.slice``) is zero-copy too, which makes it the right
layout for large read->train ingest paths.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np

try:  # available in this image; guarded so the module stays importable
    import pyarrow as pa
except ImportError:  # pragma: no cover
    pa = None

Block = Union[List[Any], Dict[str, np.ndarray], "pa.Table"]


def _is_arrow(block) -> bool:
    return pa is not None and isinstance(block, pa.Table)


class BlockAccessor:
    def __init__(self, block: Block):
        self.block = block
        self.is_arrow = _is_arrow(block)
        self.is_table = isinstance(block, dict)

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        if self.is_arrow:
            return self.block.num_rows
        if self.is_table:
            return len(next(iter(self.block.values()))) if self.block else 0
        return len(self.block)

    def iter_rows(self) -> Iterator[Any]:
        if self.is_arrow:
            names = self.block.column_names
            if names == ["value"]:
                yield from self.block.column("value").to_pylist()
                return
            for row in self.block.to_pylist():
                yield row
            return
        if self.is_table:
            keys = list(self.block)
            if keys == ["value"]:  # simple block: rows are the plain values
                yield from self.block["value"]
                return
            for i in range(self.num_rows()):
                yield {k: self.block[k][i] for k in keys}
        else:
            yield from self.block

    def to_rows(self) -> List[Any]:
        return list(self.iter_rows())

    def to_batch(self) -> Dict[str, np.ndarray]:
        """Columnar view (dict of numpy arrays; zero-copy from Arrow for
        primitive columns without nulls)."""
        if self.is_arrow:
            out = {}
            for name in self.block.column_names:
                col = self.block.column(name)
                try:
                    out[name] = col.to_numpy(zero_copy_only=False)
                except (pa.ArrowInvalid, ValueError):
                    out[name] = np.asarray(col.to_pylist(), dtype=object)
            return out
        if self.is_table:
            return dict(self.block)
        if not self.block:
            return {}
        first = self.block[0]
        if isinstance(first, dict):
            return {
                k: np.asarray([r[k] for r in self.block]) for k in first
            }
        return {"value": np.asarray(self.block)}

    def to_arrow(self) -> "pa.Table":
        if pa is None:
            raise RuntimeError("pyarrow is not available")
        if self.is_arrow:
            return self.block
        batch = self.to_batch()
        return pa.table({k: pa.array(v) for k, v in batch.items()})

    def slice(self, start: int, end: int) -> Block:
        if self.is_arrow:
            return self.block.slice(start, end - start)  # zero-copy view
        if self.is_table:
            return {k: v[start:end] for k, v in self.block.items()}
        return self.block[start:end]

    def schema(self) -> Optional[Dict[str, str]]:
        if self.num_rows() == 0:
            return None
        if self.is_arrow:
            return {f.name: str(f.type) for f in self.block.schema}
        batch = self.to_batch()
        return {k: str(v.dtype) for k, v in batch.items()}

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0]
        if not blocks:
            return []
        if _is_arrow(blocks[0]):
            if all(_is_arrow(b) for b in blocks):
                return pa.concat_tables(blocks)
            blocks = [BlockAccessor(b).to_arrow() for b in blocks]
            return pa.concat_tables(blocks)
        if isinstance(blocks[0], dict):
            keys = list(blocks[0])
            batches = [BlockAccessor(b).to_batch() for b in blocks]
            return {k: np.concatenate([b[k] for b in batches]) for k in keys}
        out: List[Any] = []
        for b in blocks:
            out.extend(BlockAccessor(b).to_rows())
        return out

    @staticmethod
    def from_batch(batch: Union[Dict[str, np.ndarray], np.ndarray, List]) -> Block:
        if pa is not None and isinstance(batch, pa.Table):
            return batch
        if isinstance(batch, dict):
            return {k: np.asarray(v) for k, v in batch.items()}
        if isinstance(batch, np.ndarray):
            return {"value": batch}
        return list(batch)
