"""Blocks: the unit of distributed data.

Analog of ``python/ray/data/block.py``: a block is an object-store value
holding a batch of rows — here either a list of rows or a dict-of-numpy
column table.  ``BlockAccessor`` normalizes the two layouts.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np

Block = Union[List[Any], Dict[str, np.ndarray]]


class BlockAccessor:
    def __init__(self, block: Block):
        self.block = block
        self.is_table = isinstance(block, dict)

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        if self.is_table:
            return len(next(iter(self.block.values()))) if self.block else 0
        return len(self.block)

    def iter_rows(self) -> Iterator[Any]:
        if self.is_table:
            keys = list(self.block)
            if keys == ["value"]:  # simple block: rows are the plain values
                yield from self.block["value"]
                return
            for i in range(self.num_rows()):
                yield {k: self.block[k][i] for k in keys}
        else:
            yield from self.block

    def to_rows(self) -> List[Any]:
        return list(self.iter_rows())

    def to_batch(self) -> Dict[str, np.ndarray]:
        """Columnar view (dict of numpy arrays)."""
        if self.is_table:
            return dict(self.block)
        if not self.block:
            return {}
        first = self.block[0]
        if isinstance(first, dict):
            return {
                k: np.asarray([r[k] for r in self.block]) for k in first
            }
        return {"value": np.asarray(self.block)}

    def slice(self, start: int, end: int) -> Block:
        if self.is_table:
            return {k: v[start:end] for k, v in self.block.items()}
        return self.block[start:end]

    def schema(self) -> Optional[Dict[str, str]]:
        if self.num_rows() == 0:
            return None
        batch = self.to_batch()
        return {k: str(v.dtype) for k, v in batch.items()}

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0]
        if not blocks:
            return []
        if isinstance(blocks[0], dict):
            keys = list(blocks[0])
            return {k: np.concatenate([b[k] for b in blocks]) for k in keys}
        out: List[Any] = []
        for b in blocks:
            out.extend(b)
        return out

    @staticmethod
    def from_batch(batch: Union[Dict[str, np.ndarray], np.ndarray, List]) -> Block:
        if isinstance(batch, dict):
            return {k: np.asarray(v) for k, v in batch.items()}
        if isinstance(batch, np.ndarray):
            return {"value": batch}
        return list(batch)
