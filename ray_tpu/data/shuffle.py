"""Distributed shuffle ops: map-side partition + reduce.

The reference's push-based shuffle
(``python/ray/data/_internal/push_based_shuffle.py``): every input block
is partitioned into N sub-blocks by a map task (``num_returns=N``), and N
reduce tasks each concatenate their partition from every map output.  The
driver only ever touches refs — no row materialization — so a shuffle of
1 GiB moves 1 GiB through the object store, not through the driver.

``sort`` uses sample-based range partitioning (the reference's
``sort.py`` sample stage): sample keys -> pick N-1 boundaries -> range
partition -> per-partition local sort.
"""

from __future__ import annotations

import random
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.plan import AllToAllStage


def _columnar(block: Block) -> Block:
    """Arrow blocks take the columnar fast paths as dict tables (a copy,
    but row-wise Python bucketing would be far worse); other layouts pass
    through untouched."""
    acc = BlockAccessor(block)
    return acc.to_batch() if acc.is_arrow else block


def _partition_random(block: Block, n: int, seed: Optional[int]):
    """Assign each row to a random partition (map side of the shuffle)."""
    block = _columnar(block)
    acc = BlockAccessor(block)
    rows = acc.num_rows()
    rng = np.random.default_rng(seed)
    assign = rng.integers(0, n, rows)
    parts = []
    if acc.is_table:
        idx = np.arange(rows)
        for j in range(n):
            sel = idx[assign == j]
            parts.append({k: np.asarray(v)[sel] for k, v in block.items()})
    else:
        buckets: List[List[Any]] = [[] for _ in range(n)]
        for r, j in zip(acc.iter_rows(), assign):
            buckets[j].append(r)
        parts = buckets
    return tuple(parts) if n > 1 else parts[0]


def _reduce_concat(shuffle_seed: Optional[int], local_shuffle: bool, *parts: Block) -> Block:
    merged = BlockAccessor.concat(list(parts))
    if not local_shuffle:
        return merged
    acc = BlockAccessor(merged)
    rows = acc.num_rows()
    rng = np.random.default_rng(shuffle_seed)
    order = rng.permutation(rows)
    if acc.is_table:
        return {k: np.asarray(v)[order] for k, v in merged.items()}
    return [merged[i] for i in order]


def random_shuffle_stage(seed: Optional[int], num_blocks: Optional[int] = None) -> AllToAllStage:
    def run(refs: List[Any], counts):
        n = num_blocks or max(1, len(refs))
        mapper = ray_tpu.remote(num_cpus=1, num_returns=n)(_partition_random)
        reducer = ray_tpu.remote(num_cpus=1)(_reduce_concat)
        parts = []
        for i, r in enumerate(refs):
            out = mapper.remote(r, n, None if seed is None else seed + i)
            parts.append([out] if n == 1 else list(out))
        new_refs = [
            reducer.remote(None if seed is None else seed * 31 + j, True,
                           *[p[j] for p in parts])
            for j in range(n)
        ]
        return new_refs, None

    return AllToAllStage("random_shuffle", run)


def _slice_ranges(block: Block, bounds: List[int]):
    """Split a block at row indices (map side of repartition)."""
    acc = BlockAccessor(block)
    parts = [acc.slice(lo, hi) for lo, hi in zip([0] + bounds, bounds + [acc.num_rows()])]
    return tuple(parts) if len(parts) > 1 else parts[0]


def _count_rows(block: Block) -> int:
    return BlockAccessor(block).num_rows()


def compute_counts(refs: List[Any], counts: Optional[List[int]]) -> List[int]:
    """Per-block row counts, via tasks when not already known."""
    if counts is not None:
        return counts
    task = ray_tpu.remote(num_cpus=1)(_count_rows)
    return ray_tpu.get([task.remote(r) for r in refs])


def range_partition(refs: List[Any], counts: List[int],
                    g_bounds: List[int]) -> List[List[Any]]:
    """Slice blocks at global row boundaries.  Returns, for each of the
    ``len(g_bounds)+1`` output ranges, the list of sub-block refs from
    every input block (the map side shared by repartition/split/zip)."""
    n_parts = len(g_bounds) + 1
    mapper = ray_tpu.remote(num_cpus=1, num_returns=n_parts)(_slice_ranges)
    per_block, offset = [], 0
    for r, c in zip(refs, counts):
        local = [int(min(max(b - offset, 0), c)) for b in g_bounds]
        out = mapper.remote(r, local)
        per_block.append(list(out) if n_parts > 1 else [out])
        offset += c
    return [[p[j] for p in per_block] for j in range(n_parts)]


def repartition_stage(num_blocks: int) -> AllToAllStage:
    """Even re-split without a full shuffle: each input block is sliced
    into ``num_blocks`` ranges proportionally; reducer j concatenates the
    j-th slice of every block."""

    def run(refs: List[Any], counts):
        n = num_blocks
        counts = compute_counts(refs, counts)
        total = sum(counts)
        per = [total // n + (1 if j < total % n else 0) for j in range(n)]
        g_bounds = list(np.cumsum(per)[:-1])
        parts = range_partition(refs, counts, g_bounds)
        reducer = ray_tpu.remote(num_cpus=1)(_reduce_concat)
        new_refs = [reducer.remote(None, False, *parts[j]) for j in range(n)]
        return new_refs, per

    return AllToAllStage("repartition", run)


def _key_fn(key):
    if isinstance(key, str):
        return lambda r: r[key]
    if key is None:
        return lambda r: r
    return key


def _sample_keys(block: Block, key, k: int):
    acc = BlockAccessor(block)
    rows = acc.to_rows()
    if not rows:
        return []
    kf = _key_fn(key)
    sample = random.Random(0).sample(rows, min(k, len(rows)))
    return [kf(r) for r in sample]


def _partition_by_range(block: Block, key, boundaries: List[Any]):
    acc = BlockAccessor(block)
    kf = _key_fn(key)
    n = len(boundaries) + 1
    buckets: List[List[Any]] = [[] for _ in range(n)]
    import bisect

    for r in acc.iter_rows():
        buckets[bisect.bisect_right(boundaries, kf(r))].append(r)
    return tuple(buckets) if n > 1 else buckets[0]


def _sort_block(key, descending: bool, *parts: Block) -> Block:
    merged = BlockAccessor.concat(list(parts))
    rows = BlockAccessor(merged).to_rows()
    rows.sort(key=_key_fn(key), reverse=descending)
    if rows and isinstance(rows[0], dict):
        return BlockAccessor.from_batch(
            {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
        )
    return rows


def sort_stage(key, descending: bool) -> AllToAllStage:
    """Sample-based range partition + per-partition sort (sort.py analog).
    Only a bounded key sample ever reaches the driver."""

    def run(refs: List[Any], counts):
        n = max(1, len(refs))
        sampler = ray_tpu.remote(num_cpus=1)(_sample_keys)
        samples: List[Any] = []
        for s in ray_tpu.get([sampler.remote(r, key, 32) for r in refs]):
            samples.extend(s)
        samples.sort()
        if samples and n > 1:
            step = max(1, len(samples) // n)
            boundaries = samples[step::step][: n - 1]
        else:
            boundaries = []
        n_out = len(boundaries) + 1
        mapper = ray_tpu.remote(num_cpus=1, num_returns=n_out)(_partition_by_range)
        reducer = ray_tpu.remote(num_cpus=1)(_sort_block)
        parts = []
        for r in refs:
            out = mapper.remote(r, key, boundaries)
            parts.append([out] if n_out == 1 else list(out))
        new_refs = [reducer.remote(key, descending, *[p[j] for p in parts])
                    for j in range(n_out)]
        if descending:
            new_refs.reverse()
        return new_refs, None

    return AllToAllStage("sort", run)
