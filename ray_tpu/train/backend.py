"""Training backends: per-framework gang setup.

``Backend``/``BackendConfig`` mirror ``python/ray/train/backend.py:55,43``.
:class:`JaxConfig` is the TPU replacement for the torch process-group
rendezvous (``torch/config.py:69`` ``dist.init_process_group``):

- every rank joins a host-side collective group (gradient sync for
  plain data parallelism — the gloo-analog path that works anywhere), and
- with ``use_jax_distributed=True`` (real multi-host pods) rank 0
  publishes a coordinator address through the GCS KV and every worker
  calls ``jax.distributed.initialize`` so all hosts enter one SPMD
  program over ICI/DCN.
"""

from __future__ import annotations

import dataclasses
import uuid
from typing import Optional

import ray_tpu
from ray_tpu.train.worker_group import WorkerGroup


@dataclasses.dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


class Backend:
    def on_start(self, worker_group: WorkerGroup, backend_config: "BackendConfig"):
        pass

    def on_training_start(self, worker_group: WorkerGroup, backend_config: "BackendConfig"):
        pass

    def on_shutdown(self, worker_group: WorkerGroup, backend_config: "BackendConfig"):
        pass


@dataclasses.dataclass
class JaxConfig(BackendConfig):
    use_jax_distributed: bool = False
    coordinator_port: int = 0  # 0 = pick a free port
    group_name: Optional[str] = None  # collective group; default unique per run
    # extra env applied on every worker BEFORE jax initializes there
    # (XLA_FLAGS / JAX_PLATFORMS / TPU topology variables); the seat of
    # the reference torch config's backend env knobs
    env_vars: Optional[dict] = None

    @property
    def backend_cls(self):
        return _JaxBackend


class _JaxBackend(Backend):
    def on_start(self, worker_group: WorkerGroup, cfg: JaxConfig):
        n = worker_group.num_workers
        group = cfg.group_name or f"train-{uuid.uuid4().hex[:8]}"
        cfg.group_name = group
        # rank 0 first: it creates the coordinator the others poll for
        ray_tpu.get(
            worker_group.workers[0].join_collective_group.remote(n, 0, group),
            timeout=60,
        )
        ray_tpu.get(
            [
                w.join_collective_group.remote(n, i, group)
                for i, w in enumerate(worker_group.workers)
                if i > 0
            ],
            timeout=60,
        )
        env = {
            "RAY_TRAIN_WORLD_SIZE": str(n),
            "RAY_TRAIN_COLLECTIVE_GROUP": group,
        }
        if cfg.env_vars:
            env.update({k: str(v) for k, v in cfg.env_vars.items()})
        ray_tpu.get(
            [w.setup_env.remote({**env, "RAY_TRAIN_WORLD_RANK": str(i)})
             for i, w in enumerate(worker_group.workers)],
            timeout=60,
        )
        if cfg.use_jax_distributed:
            self._init_jax_distributed(worker_group, cfg)

    def _init_jax_distributed(self, worker_group: WorkerGroup, cfg: JaxConfig):
        """Multi-host SPMD bring-up (the `_setup_torch_process_group` seat)."""
        port = cfg.coordinator_port

        def get_coordinator(port):
            import socket

            host = socket.gethostbyname(socket.gethostname())
            if port == 0:
                s = socket.socket()
                s.bind(("", 0))
                port = s.getsockname()[1]
                s.close()
            return f"{host}:{port}"

        coordinator = worker_group.execute_single(0, get_coordinator, port)

        def init_dist(coordinator, num_processes, process_id):
            import jax

            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
            )
            return True

        import cloudpickle

        blob = cloudpickle.dumps(init_dist)
        ray_tpu.get(
            [w.execute.remote(blob, coordinator, worker_group.num_workers, i)
             for i, w in enumerate(worker_group.workers)],
            timeout=300,
        )

    def on_shutdown(self, worker_group: WorkerGroup, cfg: JaxConfig):
        pass
