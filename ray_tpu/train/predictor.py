"""Predictors: checkpoint -> inference, single-batch and over Datasets.

Analog of the reference's ``python/ray/train/predictor.py`` (Predictor) and
``python/ray/train/batch_predictor.py`` (BatchPredictor): a Predictor turns
an AIR :class:`~ray_tpu.air.Checkpoint` into a callable model; a
BatchPredictor scores a whole :class:`~ray_tpu.data.Dataset` by fanning the
predictor out over an actor pool (``num_tpus=1`` actors put one jitted model
on each chip — the TPU batch-inference path of BASELINE's XGBoost
batch-prediction rows).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Union

import numpy as np

from ray_tpu.air import Checkpoint


class Predictor:
    """Base predictor (``train/predictor.py`` analog).

    Subclasses implement :meth:`from_checkpoint` and :meth:`predict` over a
    numpy batch (an ``np.ndarray`` or a ``{column: np.ndarray}`` dict).
    """

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Union[np.ndarray, Dict[str, np.ndarray]], **kwargs):
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Predictor over a jitted jax apply function.

    ``apply_fn(params, batch) -> predictions``; params come from the
    checkpoint (``params_key`` selects them out of a training-state dict).
    The function is jitted once and reused across batches, so the per-batch
    cost on TPU is one device transfer + one compiled call.
    """

    def __init__(self, params: Any, apply_fn: Callable, *, jit: bool = True):
        import jax

        self._params = params
        self._apply = jax.jit(apply_fn) if jit else apply_fn

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint: Checkpoint,
        apply_fn: Callable,
        *,
        params_key: str = "params",
        jit: bool = True,
        **_kwargs,
    ) -> "JaxPredictor":
        data = checkpoint.to_dict()
        params = data.get(params_key, data) if isinstance(data, dict) else data
        return cls(params, apply_fn, jit=jit)

    def predict(self, batch, **kwargs):
        out = self._apply(self._params, batch)
        import jax

        return jax.tree_util.tree_map(np.asarray, out)


class _ScoringWrapper:
    """The callable-class map_batches runs on each actor: builds the
    predictor once per actor (model lives on that actor's chip), then scores
    batches (``batch_predictor.py`` ScoringWrapper analog)."""

    def __init__(
        self,
        checkpoint_blob: bytes,
        predictor_cls: type,
        predictor_kwargs: dict,
        feature_columns,
        keep_columns,
    ):
        import cloudpickle

        checkpoint = cloudpickle.loads(checkpoint_blob)
        self._predictor = predictor_cls.from_checkpoint(checkpoint, **predictor_kwargs)
        self._feature_columns = feature_columns
        self._keep_columns = keep_columns

    def __call__(self, batch):
        feats = batch
        if self._feature_columns is not None and isinstance(batch, dict):
            if len(self._feature_columns) == 1:
                feats = batch[self._feature_columns[0]]
            else:
                feats = {c: batch[c] for c in self._feature_columns}
        preds = self._predictor.predict(feats)
        if not isinstance(preds, dict):
            preds = {"predictions": np.asarray(preds)}
        if self._keep_columns and isinstance(batch, dict):
            for c in self._keep_columns:
                preds[c] = batch[c]
        return preds


class BatchPredictor:
    """Score a Dataset with an actor pool of predictors
    (``train/batch_predictor.py`` analog)."""

    def __init__(self, checkpoint: Checkpoint, predictor_cls: type, **predictor_kwargs):
        if not (isinstance(predictor_cls, type) and issubclass(predictor_cls, Predictor)):
            raise TypeError(f"predictor_cls must be a Predictor subclass, got {predictor_cls!r}")
        self._checkpoint = checkpoint
        self._predictor_cls = predictor_cls
        self._predictor_kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(
        cls, checkpoint: Checkpoint, predictor_cls: type, **predictor_kwargs
    ) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **predictor_kwargs)

    def predict(
        self,
        data,
        *,
        batch_size: Optional[int] = None,
        min_scoring_workers: int = 1,
        max_scoring_workers: int = 2,
        num_tpus_per_worker: float = 0,
        num_cpus_per_worker: float = 1,
        feature_columns=None,
        keep_columns=None,
    ):
        """Returns a Dataset of predictions (lazy, like the input)."""
        import cloudpickle

        from ray_tpu.data.dataset import ActorPoolStrategy

        if min_scoring_workers > max_scoring_workers:
            raise ValueError(
                f"min_scoring_workers={min_scoring_workers} exceeds "
                f"max_scoring_workers={max_scoring_workers}"
            )
        ckpt_blob = cloudpickle.dumps(self._checkpoint)
        return data.map_batches(
            _ScoringWrapper,
            batch_size=batch_size,
            compute=ActorPoolStrategy(size=max_scoring_workers),
            fn_constructor_args=(
                ckpt_blob,
                self._predictor_cls,
                self._predictor_kwargs,
                feature_columns,
                keep_columns,
            ),
            num_tpus=num_tpus_per_worker,
        )
