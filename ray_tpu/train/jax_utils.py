"""JAX training helpers for the data-parallel gang.

The ``prepare_model``-shaped conveniences of the torch backend
(``python/ray/train/torch/train_loop_utils.py:51,106``), re-thought for
jax: gradient sync is one fused host all-reduce of the raveled pytree
(one collective round per step, not one per leaf), and batch sharding is a
pure function of rank.

On a real multi-host pod with ``use_jax_distributed=True`` none of this is
needed — the mesh spans hosts and ``psum`` inside pjit rides ICI; these
helpers are the portable path (CPU dev boxes, single-host multi-process).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ray_tpu.air import session


def allreduce_grads(grads: Any, group_name: Optional[str] = None) -> Any:
    """Mean-all-reduce a grad pytree across the training gang (one round).

    When the hosting process has an active :class:`~ray_tpu.util.perf
    .StepProfiler` with a step open, the collective round bills to the
    ``collective`` phase of that step — the gang's sync share shows up
    in the step-phase breakdown without the train fn instrumenting
    anything itself."""
    import jax
    from jax.flatten_util import ravel_pytree

    from ray_tpu.util import collective
    from ray_tpu.util import perf as _perf

    import contextlib
    import os

    group = group_name or os.environ.get("RAY_TRAIN_COLLECTIVE_GROUP", "default")
    flat, unravel = ravel_pytree(grads)
    prof = _perf.active_profiler()
    scope = prof.phase("collective") if prof is not None \
        else contextlib.nullcontext()
    with scope:
        summed = collective.allreduce(
            np.asarray(flat), group_name=group, op="mean")
    return unravel(jax.numpy.asarray(summed))


def step_profiler(*, cfg: Any = None, n_params: Optional[int] = None,
                  tokens_per_step: Optional[int] = None,
                  rank: Optional[int] = None, **kwargs):
    """Build + install a :class:`~ray_tpu.util.perf.StepProfiler` for
    this train worker, with the FLOPs model derived from a model config
    (``util/flops.py`` — the same arithmetic bench.py uses, so live MFU
    and bench MFU agree by construction)::

        prof = jax_utils.step_profiler(cfg=cfg, n_params=n_params,
                                       tokens_per_step=B * T)
        train_step = prof.wrap_jit(train_step)
        for ...:
            with prof.step():
                ...
    """
    from ray_tpu.util import flops as flops_mod
    from ray_tpu.util import perf as _perf

    fpt = kwargs.pop("flops_per_token", None)
    if fpt is None and cfg is not None and n_params is not None:
        fpt = flops_mod.model_flops_per_token(cfg, n_params)
    if rank is None:
        rank = session.get_world_rank()
    return _perf.StepProfiler(
        flops_per_token=fpt, tokens_per_step=tokens_per_step,
        rank=rank, **kwargs).install()


def shard_batch(batch: Any, *, rank: Optional[int] = None, world_size: Optional[int] = None) -> Any:
    """This rank's slice of a global batch (leading axis split)."""
    import jax

    rank = rank if rank is not None else session.get_world_rank()
    world_size = world_size if world_size is not None else session.get_world_size()

    def _slice(x):
        n = x.shape[0]
        per = n // world_size
        return x[rank * per:(rank + 1) * per]

    return jax.tree.map(_slice, batch)


def global_mesh(axis_name: str = "dp"):
    """1-D mesh over all addressable devices (after jax.distributed this is
    the multi-host mesh)."""
    import jax
    import numpy as np_
    from jax.sharding import Mesh

    return Mesh(np_.asarray(jax.devices()), (axis_name,))
