"""JAX training helpers for the data-parallel gang.

The ``prepare_model``-shaped conveniences of the torch backend
(``python/ray/train/torch/train_loop_utils.py:51,106``), re-thought for
jax: gradient sync is one fused host all-reduce of the raveled pytree
(one collective round per step, not one per leaf), and batch sharding is a
pure function of rank.

On a real multi-host pod with ``use_jax_distributed=True`` none of this is
needed — the mesh spans hosts and ``psum`` inside pjit rides ICI; these
helpers are the portable path (CPU dev boxes, single-host multi-process).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ray_tpu.air import session


def allreduce_grads(grads: Any, group_name: Optional[str] = None) -> Any:
    """Mean-all-reduce a grad pytree across the training gang (one round)."""
    import jax
    from jax.flatten_util import ravel_pytree

    from ray_tpu.util import collective

    import os

    group = group_name or os.environ.get("RAY_TRAIN_COLLECTIVE_GROUP", "default")
    flat, unravel = ravel_pytree(grads)
    summed = collective.allreduce(np.asarray(flat), group_name=group, op="mean")
    return unravel(jax.numpy.asarray(summed))


def shard_batch(batch: Any, *, rank: Optional[int] = None, world_size: Optional[int] = None) -> Any:
    """This rank's slice of a global batch (leading axis split)."""
    import jax

    rank = rank if rank is not None else session.get_world_rank()
    world_size = world_size if world_size is not None else session.get_world_size()

    def _slice(x):
        n = x.shape[0]
        per = n // world_size
        return x[rank * per:(rank + 1) * per]

    return jax.tree.map(_slice, batch)


def global_mesh(axis_name: str = "dp"):
    """1-D mesh over all addressable devices (after jax.distributed this is
    the multi-host mesh)."""
    import jax
    import numpy as np_
    from jax.sharding import Mesh

    return Mesh(np_.asarray(jax.devices()), (axis_name,))
