"""Trainers: BaseTrainer / DataParallelTrainer / JaxTrainer.

``BaseTrainer.fit`` (reference ``train/base_trainer.py:339``) returns a
``Result``; ``DataParallelTrainer`` (``data_parallel_trainer.py:56``)
drives a BackendExecutor gang through ``train_loop_per_worker``, collecting
``session.report`` streams and keeping ranked checkpoints.  Fault
tolerance: on worker failure the whole gang restarts from the latest
checkpoint up to ``FailureConfig.max_failures`` times (gang = failure
domain, the TPU-slice semantics).
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu

from ray_tpu.air import (
    Checkpoint,
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air import session as air_session
from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.backend_executor import BackendExecutor, TrainingFailedError


class BaseTrainer:
    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
        dataset_config: Optional["DataConfig"] = None,
    ):
        from ray_tpu.train.data_config import DataConfig

        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        self.datasets = datasets or {}
        self.dataset_config = dataset_config or DataConfig()

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Wrap into a Tune trainable (base_trainer.py:500 analog)."""
        from ray_tpu.tune.trainable import wrap_trainer

        return wrap_trainer(self)


class DataParallelTrainer(BaseTrainer):
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
        dataset_config=None,
    ):
        super().__init__(
            scaling_config=scaling_config, run_config=run_config,
            resume_from_checkpoint=resume_from_checkpoint, datasets=datasets,
            dataset_config=dataset_config,
        )
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.backend_config = backend_config or BackendConfig()

    def _storage_dir(self) -> str:
        base = self.run_config.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_results"
        )
        name = self.run_config.name or f"train_{int(time.time())}"
        path = os.path.join(base, name)
        os.makedirs(path, exist_ok=True)
        return path

    def fit(self) -> Result:
        from ray_tpu._private.usage import record_feature

        record_feature("train")
        failure_cfg = self.run_config.failure_config or FailureConfig()
        ckpt_cfg = self.run_config.checkpoint_config or CheckpointConfig()
        storage = self._storage_dir()
        latest_ckpt = self.resume_from_checkpoint
        failures = 0

        while True:
            executor = BackendExecutor(self.backend_config, self.scaling_config,
                                       prior_gang_starts=failures)
            try:
                executor.start()
                executor.start_training(
                    self.train_loop_per_worker,
                    config=self.train_loop_config,
                    checkpoint=latest_ckpt,
                    datasets=self.datasets or None,
                    data_config=self.dataset_config,
                    trial_info={"name": self.run_config.name or "train", "id": "0"},
                )
                manager = _CheckpointBook(storage, ckpt_cfg)
                last_metrics: Optional[Dict] = None
                while True:
                    if air_session.is_stop_requested():
                        break  # superseded (e.g. PBT reset) — abort the gang
                    results = executor.get_next_results()
                    if results is None:
                        break
                    for kind, metrics, ckpt in results:
                        if kind != "report":
                            continue
                        # rank-0's stream defines the run's metrics
                        last_metrics = metrics
                        if ckpt is not None:
                            manager.add(ckpt, metrics)
                            latest_ckpt = ckpt
                return Result(
                    metrics=last_metrics,
                    checkpoint=manager.best() or latest_ckpt,
                    path=storage,
                    best_checkpoints=manager.ranked(),
                )
            except (TrainingFailedError, ray_tpu.exceptions.RayActorError,
                    ray_tpu.exceptions.WorkerCrashedError) as e:
                # worker DEATH during backend setup / rendezvous (before any
                # result flows) is the same gang failure as an in-loop one;
                # permanent failures (scheduling timeouts etc.) still raise
                failures += 1
                if failures > failure_cfg.max_failures:
                    return Result(metrics=None, checkpoint=latest_ckpt,
                                  error=e, path=storage)
                # whole-gang restart from the last checkpoint
            finally:
                executor.shutdown()


class JaxTrainer(DataParallelTrainer):
    """DataParallelTrainer defaulting to the JAX backend (the reference's
    TorchTrainer seat, BASELINE configs 2-3)."""

    def __init__(self, train_loop_per_worker, *, jax_config: Optional[JaxConfig] = None,
                 **kwargs):
        kwargs.setdefault("backend_config", jax_config or JaxConfig())
        super().__init__(train_loop_per_worker, **kwargs)


class _CheckpointBook:
    """Rank + persist reported checkpoints (air CheckpointManager analog)."""

    def __init__(self, storage: str, cfg: CheckpointConfig):
        self.storage = storage
        self.cfg = cfg
        self.entries: List[tuple] = []  # (score, idx, Checkpoint)
        self._idx = 0

    def add(self, ckpt: Checkpoint, metrics: Optional[Dict]) -> None:
        attr = self.cfg.checkpoint_score_attribute
        score = (metrics or {}).get(attr) if attr else self._idx
        if score is None:
            score = self._idx
        if self.cfg.checkpoint_score_order == "min":
            score = -score
        path = os.path.join(self.storage, f"checkpoint_{self._idx:06d}")
        ckpt.to_directory(path)
        self.entries.append((score, self._idx, Checkpoint.from_directory(path)))
        self._idx += 1
        keep = self.cfg.num_to_keep
        if keep is not None and len(self.entries) > keep:
            self.entries.sort(key=lambda e: (-e[0], -e[1]))
            for _, idx, stale in self.entries[keep:]:
                import shutil

                shutil.rmtree(
                    os.path.join(self.storage, f"checkpoint_{idx:06d}"),
                    ignore_errors=True,
                )
            self.entries = self.entries[:keep]

    def best(self) -> Optional[Checkpoint]:
        if not self.entries:
            return None
        return max(self.entries, key=lambda e: (e[0], e[1]))[2]

    def ranked(self) -> List[Checkpoint]:
        return [e[2] for e in sorted(self.entries, key=lambda e: (-e[0], -e[1]))]
