"""Ray-Train-style distributed training orchestration, JAX/TPU-native.

Actor ``WorkerGroup`` + pluggable ``Backend`` per SURVEY §3.5, with the
torch/NCCL rendezvous (``python/ray/train/torch/config.py:69``) replaced by
:class:`JaxConfig`: worker ranks join a collective group, and on real pods
``jax.distributed.initialize`` over ICI makes every worker a process of one
global SPMD program.
"""

from ray_tpu.air import Checkpoint, Result, RunConfig, ScalingConfig
from ray_tpu.train.backend import Backend, BackendConfig, JaxConfig
from ray_tpu.train.data_config import DataConfig
from ray_tpu.train.predictor import BatchPredictor, JaxPredictor, Predictor
from ray_tpu.train.sklearn_trainer import SklearnPredictor, SklearnTrainer
from ray_tpu.train.trainer import BaseTrainer, DataParallelTrainer, JaxTrainer
from ray_tpu.train.worker_group import WorkerGroup
from ray_tpu.train import jax_utils

__all__ = [
    "Predictor",
    "JaxPredictor",
    "BatchPredictor",
    "SklearnTrainer",
    "SklearnPredictor",
    "Backend",
    "BackendConfig",
    "DataConfig",
    "JaxConfig",
    "BaseTrainer",
    "DataParallelTrainer",
    "JaxTrainer",
    "WorkerGroup",
    "jax_utils",
    "Checkpoint",
    "Result",
    "RunConfig",
    "ScalingConfig",
]
