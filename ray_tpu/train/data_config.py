"""DataConfig: how Trainer datasets become per-worker shards.

Analog of ``python/ray/train/_internal/data_config.py`` (``DataConfig``):
the trainer hands its datasets plus this config to the BackendExecutor,
which — knowing where each rank's actor actually landed — wires every
worker a shard of each dataset:

- datasets in ``datasets_to_split`` go through ``Dataset.streaming_split``:
  one shared streaming pipeline, block-level shard assignment, soft
  node-affinity locality hints so each rank's blocks materialize on ITS
  node, and a bounded in-flight block budget (backpressure).
- other Datasets are passed whole to every worker (the reference's
  un-split datasets, e.g. a small validation set each rank scans fully).
- plain sequences fall back to even slicing.

A single-worker run hands the dataset over WITH its lazy plan so the
worker's ``iter_batches`` streams read+transform — splitting would execute
the plan eagerly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union


class DataConfig:
    def __init__(
        self,
        datasets_to_split: Union[str, List[str]] = "all",
        *,
        locality: bool = True,
        equal: bool = True,
        max_in_flight_blocks: Optional[int] = None,
    ):
        if datasets_to_split != "all" and not isinstance(datasets_to_split, list):
            raise TypeError(
                "datasets_to_split should be 'all' or a list of dataset "
                f"names, got {datasets_to_split!r}")
        self._to_split = datasets_to_split
        self.locality = locality
        self.equal = equal
        self.max_in_flight_blocks = max_in_flight_blocks

    def _should_split(self, name: str) -> bool:
        return self._to_split == "all" or name in self._to_split

    def configure(
        self,
        datasets: Dict[str, Any],
        world_size: int,
        worker_node_ids: Optional[List[str]] = None,
    ) -> List[Dict[str, Any]]:
        """Per-worker shard dicts for ``datasets`` (one dict per rank).

        ``worker_node_ids[i]`` is rank i's node — the streaming split's
        locality hint, so rank i's blocks are produced on rank i's node.
        """
        shards: List[Dict[str, Any]] = [dict() for _ in range(world_size)]
        hints: Optional[List[Optional[str]]] = None
        if self.locality and worker_node_ids is not None \
                and len(worker_node_ids) == world_size:
            hints = list(worker_node_ids)
        for name, ds in datasets.items():
            if world_size == 1 and hasattr(ds, "iter_batches"):
                # single worker: hand over the dataset WITH its lazy plan —
                # splitting would execute it eagerly and the worker's
                # iter_batches could no longer stream read+transform
                parts = [ds]
            elif hasattr(ds, "streaming_split") and self._should_split(name):
                parts = ds.streaming_split(
                    world_size, equal=self.equal, locality_hints=hints,
                    max_in_flight_blocks=self.max_in_flight_blocks)
            elif hasattr(ds, "iter_batches"):
                # un-split dataset: every rank sees the whole thing
                parts = [ds] * world_size
            elif hasattr(ds, "split"):
                parts = ds.split(world_size)
            else:  # plain sequence: even slices
                per = len(ds) // world_size
                parts = [ds[i * per:(i + 1) * per] for i in range(world_size)]
            for i in range(world_size):
                shards[i][name] = parts[i]
        return shards
