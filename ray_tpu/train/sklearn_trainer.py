"""SklearnTrainer + SklearnPredictor: CPU estimator training under the
Train/Tune umbrella.

Analog of the reference's ``python/ray/train/sklearn/sklearn_trainer.py``
and the GBDT trainer family (``train/gbdt_trainer.py``, xgboost/lightgbm —
not in this image; sklearn's HistGradientBoosting* covers the gradient-
boosted-trees role).  The fit runs inside a Tune trial actor like every
other trainer, consumes ``ray_tpu.data`` Datasets, reports validation
metrics through ``session.report``, and checkpoints the fitted estimator
as the standard AIR Checkpoint currency (so :class:`BatchPredictor` scores
Datasets with it).
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ray_tpu.air import Checkpoint
from ray_tpu.train.predictor import Predictor

_ESTIMATOR_KEY = "estimator_pkl"
_COLUMNS_KEY = "feature_columns"


def _to_xy(ds, label_column: str, feature_columns: Optional[List[str]]):
    """Returns (X, y, columns-in-training-order) — the column order is
    persisted in the checkpoint so prediction can never permute features."""
    rows = ds.take_all()
    if not rows:
        raise ValueError("empty dataset")
    if isinstance(rows[0], dict):
        cols = list(feature_columns or [c for c in rows[0] if c != label_column])
        X = np.asarray([[r[c] for c in cols] for r in rows], np.float64)
        y = np.asarray([r[label_column] for r in rows])
        return X, y, cols
    raise ValueError("SklearnTrainer needs datasets of dict rows "
                     "(use from_items / read_csv)")


class SklearnTrainer:
    """Fit an sklearn estimator on Datasets as a Train trainer.

    Example::

        trainer = SklearnTrainer(
            estimator=HistGradientBoostingClassifier(),
            datasets={"train": train_ds, "valid": valid_ds},
            label_column="y",
        )
        result = trainer.fit()
        est = SklearnTrainer.get_model(result.checkpoint)
    """

    def __init__(
        self,
        *,
        estimator: Any,
        datasets: Dict[str, Any],
        label_column: str,
        feature_columns: Optional[List[str]] = None,
        scaling_config: Any = None,
        run_config: Any = None,
    ):
        if "train" not in datasets:
            raise ValueError("datasets must include a 'train' split")
        self.estimator = estimator
        self.datasets = datasets
        self.label_column = label_column
        self.feature_columns = feature_columns
        self.scaling_config = scaling_config
        self.run_config = run_config

    # -- Trainable seam -------------------------------------------------
    def _train_loop(self, config: Optional[dict] = None) -> None:
        from ray_tpu.air import session

        est = self.estimator
        X, y, cols = _to_xy(self.datasets["train"], self.label_column,
                            self.feature_columns)
        est.fit(X, y)
        metrics: Dict[str, Any] = {"fit_rows": int(len(y))}
        for split, ds in self.datasets.items():
            if split == "train":
                continue
            Xv, yv, _ = _to_xy(ds, self.label_column, cols)
            metrics[f"{split}_score"] = float(est.score(Xv, yv))
        session.report(
            metrics,
            checkpoint=Checkpoint.from_dict({
                _ESTIMATOR_KEY: pickle.dumps(est),
                _COLUMNS_KEY: cols,
            }),
        )

    def fit(self):
        """Run under Tune like every trainer (one trial, one actor)."""
        from ray_tpu.air import RunConfig
        from ray_tpu.tune import TuneConfig, Tuner

        tuner = Tuner(
            self._train_loop,
            tune_config=TuneConfig(num_samples=1, max_concurrent_trials=1),
            run_config=self.run_config or RunConfig(),
        )
        grid = tuner.fit()
        result = grid[0]
        if result.error is not None:
            raise result.error
        return result

    @staticmethod
    def get_model(checkpoint: Checkpoint):
        """Fitted estimator out of a trainer checkpoint."""
        return pickle.loads(checkpoint.to_dict()[_ESTIMATOR_KEY])


class SklearnPredictor(Predictor):
    """Score batches with a fitted estimator (``train/sklearn/
    sklearn_predictor.py`` analog); plugs into BatchPredictor.  Dict
    batches are ordered by the TRAINING column order saved in the
    checkpoint — never by dict/sort order, which would silently permute
    features."""

    def __init__(self, estimator: Any, feature_columns: Optional[List[str]] = None):
        self._est = estimator
        self._cols = feature_columns

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **_kw) -> "SklearnPredictor":
        data = checkpoint.to_dict()
        return cls(pickle.loads(data[_ESTIMATOR_KEY]), data.get(_COLUMNS_KEY))

    def predict(self, batch: Union[np.ndarray, Dict[str, np.ndarray]], **_kw):
        if isinstance(batch, dict):
            if self._cols is None:
                raise ValueError(
                    "dict batch but the checkpoint carries no feature-column "
                    "order; pass feature_columns or score plain arrays"
                )
            missing = [c for c in self._cols if c not in batch]
            if missing:
                raise ValueError(f"batch lacks trained feature columns {missing}")
            X = np.stack(
                [np.asarray(batch[c], np.float64) for c in self._cols], axis=1
            )
        else:
            X = np.asarray(batch, np.float64)
        return np.asarray(self._est.predict(X))
