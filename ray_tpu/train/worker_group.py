"""WorkerGroup: the gang of training actors.

Analog of ``python/ray/train/_internal/worker_group.py:92``: N actors
created inside a placement group (gang semantics — a TPU slice's hosts
lease together and die together, SURVEY §7 hard-part 3), with broadcast
execution and per-worker result queues.
"""

from __future__ import annotations

import queue
import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.air import session as air_session
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


class TrainWorker:
    """Actor hosting one rank of the training gang.

    The user's train fn runs on a dedicated thread so the actor stays
    responsive to ``next_result`` polls (the reference gets this from its
    async result queue in ``_TrainSession``).
    """

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.queue: "queue.Queue" = queue.Queue()
        self.thread: Optional[threading.Thread] = None
        self.env: Dict[str, str] = {}

    def setup_env(self, env: Dict[str, str]) -> bool:
        import os

        self.env = env
        os.environ.update(env)
        return True

    def join_collective_group(self, world_size: int, rank: int, group_name: str) -> bool:
        from ray_tpu.util import collective

        collective.init_collective_group(world_size, rank, group_name=group_name)
        return True

    def execute(self, fn_blob: bytes, *args, **kwargs):
        """Run a pickled callable synchronously and return its result."""
        fn = cloudpickle.loads(fn_blob)
        return fn(*args, **kwargs)

    def run_train_fn(
        self, fn_blob: bytes, config: Optional[dict],
        session_kwargs: Dict[str, Any],
    ) -> bool:
        fn = cloudpickle.loads(fn_blob)
        ckpt = session_kwargs.pop("checkpoint", None)

        def report_fn(metrics, checkpoint):
            self.queue.put(("report", metrics, checkpoint))

        sess = air_session._Session(
            world_size=self.world_size, world_rank=self.rank,
            local_rank=self.rank, checkpoint=ckpt,
            report_fn=report_fn, **session_kwargs,
        )

        def runner():
            air_session._set_session(sess)
            try:
                if config is not None:
                    fn(config)
                else:
                    fn()
                self.queue.put(("finished", None, None))
            except BaseException:  # noqa: BLE001
                self.queue.put(("error", traceback.format_exc(), None))
            finally:
                air_session._set_session(None)

        self.thread = threading.Thread(target=runner, daemon=True)
        self.thread.start()
        return True

    def next_result(self, timeout: float = 30.0):
        """One queued event, or ("pending", None, None) on timeout."""
        try:
            return self.queue.get(timeout=timeout)
        except queue.Empty:
            return ("pending", None, None)


class WorkerGroup:
    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Dict[str, float],
        placement_strategy: str = "PACK",
    ):
        self.num_workers = num_workers
        bundles = [dict(resources_per_worker) for _ in range(num_workers)]
        self.pg = placement_group(bundles, strategy=placement_strategy)
        ray_tpu.get(self.pg.ready(), timeout=60)
        Worker = ray_tpu.remote(TrainWorker)
        opts: Dict[str, Any] = {}
        if "CPU" in resources_per_worker:
            opts["num_cpus"] = resources_per_worker["CPU"]
        if "TPU" in resources_per_worker:
            opts["num_tpus"] = resources_per_worker["TPU"]
        self.workers = [
            Worker.options(
                **opts,
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self.pg, placement_group_bundle_index=i
                ),
            ).remote(i, num_workers)
            for i in range(num_workers)
        ]

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Run ``fn`` on every worker, gathered (worker_group.py:92 analog)."""
        blob = cloudpickle.dumps(fn)
        return ray_tpu.get(
            [w.execute.remote(blob, *args, **kwargs) for w in self.workers],
            timeout=300,
        )

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        blob = cloudpickle.dumps(fn)
        return ray_tpu.get(
            self.workers[rank].execute.remote(blob, *args, **kwargs), timeout=300
        )

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        try:
            remove_placement_group(self.pg)
        except Exception:
            pass
