"""BackendExecutor: drives the worker gang through a training run.

Analog of ``python/ray/train/_internal/backend_executor.py:42`` (``start``
``:93``, ``_create_placement_group`` ``:137``, ``start_training`` ``:314``,
``get_next_results`` ``:411``) — placement-group creation lives inside
WorkerGroup here; this class owns backend setup, launching the train fn,
and draining per-worker result queues in lockstep.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu._private import events as _events
from ray_tpu.air import Checkpoint, ScalingConfig
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.worker_group import WorkerGroup


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
        prior_gang_starts: int = 0,
    ):
        self.backend_config = backend_config
        self.scaling_config = scaling_config
        self.backend: Backend = backend_config.backend_cls()
        self.worker_group: Optional[WorkerGroup] = None
        self._finished: List[bool] = []
        # fit() builds a FRESH executor per whole-gang restart: the prior
        # start count must ride along or every incarnation reads as the
        # first and the flight recorder never shows "gang restarted"
        self._gang_starts = prior_gang_starts

    def start(self) -> None:
        sc = self.scaling_config
        self._gang_starts += 1
        self.worker_group = WorkerGroup(
            sc.num_workers, sc.worker_resources, sc.placement_strategy
        )
        self.backend.on_start(self.worker_group, self.backend_config)
        _events.emit(
            "train",
            "gang restarted" if self._gang_starts > 1 else "gang started",
            severity="WARNING" if self._gang_starts > 1 else "INFO",
            world_size=sc.num_workers, start_no=self._gang_starts)

    def worker_node_ids(self) -> List[str]:
        """Which node each rank's actor landed on (the locality input to
        DataConfig: rank i's streaming shard materializes its blocks on
        node ``worker_node_ids()[i]``)."""
        if self.worker_group is None:
            return []

        def node_of_self():
            import ray_tpu as _rt

            return _rt.get_runtime_context().node_id

        return self.worker_group.execute(node_of_self)

    def perf_summaries(self) -> List[Optional[dict]]:
        """Per-rank step-profiler summaries (None for ranks whose train
        fn never installed one): phase totals, live MFU, compile table,
        last HBM sample — the device-time attribution artifact collected
        off the gang after (or during) a run.  Also emits one ``perf``
        flight-recorder event with the gang-level aggregate so the
        doctor and the timeline see a run's final numbers even when
        nobody polls the executor."""
        if self.worker_group is None:
            return []

        def _local():
            from ray_tpu.util import perf as _perf

            return _perf.local_summary()

        summaries = self.worker_group.execute(_local)
        ranks = [s for s in summaries if s]
        if ranks:
            mfus = [s["mfu"]["mean"] for s in ranks
                    if (s.get("mfu") or {}).get("mean") is not None]
            _events.emit(
                "perf", "gang perf summary", severity="INFO",
                world_size=len(summaries),
                profiled_ranks=len(ranks),
                steps=sum(s.get("steps", 0) for s in ranks),
                mean_mfu=round(sum(mfus) / len(mfus), 5) if mfus else None)
        return summaries

    def start_training(
        self,
        train_fn: Callable,
        config: Optional[Dict] = None,
        checkpoint: Optional[Checkpoint] = None,
        dataset_shards: Optional[List[Dict[str, Any]]] = None,
        datasets: Optional[Dict[str, Any]] = None,
        data_config=None,
        trial_info: Optional[Dict[str, str]] = None,
    ) -> None:
        if dataset_shards is None and datasets:
            # shard wiring happens HERE, not in the trainer: only the
            # executor knows which node each rank landed on, and the
            # streaming split needs those node ids as locality hints
            from ray_tpu.train.data_config import DataConfig

            data_config = data_config or DataConfig()
            dataset_shards = data_config.configure(
                datasets, self.worker_group.num_workers,
                self.worker_node_ids())
        self.backend.on_training_start(self.worker_group, self.backend_config)
        blob = cloudpickle.dumps(train_fn)
        futures = []
        for i, w in enumerate(self.worker_group.workers):
            session_kwargs: Dict[str, Any] = {
                "checkpoint": checkpoint,
                "trial_name": (trial_info or {}).get("name", ""),
                "trial_id": (trial_info or {}).get("id", ""),
            }
            if dataset_shards is not None:
                session_kwargs["dataset_shards"] = dataset_shards[i]
            futures.append(w.run_train_fn.remote(blob, config, session_kwargs))
        ray_tpu.get(futures, timeout=300)
        self._finished = [False] * self.worker_group.num_workers

    def get_next_results(self, timeout: float = 600.0) -> Optional[List[tuple]]:
        """One (kind, payload, checkpoint) per still-running worker; None
        when every worker has finished.  A worker error raises — gang
        training is all-or-nothing (a straggler is a distributed deadlock,
        so failures surface immediately)."""
        import time

        if self.worker_group is None:
            return None
        if all(self._finished):
            return None
        from ray_tpu.air import session as air_session

        deadline = time.monotonic() + timeout
        results: Dict[int, tuple] = {}
        while time.monotonic() < deadline:
            if air_session.is_stop_requested():
                # Hosting trial superseded (PBT reset): surface as "done" so
                # fit() returns and its finally releases the gang's placement
                # group promptly instead of holding TPUs past the reset.
                return None
            pending = [
                i for i in range(self.worker_group.num_workers)
                if not self._finished[i] and i not in results
            ]
            if not pending:
                break
            futs = {
                i: self.worker_group.workers[i].next_result.remote(timeout=5.0)
                for i in pending
            }
            for i, f in futs.items():
                try:
                    kind, payload, ckpt = ray_tpu.get(f, timeout=60)
                except (ray_tpu.exceptions.RayActorError,
                        ray_tpu.exceptions.WorkerCrashedError) as e:
                    # worker PROCESS death is a gang failure exactly like an
                    # in-loop exception — fit()'s whole-gang restart must
                    # see one error type.  Other RayErrors (get timeouts,
                    # cancellations) are NOT deaths and propagate as-is.
                    _events.emit("train", f"gang failure: rank {i} died",
                                 severity="ERROR", rank=i,
                                 error=f"{type(e).__name__}: {e}"[:200])
                    raise TrainingFailedError(
                        f"worker {i} died: {type(e).__name__}: {e}"
                    ) from e
                if kind == "pending":
                    continue
                if kind == "error":
                    _events.emit("train", f"gang failure: rank {i} errored",
                                 severity="ERROR", rank=i,
                                 error=str(payload)[:200])
                    raise TrainingFailedError(
                        f"worker {i} failed:\n{payload}"
                    )
                if kind == "finished":
                    self._finished[i] = True
                    continue
                results[i] = (kind, payload, ckpt)
        if all(self._finished) and not results:
            return None
        return [results[i] for i in sorted(results)] if results else []

    def shutdown(self) -> None:
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
            self.worker_group.shutdown()
            self.worker_group = None
