"""Training session: the worker-side API inside train loops.

Analog of ``python/ray/air/session.py:41`` (``session.report``) and the
``_TrainSession`` it fronts (``python/ray/train/_internal/session.py:61``):
the user's ``train_loop_per_worker`` calls ``report(metrics, checkpoint=)``
and reads rank/world info; the hosting worker wires the queue back to the
driver.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint

# Thread-local primary + process-global fallback: a superseded runner thread
# (e.g. a PBT ``reset`` swapping trainables while the old fn drains) reads its
# *own* session and can only CAS-clear the global if it still owns it, while
# helper threads the user's train fn spawns (no TLS entry) still resolve the
# most recently installed session.
_tls = threading.local()
_global_lock = threading.Lock()
_global_session: Optional["_Session"] = None

_STEP_TIME_HIST = None


def _step_time_hist():
    global _STEP_TIME_HIST
    if _STEP_TIME_HIST is None:
        from ray_tpu.util.metrics import Histogram

        _STEP_TIME_HIST = Histogram(
            "ray_tpu_train_step_time_s",
            "wall time between consecutive session.report calls (s)",
            boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30, 120],
            tag_keys=("rank",))
    return _STEP_TIME_HIST


class _Session:
    def __init__(
        self, *, world_size: int = 1, world_rank: int = 0, local_rank: int = 0,
        trial_name: str = "", trial_id: str = "", checkpoint: Optional[Checkpoint] = None,
        dataset_shards: Optional[Dict[str, Any]] = None, report_fn=None,
        stop_event: Optional[threading.Event] = None,
    ):
        self.world_size = world_size
        self.world_rank = world_rank
        self.local_rank = local_rank
        self.trial_name = trial_name
        self.trial_id = trial_id
        self.loaded_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        self._report_fn = report_fn  # callable(metrics, checkpoint)
        self.stop_event = stop_event
        self._last_report_t: Optional[float] = None

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
        from ray_tpu._private import events as _events

        if _events.ENABLED:
            # report() runs once per step in the canonical train loop, so
            # the inter-report gap IS the step time (ingest wait included;
            # the ingest-wait counter isolates that share)
            import time as _time

            now = _time.perf_counter()
            if self._last_report_t is not None:
                _step_time_hist().observe(
                    now - self._last_report_t,
                    tags={"rank": str(self.world_rank)})
            self._last_report_t = now
        if self._report_fn is not None:
            self._report_fn(metrics, checkpoint)


def _set_session(s: Optional[_Session]) -> None:
    global _global_session
    prev = getattr(_tls, "session", None)
    _tls.session = s
    with _global_lock:
        if s is not None:
            _global_session = s
        elif prev is not None and _global_session is prev:
            _global_session = None


def _get_session() -> Optional[_Session]:
    s = getattr(_tls, "session", None)
    return s if s is not None else _global_session


def is_stop_requested() -> bool:
    """True once the hosting trainable was told to stop (e.g. a PBT
    ``reset`` superseded this trial) — long-running library loops such as
    ``DataParallelTrainer.fit`` poll this to abort cooperatively."""
    s = _get_session()
    return bool(s is not None and s.stop_event is not None and s.stop_event.is_set())


def report(metrics: Dict[str, Any], *, checkpoint: Optional[Checkpoint] = None) -> None:
    """Send metrics (and optionally a checkpoint) back to the driver."""
    s = _get_session()
    if s is None:
        raise RuntimeError("session.report() called outside a train session")
    s.report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    s = _get_session()
    return s.loaded_checkpoint if s else None


def get_world_size() -> int:
    s = _get_session()
    return s.world_size if s else 1


def get_world_rank() -> int:
    s = _get_session()
    return s.world_rank if s else 0


def get_local_rank() -> int:
    s = _get_session()
    return s.local_rank if s else 0


def get_trial_name() -> str:
    s = _get_session()
    return s.trial_name if s else ""


def get_trial_id() -> str:
    s = _get_session()
    return s.trial_id if s else ""


def get_dataset_shard(name: str = "train"):
    """This worker's shard of the dataset passed to the Trainer
    (``air/session.py:345``)."""
    s = _get_session()
    if s is None:
        return None
    return s.dataset_shards.get(name)
