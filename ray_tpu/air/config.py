"""Run/scaling configuration dataclasses (``python/ray/air/config.py``).

``ScalingConfig`` speaks TPU natively: ``use_tpu`` + ``topology`` describe
a pod slice, and ``placement_strategy`` defaults to the gang semantics a
slice needs (all workers or none).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    tpus_per_worker: int = 0  # chips each worker owns (0 with use_tpu=False)
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    # TPU slice topology hint, e.g. "v5e-16" — informs mesh construction
    topology: Optional[str] = None

    @property
    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu:
            res.setdefault("TPU", float(self.tpus_per_worker or 1))
        return res


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    verbose: int = 1
    # tune.Callback instances (loggers etc.); factories taking the
    # experiment dir (e.g. CSVLoggerCallback) are instantiated by Tuner
    callbacks: Optional[list] = None
