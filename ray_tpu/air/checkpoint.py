"""Checkpoint: the tagged-union checkpoint currency.

Analog of ``python/ray/air/checkpoint.py:60``: one object losslessly
interconvertible among a dict, a local directory, a URI (local-path or
``file://`` in this build), and an object-store ref — the single currency
Train/Tune/Serve/RLlib pass around (SURVEY §5.4).

Sharded jax arrays go through orbax (:meth:`save_jax` / :meth:`load_jax`)
so multi-host checkpointing is an async tensorstore write per shard rather
than a driver-side gather.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Optional

_DICT_FILE = "checkpoint.pkl"
_JAX_DIR = "jax_state"


class Checkpoint:
    """Exactly one of ``_data`` (dict), ``_local_path``, ``_obj_ref`` is set."""

    def __init__(self, data: Optional[Dict] = None, local_path: Optional[str] = None,
                 obj_ref=None):
        if sum(x is not None for x in (data, local_path, obj_ref)) != 1:
            raise ValueError("Checkpoint takes exactly one of data/local_path/obj_ref")
        self._data = data
        self._local_path = local_path
        self._obj_ref = obj_ref
        self._uuid = uuid.uuid4().hex

    # -- constructors -------------------------------------------------
    @classmethod
    def from_dict(cls, data: Dict) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(local_path=os.path.abspath(path))

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        path = uri[len("file://"):] if uri.startswith("file://") else uri
        return cls.from_directory(path)

    @classmethod
    def from_object_ref(cls, ref) -> "Checkpoint":
        return cls(obj_ref=ref)

    # -- conversions --------------------------------------------------
    def to_dict(self) -> Dict:
        if self._data is not None:
            return dict(self._data)
        if self._obj_ref is not None:
            import ray_tpu

            return Checkpoint.from_dict(ray_tpu.get(self._obj_ref)).to_dict()
        fp = os.path.join(self._local_path, _DICT_FILE)
        if os.path.exists(fp):
            with open(fp, "rb") as f:
                return pickle.load(f)
        # directory checkpoint without a dict payload: expose the files
        out: Dict[str, Any] = {}
        for name in os.listdir(self._local_path):
            p = os.path.join(self._local_path, name)
            if os.path.isfile(p):
                with open(p, "rb") as f:
                    out[name] = f.read()
        return out

    def to_directory(self, path: Optional[str] = None) -> str:
        path = path or os.path.join(tempfile.gettempdir(), f"ckpt_{self._uuid}")
        os.makedirs(path, exist_ok=True)
        if self._local_path is not None:
            if os.path.abspath(path) != self._local_path:
                shutil.copytree(self._local_path, path, dirs_exist_ok=True)
        else:
            with open(os.path.join(path, _DICT_FILE), "wb") as f:
                pickle.dump(self.to_dict(), f, protocol=5)
        return path

    def to_uri(self, uri: str) -> str:
        path = uri[len("file://"):] if uri.startswith("file://") else uri
        self.to_directory(path)
        return uri

    def to_object_ref(self):
        import ray_tpu

        if self._obj_ref is not None:
            return self._obj_ref
        return ray_tpu.put(self.to_dict())

    # -- jax state ----------------------------------------------------
    @classmethod
    def save_jax(cls, state: Any, path: str) -> "Checkpoint":
        """Write a pytree of (possibly sharded) jax arrays with orbax."""
        import orbax.checkpoint as ocp

        path = os.path.abspath(path)
        os.makedirs(path, exist_ok=True)
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(path, _JAX_DIR), state, force=True)
        ckptr.wait_until_finished()
        return cls.from_directory(path)

    def load_jax(self, abstract_state: Any = None) -> Any:
        """Restore the orbax pytree (optionally resharded to match
        ``abstract_state``'s shardings)."""
        import orbax.checkpoint as ocp

        if self._local_path is None:
            raise ValueError("load_jax requires a directory checkpoint")
        ckptr = ocp.StandardCheckpointer()
        return ckptr.restore(
            os.path.join(self._local_path, _JAX_DIR), abstract_state
        )

    def __repr__(self):
        kind = ("dict" if self._data is not None
                else "dir" if self._local_path else "objref")
        return f"Checkpoint({kind})"
