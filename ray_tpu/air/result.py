"""Result: the terminal state of a training run (``python/ray/air/result.py``)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint


@dataclasses.dataclass
class Result:
    metrics: Optional[Dict[str, Any]] = None
    checkpoint: Optional[Checkpoint] = None
    error: Optional[Exception] = None
    path: Optional[str] = None
    metrics_dataframe: Optional[Any] = None
    best_checkpoints: Optional[List] = None

    @property
    def config(self) -> Optional[Dict]:
        return (self.metrics or {}).get("config")
