"""AIR-style shared layer: checkpoints, configs, session, results.

The common currency among Train, Tune, Serve and RLlib — the analog of
``python/ray/air`` (``Checkpoint`` ``air/checkpoint.py:60``, configs
``air/config.py``, ``session.report`` ``air/session.py:41``).
"""

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.result import Result
from ray_tpu.air import remote_storage
from ray_tpu.air import session

__all__ = [
    "remote_storage",
    "Checkpoint",
    "ScalingConfig",
    "RunConfig",
    "FailureConfig",
    "CheckpointConfig",
    "Result",
    "session",
]
