"""Remote-storage seam for checkpoints/experiments (reference
``python/ray/air/_internal/remote_storage.py`` + ``tune/syncer.py``).

A tiny filesystem registry keyed by URI scheme: ``file://`` ships in-tree;
cloud schemes (s3/gs/...) plug in through :func:`register_filesystem` — in
this zero-egress build they error actionably instead of importing cloud
SDKs.  Tune syncs experiment state through this module whenever
``RunConfig.storage_path`` is a URI.
"""

from __future__ import annotations

import os
import shutil
from typing import Dict
from urllib.parse import urlparse


class StorageFilesystem:
    """Minimal fs interface: recursive dir upload/download + existence."""

    def upload_dir(self, local_dir: str, uri: str) -> None:
        raise NotImplementedError

    def download_dir(self, uri: str, local_dir: str) -> None:
        raise NotImplementedError

    def exists(self, uri: str) -> bool:
        raise NotImplementedError


class _LocalFilesystem(StorageFilesystem):
    """``file://`` — also the template for dir-backed mock 'clouds'."""

    def _path(self, uri: str) -> str:
        p = urlparse(uri)
        return os.path.join("/", p.netloc, p.path.lstrip("/")) if p.netloc \
            else p.path

    def upload_dir(self, local_dir: str, uri: str) -> None:
        dst = self._path(uri)
        os.makedirs(dst, exist_ok=True)
        shutil.copytree(local_dir, dst, dirs_exist_ok=True)

    def download_dir(self, uri: str, local_dir: str) -> None:
        src = self._path(uri)
        if not os.path.isdir(src):
            raise FileNotFoundError(uri)
        os.makedirs(local_dir, exist_ok=True)
        shutil.copytree(src, local_dir, dirs_exist_ok=True)

    def exists(self, uri: str) -> bool:
        return os.path.exists(self._path(uri))


class DirBackedFilesystem(_LocalFilesystem):
    """A 'cloud' rooted at a local directory — the hermetic test double for
    s3/gs (the reference tests with moto/fake-gcs the same way)."""

    def __init__(self, root: str):
        self.root = root

    def _path(self, uri: str) -> str:
        p = urlparse(uri)
        return os.path.join(self.root, p.netloc, p.path.lstrip("/"))


_FILESYSTEMS: Dict[str, StorageFilesystem] = {"file": _LocalFilesystem()}


def register_filesystem(scheme: str, fs: StorageFilesystem) -> None:
    _FILESYSTEMS[scheme] = fs


def is_uri(path: str) -> bool:
    return isinstance(path, str) and "://" in path


def _fs_for(uri: str) -> StorageFilesystem:
    scheme = urlparse(uri).scheme
    fs = _FILESYSTEMS.get(scheme)
    if fs is None:
        raise ValueError(
            f"no storage backend for {scheme!r} URIs; this build ships "
            f"'file://' — register one with "
            f"ray_tpu.air.remote_storage.register_filesystem({scheme!r}, fs) "
            f"(cloud SDKs are not bundled in this environment)")
    return fs


def upload_dir(local_dir: str, uri: str) -> None:
    _fs_for(uri).upload_dir(local_dir, uri)


def download_dir(uri: str, local_dir: str) -> None:
    _fs_for(uri).download_dir(uri, local_dir)


def exists(uri: str) -> bool:
    return _fs_for(uri).exists(uri)
