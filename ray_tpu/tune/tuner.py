"""Tuner + tune.run: the experiment entry points.

Analog of ``python/ray/tune/tuner.py:44`` / ``tune/tune.py:131``.  Also
persists experiment state so ``Tuner.restore`` resumes unfinished trials
from their latest checkpoints (``TrialRunner`` restore behavior).
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import tempfile
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.air import RunConfig
from ray_tpu.tune import experiment as T
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import FIFOScheduler
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.trainable import Trainable, wrap_function
from ray_tpu.tune.trial_runner import TrialRunner

_STATE_FILE = "experiment_state.pkl"


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Optional[Any] = None
    search_alg: Optional[Any] = None
    resources_per_trial: Optional[Dict[str, float]] = None
    max_failures: int = 0
    stop: Optional[Dict[str, Any]] = None  # e.g. {"training_iteration": 10}
    # kill a trial whose single train() iteration exceeds this (a hung
    # trial must not stall the experiment); None = no deadline
    trial_timeout_s: Optional[float] = None


class Tuner:
    def __init__(
        self,
        trainable: Any,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
        _trials: Optional[list] = None,
    ):
        self.trainable = self._resolve(trainable)
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._preloaded_trials = _trials

    @staticmethod
    def _resolve(trainable) -> type:
        from ray_tpu.train.trainer import BaseTrainer

        if isinstance(trainable, BaseTrainer):
            return trainable.as_trainable()
        if isinstance(trainable, type) and issubclass(trainable, Trainable):
            return trainable
        if callable(trainable):
            return wrap_function(trainable)
        raise TypeError(f"cannot make a trainable from {trainable!r}")

    def _exp_dir(self) -> str:
        from ray_tpu.air import remote_storage

        base = self.run_config.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_results"
        )
        name = self.run_config.name or f"tune_{int(time.time())}"
        if remote_storage.is_uri(base):
            # remote experiment storage (tune/syncer.py seam): run against
            # a local working dir, sync it to the URI on every state save
            self._sync_uri = base.rstrip("/") + "/" + name
            path = os.path.join(tempfile.gettempdir(), "ray_tpu_results", name)
        else:
            self._sync_uri = None
            path = os.path.join(base, name)
        os.makedirs(path, exist_ok=True)
        return path

    def fit(self) -> ResultGrid:
        from ray_tpu._private.usage import record_feature
        record_feature("tune")

        tc = self.tune_config
        exp_dir = self._exp_dir()
        searcher = None
        if self._preloaded_trials is not None:
            trials = self._preloaded_trials
        elif tc.search_alg is not None and hasattr(tc.search_alg, "suggest"):
            # adaptive Searcher (TPE etc.): trials are suggested as slots
            # free so later suggestions learn from earlier results
            searcher = tc.search_alg
            trials = []
        else:
            gen = tc.search_alg or BasicVariantGenerator()
            trials = [
                T.Trial(config=cfg)
                for cfg in gen.variants(self.param_space, tc.num_samples)
            ]
        callbacks = list(self.run_config.callbacks or [])
        callbacks = [cb(exp_dir) if isinstance(cb, type) else cb for cb in callbacks]
        runner = TrialRunner(
            self.trainable,
            trials,
            scheduler=tc.scheduler or FIFOScheduler(),
            max_concurrent=tc.max_concurrent_trials,
            resources_per_trial=tc.resources_per_trial,
            max_failures=tc.max_failures,
            stop=tc.stop,
            trial_timeout_s=tc.trial_timeout_s,
            searcher=searcher,
            num_samples=tc.num_samples,
            callbacks=callbacks,
        )
        try:
            runner.run()
        finally:
            self._save_state(exp_dir, trials)
        return ResultGrid(trials, metric=tc.metric, mode=tc.mode)

    def _save_state(self, exp_dir: str, trials) -> None:
        state = []
        for i, t in enumerate(trials):
            ckpt_dir = None
            if t.checkpoint is not None:
                # stored RELATIVE to exp_dir: a restore on another machine
                # (different tempdir) re-roots it under its own download
                ckpt_dir = f"trial_{t.trial_id}"
                t.checkpoint.to_directory(os.path.join(exp_dir, ckpt_dir))
            state.append({
                "trial_id": t.trial_id, "config": t.config, "status": t.status,
                "last_result": t.last_result, "error": t.error,
                "checkpoint_dir": ckpt_dir,
            })
        with open(os.path.join(exp_dir, _STATE_FILE), "wb") as f:
            pickle.dump({"trials": state, "param_space": self.param_space}, f)
        if getattr(self, "_sync_uri", None):
            from ray_tpu.air import remote_storage

            remote_storage.upload_dir(exp_dir, self._sync_uri)

    @classmethod
    def restore(cls, path: str, trainable: Any,
                tune_config: Optional[TuneConfig] = None) -> "Tuner":
        """Resume: finished trials keep their results, unfinished ones
        restart from their latest checkpoints.  ``path`` may be a storage
        URI — the experiment is downloaded to a local working dir first."""
        from ray_tpu.air import Checkpoint, remote_storage

        storage_path = os.path.dirname(path.rstrip("/"))
        exp_name = os.path.basename(path.rstrip("/"))
        if remote_storage.is_uri(path):
            local = os.path.join(
                tempfile.gettempdir(), "ray_tpu_results", exp_name)
            remote_storage.download_dir(path, local)
            path = local
        with open(os.path.join(path, _STATE_FILE), "rb") as f:
            state = pickle.load(f)
        trials = []
        for s in state["trials"]:
            t = T.Trial(config=s["config"], trial_id=s["trial_id"])
            t.last_result = s["last_result"]
            t.error = s["error"]
            ckpt_dir = s["checkpoint_dir"]
            if ckpt_dir:
                if not os.path.isabs(ckpt_dir):  # re-root relative entries
                    ckpt_dir = os.path.join(path, ckpt_dir)
                if os.path.isdir(ckpt_dir):
                    t.checkpoint = Checkpoint.from_directory(ckpt_dir)
            t.status = s["status"] if s["status"] in (T.TERMINATED, T.ERROR) else T.PENDING
            trials.append(t)
        tuner = cls(
            trainable, param_space=state["param_space"],
            tune_config=tune_config,
            # keep the ORIGINAL storage_path (URI included): a resumed
            # fit() re-derives the sync target and uploads state back
            run_config=RunConfig(storage_path=storage_path, name=exp_name),
            _trials=trials,
        )
        return tuner


def run(
    trainable: Callable,
    *,
    config: Optional[Dict[str, Any]] = None,
    num_samples: int = 1,
    metric: Optional[str] = None,
    mode: str = "min",
    scheduler: Optional[Any] = None,
    stop: Optional[Dict[str, Any]] = None,
    max_concurrent_trials: int = 4,
    resources_per_trial: Optional[Dict[str, float]] = None,
    storage_path: Optional[str] = None,
    name: Optional[str] = None,
) -> ResultGrid:
    """Function-style entry point (``tune.run``, ``tune/tune.py:131``)."""
    tuner = Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(
            metric=metric, mode=mode, num_samples=num_samples,
            scheduler=scheduler, max_concurrent_trials=max_concurrent_trials,
            resources_per_trial=resources_per_trial, stop=stop,
        ),
        run_config=RunConfig(storage_path=storage_path, name=name),
    )
    return tuner.fit()
