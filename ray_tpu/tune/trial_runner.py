"""TrialRunner: the experiment event loop.

Analog of ``python/ray/tune/execution/trial_runner.py:320`` +
``ray_trial_executor.py:213``: each trial is a dedicated actor hosting its
Trainable; the loop starts trials up to the concurrency cap, waits on
in-flight ``train()`` futures, routes results through the scheduler, and
checkpoints/stops per its decisions.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.tune import experiment as T
from ray_tpu.tune.schedulers import CONTINUE, FIFOScheduler, STOP
from ray_tpu.tune.trainable import DONE

logger = logging.getLogger(__name__)


class _TrialHost:
    """Actor hosting one trial's Trainable instance."""

    def __init__(self, trainable_blob: bytes, config: Dict[str, Any]):
        cls = cloudpickle.loads(trainable_blob)
        self.trainable = cls(config)

    def train(self) -> Dict[str, Any]:
        return self.trainable.train()

    def save(self):
        return self.trainable.save()

    def restore(self, ckpt) -> bool:
        self.trainable.restore(ckpt)
        return True

    def reset(self, trainable_blob: bytes, config: Dict[str, Any], ckpt) -> bool:
        """PBT exploit: new config (+ donor checkpoint) in place."""
        if not self.trainable.reset_config(config):
            self.trainable.stop()
            cls = cloudpickle.loads(trainable_blob)
            self.trainable = cls(config)
        if ckpt is not None:
            self.trainable.restore(ckpt)
        return True

    def stop(self) -> bool:
        self.trainable.stop()
        return True


class TrialRunner:
    def __init__(
        self,
        trainable_cls: type,
        trials: List[T.Trial],
        scheduler: Optional[FIFOScheduler] = None,
        max_concurrent: int = 4,
        resources_per_trial: Optional[Dict[str, float]] = None,
        max_failures: int = 0,
        stop: Optional[Dict[str, Any]] = None,
        trial_timeout_s: Optional[float] = None,
        searcher: Optional[Any] = None,
        num_samples: Optional[int] = None,
        callbacks: Optional[List[Any]] = None,
    ):
        self.trainable_blob = cloudpickle.dumps(trainable_cls)
        self.trials = trials
        self.scheduler = scheduler or FIFOScheduler()
        self.max_concurrent = max_concurrent
        self.resources = resources_per_trial or {"CPU": 1.0}
        self.max_failures = max_failures
        self.stop_criteria = stop or {}
        # a train() iteration exceeding this is a failure (hung-trial
        # deadline — without it one wedged trial stalls the experiment)
        self.trial_timeout_s = trial_timeout_s
        # adaptive search: new trials are suggested as slots free up, so
        # later suggestions see earlier results (Searcher interface)
        self.searcher = searcher
        self.num_samples = num_samples or len(trials)
        self.callbacks = callbacks or []

    def _callback(self, hook: str, trial, *args) -> None:
        for cb in self.callbacks:
            try:
                getattr(cb, hook)(trial, *args)
            except Exception:  # noqa: BLE001 — a logger must not kill the loop
                logger.exception("callback %s.%s failed", cb, hook)

    # -- scheduler support services -----------------------------------
    def get_trial(self, trial_id: str) -> Optional[T.Trial]:
        for t in self.trials:
            if t.trial_id == trial_id:
                return t
        return None

    def exploit_trial(self, trial: T.Trial, donor: T.Trial, new_config: Dict) -> None:
        """Clone donor's weights into ``trial`` with an explored config."""
        if donor.actor is None or trial.actor is None:
            return
        ckpt = ray_tpu.get(donor.actor.save.remote(), timeout=120)
        trial.config = new_config
        ray_tpu.get(
            trial.actor.reset.remote(self.trainable_blob, new_config, ckpt),
            timeout=300,
        )

    # -- lifecycle -----------------------------------------------------
    def _start_trial(self, trial: T.Trial) -> None:
        Host = ray_tpu.remote(_TrialHost)
        opts = {}
        if "CPU" in self.resources:
            opts["num_cpus"] = self.resources["CPU"]
        if "TPU" in self.resources:
            opts["num_tpus"] = self.resources["TPU"]
        trial.actor = Host.options(**opts).remote(self.trainable_blob, trial.config)
        if trial.checkpoint is not None:
            ray_tpu.get(trial.actor.restore.remote(trial.checkpoint), timeout=300)
        trial.future = trial.actor.train.remote()
        trial.future_started = time.time()
        trial.status = T.RUNNING

    def _stop_trial(self, trial: T.Trial, status: str, save: bool = True,
                    graceful: bool = True) -> None:
        if trial.actor is not None:
            if graceful:  # a hung trial gets no goodbye round-trips
                try:
                    if save:
                        ckpt = ray_tpu.get(trial.actor.save.remote(), timeout=120)
                        if ckpt is not None:
                            trial.checkpoint = ckpt
                    ray_tpu.get(trial.actor.stop.remote(), timeout=60)
                except Exception:
                    pass
            elif trial.future is not None:
                # deadline kill: cancel the wedged train() call first (the
                # core cancellation primitive) so its future resolves with
                # TaskCancelledError instead of dangling until actor death
                try:
                    ray_tpu.cancel(trial.future, recursive=True)
                except Exception:
                    pass
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
        trial.actor = None
        trial.future = None
        trial.future_started = None
        trial.status = status

    def _should_stop(self, result: Dict[str, Any]) -> bool:
        if result.get(DONE):
            return True
        for key, bound in self.stop_criteria.items():
            v = result.get(key)
            if v is not None and v >= bound:
                return True
        return False

    def step(self) -> bool:
        """One event-loop turn; returns False when the experiment is done."""
        running = [t for t in self.trials if t.status == T.RUNNING]
        pending = [t for t in self.trials if t.status == T.PENDING]
        if self.searcher is not None:
            # top up from the searcher: each suggestion sees all completed
            # results reported so far
            while (
                len(self.trials) < self.num_samples
                and len(running) + len(pending) < self.max_concurrent
            ):
                trial = T.Trial(config={})
                cfg = self.searcher.suggest(trial.trial_id)
                if cfg is None:
                    break
                trial.config = cfg
                self.trials.append(trial)
                pending.append(trial)
        if not running and not pending:
            return False
        for t in pending[: max(0, self.max_concurrent - len(running))]:
            self._start_trial(t)
            self._callback("on_trial_start", t)
            running.append(t)
        if not running:
            return False

        futures = {t.future: t for t in running if t.future is not None}
        wait_timeout = 120.0 if self.trial_timeout_s is None else min(
            120.0, max(1.0, self.trial_timeout_s / 4)
        )
        ready, _ = ray_tpu.wait(list(futures), num_returns=1, timeout=wait_timeout)
        if self.trial_timeout_s is not None:
            # enforce the per-iteration deadline EVERY turn — a wedged
            # trial must not survive behind other trials' progress
            now = time.time()
            for trial in running:
                if trial.future in ready or trial.future is None:
                    continue
                if (trial.future_started is not None
                        and now - trial.future_started > self.trial_timeout_s):
                    trial.num_failures += 1
                    logger.warning("trial %s exceeded trial_timeout_s=%.0f; killing",
                                   trial.trial_id, self.trial_timeout_s)
                    if trial.num_failures > self.max_failures:
                        trial.error = f"trial timed out after {self.trial_timeout_s}s"
                        self._stop_trial(trial, T.ERROR, save=False, graceful=False)
                        self._callback("on_trial_error", trial)
                        if self.searcher is not None:
                            self.searcher.on_trial_complete(trial.trial_id, None)
                    else:
                        self._stop_trial(trial, T.PENDING, save=False, graceful=False)
        for fut in ready:
            trial = futures[fut]
            try:
                result = ray_tpu.get(fut)
            except Exception as e:  # noqa: BLE001
                trial.num_failures += 1
                if trial.num_failures > self.max_failures:
                    trial.error = str(e)
                    self._stop_trial(trial, T.ERROR, save=False)
                    self._callback("on_trial_error", trial)
                    if self.searcher is not None:
                        self.searcher.on_trial_complete(trial.trial_id, None)
                else:
                    self._stop_trial(trial, T.PENDING, save=False)
                continue
            # merge: the synthetic terminal {done: True} must not clobber the
            # last real metrics
            trial.last_result = {**(trial.last_result or {}), **result}
            self._callback("on_trial_result", trial, result)
            if self._should_stop(result):
                self.scheduler.on_trial_complete(self, trial, result)
                self._stop_trial(trial, T.TERMINATED)
                self._finish_trial(trial)
                continue
            decision = self.scheduler.on_trial_result(self, trial, result)
            if decision == STOP:
                self._stop_trial(trial, T.TERMINATED)
                self._finish_trial(trial)
            else:
                trial.future = trial.actor.train.remote()
                trial.future_started = time.time()
        return True

    def _finish_trial(self, trial: T.Trial) -> None:
        self._callback("on_trial_complete", trial)
        if self.searcher is not None:
            self.searcher.on_trial_complete(trial.trial_id, trial.last_result)

    def run(self) -> List[T.Trial]:
        while self.step():
            pass
        for cb in self.callbacks:
            try:
                cb.on_experiment_end(self.trials)
            except Exception:  # noqa: BLE001
                logger.exception("callback on_experiment_end failed")
        return self.trials
