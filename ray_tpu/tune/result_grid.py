"""ResultGrid: terminal view of an experiment (``tune/result_grid.py``)."""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.air import Result
from ray_tpu.tune import experiment as T


class ResultGrid:
    def __init__(self, trials: List[T.Trial], metric: Optional[str] = None,
                 mode: str = "min"):
        self._trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self) -> int:
        return len(self._trials)

    def __getitem__(self, i: int) -> Result:
        t = self._trials[i]
        return Result(
            metrics={**(t.last_result or {}), "config": t.config},
            checkpoint=t.checkpoint,
            error=RuntimeError(t.error) if t.error else None,
        )

    @property
    def errors(self) -> List[str]:
        return [t.error for t in self._trials if t.error]

    def get_best_result(self, metric: Optional[str] = None, mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (none set on TuneConfig)")
        best, best_v = None, None
        for i, t in enumerate(self._trials):
            if not t.last_result or metric not in t.last_result:
                continue
            v = t.last_result[metric]
            better = (
                best_v is None
                or (mode == "min" and v < best_v)
                or (mode == "max" and v > best_v)
            )
            if better:
                best, best_v = i, v
        if best is None:
            raise ValueError(f"no trial reported metric {metric!r}")
        return self[best]

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for t in self._trials:
            row = dict(t.last_result or {})
            row["trial_id"] = t.trial_id
            for k, v in t.config.items():
                row[f"config/{k}"] = v
            rows.append(row)
        return pd.DataFrame(rows)
