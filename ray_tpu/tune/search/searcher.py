"""Adaptive searchers: suggest-on-demand with result feedback.

Analog of the reference's ``python/ray/tune/search/searcher.py`` (Searcher:
``suggest``/``on_trial_complete``) plus an independent TPE implementation in
the spirit of the hyperopt integration (``tune/search/hyperopt``) — written
from the TPE recipe (good/bad split at a quantile, propose from the good
set's density, rank by the density ratio) with no external dependency.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer


class Searcher:
    """Adaptive search interface (``search/searcher.py`` analog).  The
    TrialRunner calls :meth:`suggest` when it has a free slot and
    :meth:`on_trial_complete` when a trial finishes."""

    def __init__(self, metric: str, mode: str = "min"):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(
        self, trial_id: str, result: Optional[Dict[str, Any]] = None
    ) -> None:
        pass


def _flatten(space: Dict, prefix: Tuple = ()) -> Dict[Tuple, Domain]:
    out: Dict[Tuple, Domain] = {}
    for k, v in space.items():
        if isinstance(v, Domain):
            out[prefix + (k,)] = v
        elif isinstance(v, dict) and "grid_search" not in v:
            out.update(_flatten(v, prefix + (k,)))
    return out


def _assemble(space: Dict, values: Dict[Tuple, Any], prefix: Tuple = ()) -> Dict:
    out = {}
    for k, v in space.items():
        path = prefix + (k,)
        if isinstance(v, Domain):
            out[k] = values[path]
        elif isinstance(v, dict) and "grid_search" not in v:
            out[k] = _assemble(v, values, path)
        else:
            out[k] = v
    return out


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator over independent dimensions.

    For each dimension: observations are split into the best ``gamma``
    fraction ("good") and the rest; candidates are drawn from a mixture of
    Gaussians centered on good observations (categorical: reweighted
    counts) and scored by the good/bad density ratio; the best of
    ``n_candidates`` wins.  The first ``n_initial_points`` suggestions are
    random (the startup phase every TPE needs).
    """

    def __init__(
        self,
        space: Dict[str, Any],
        metric: str,
        mode: str = "min",
        n_initial_points: int = 5,
        gamma: float = 0.25,
        n_candidates: int = 24,
        seed: int = 0,
    ):
        super().__init__(metric, mode)
        self.space = space
        self.dims = _flatten(space)
        if not self.dims:
            raise ValueError("TPESearcher needs at least one Domain in the space")
        self.n_initial = n_initial_points
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._live: Dict[str, Dict[Tuple, Any]] = {}  # trial -> dim values
        self._obs: List[Tuple[Dict[Tuple, Any], float]] = []  # (values, score)

    # -- Searcher interface -------------------------------------------
    def suggest(self, trial_id: str) -> Dict[str, Any]:
        if len(self._obs) < self.n_initial:
            values = {p: d.sample(self.rng) for p, d in self.dims.items()}
        else:
            values = {p: self._suggest_dim(p, d) for p, d in self.dims.items()}
        self._live[trial_id] = values
        return _assemble(self.space, values)

    def on_trial_complete(self, trial_id: str, result=None) -> None:
        values = self._live.pop(trial_id, None)
        if values is None or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "max":
            score = -score  # internally always minimize
        self._obs.append((values, score))

    # -- TPE internals ------------------------------------------------
    def _split(self) -> Tuple[list, list]:
        ranked = sorted(self._obs, key=lambda o: o[1])
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        return ranked[:n_good], ranked[n_good:]

    def _suggest_dim(self, path: Tuple, dom: Domain) -> Any:
        good, bad = self._split()
        gv = [o[0][path] for o in good]
        bv = [o[0][path] for o in bad]
        if isinstance(dom, Categorical):
            return self._categorical(dom, gv, bv)
        return self._numeric(dom, gv, bv)

    def _categorical(self, dom: Categorical, gv: list, bv: list) -> Any:
        k = len(dom.categories)
        # Laplace-smoothed counts; score = p_good / p_bad
        def probs(vals):
            c = {cat: 1.0 for cat in dom.categories}
            for v in vals:
                c[v] = c.get(v, 1.0) + 1.0
            tot = sum(c.values())
            return {cat: c[cat] / tot for cat in dom.categories}

        pg, pb = probs(gv), probs(bv)
        # sample candidates from pg, keep the best ratio
        cats = list(dom.categories)
        weights = [pg[c] for c in cats]
        best, best_score = None, -1.0
        for _ in range(min(self.n_candidates, 4 * k)):
            cand = self.rng.choices(cats, weights=weights)[0]
            score = pg[cand] / pb[cand]
            if score > best_score:
                best, best_score = cand, score
        return best

    def _numeric(self, dom: Domain, gv: list, bv: list) -> Any:
        log = isinstance(dom, Float) and dom.log
        lo = math.log(dom.low) if log else float(dom.low)
        hi = math.log(dom.high) if log else float(dom.high)
        to_x = (lambda v: math.log(v)) if log else float
        gx, bx = [to_x(v) for v in gv], [to_x(v) for v in bv]
        span = hi - lo
        # Parzen bandwidth: span scaled down with observation count
        bw_g = max(span / (1 + len(gx)), span * 0.03)
        bw_b = max(span / (1 + len(bx)), span * 0.03)

        def density(x: float, centers: list, bw: float) -> float:
            if not centers:
                return 1.0 / span
            s = 0.0
            for c in centers:
                z = (x - c) / bw
                s += math.exp(-0.5 * z * z)
            return s / (len(centers) * bw * math.sqrt(2 * math.pi)) + 1e-12

        best, best_score = None, -1.0
        for _ in range(self.n_candidates):
            center = self.rng.choice(gx) if gx else self.rng.uniform(lo, hi)
            x = self.rng.gauss(center, bw_g)
            x = min(hi, max(lo, x))
            score = density(x, gx, bw_g) / density(x, bx, bw_b)
            if score > best_score:
                best, best_score = x, score
        v = math.exp(best) if log else best
        if isinstance(dom, Integer):
            return max(dom.low, min(dom.high - 1, int(round(v))))
        return v
