from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.search.searcher import Searcher, TPESearcher
from ray_tpu.tune.search.sample import (
    Categorical,
    Domain,
    Float,
    Integer,
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)

__all__ = [
    "BasicVariantGenerator",
    "Searcher",
    "TPESearcher",
    "Domain",
    "Float",
    "Integer",
    "Categorical",
    "uniform",
    "loguniform",
    "choice",
    "randint",
    "grid_search",
]
