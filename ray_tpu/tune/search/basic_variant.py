"""Basic variant generation: grid cross-product x random sampling.

Analog of ``python/ray/tune/search/basic_variant.py``: every
``grid_search`` key expands combinatorially; ``Domain`` leaves are sampled
``num_samples`` times per grid point.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Tuple

from ray_tpu.tune.search.sample import Domain


def _split(space: Dict, prefix: Tuple = ()) -> Tuple[List, List]:
    """-> ([(path, grid values)], [(path, domain)])"""
    grids, domains = [], []
    for k, v in space.items():
        path = prefix + (k,)
        if isinstance(v, dict) and set(v) == {"grid_search"}:
            grids.append((path, v["grid_search"]))
        elif isinstance(v, dict):
            g, d = _split(v, path)
            grids += g
            domains += d
        elif isinstance(v, Domain):
            domains.append((path, v))
    return grids, domains


def _set(config: Dict, path: Tuple, value: Any) -> None:
    for k in path[:-1]:
        config = config.setdefault(k, {})
    config[path[-1]] = value


def _base(space: Dict) -> Dict:
    out = {}
    for k, v in space.items():
        if isinstance(v, dict) and set(v) == {"grid_search"}:
            continue
        if isinstance(v, Domain):
            continue
        out[k] = _base(v) if isinstance(v, dict) else v
    return out


class BasicVariantGenerator:
    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)

    def variants(self, space: Dict, num_samples: int = 1) -> Iterator[Dict]:
        grids, domains = _split(space)
        grid_values = [vals for _, vals in grids] or [[None]]
        grid_paths = [p for p, _ in grids]
        for combo in itertools.product(*grid_values):
            for _ in range(num_samples):
                cfg = _base(space)
                for path, val in zip(grid_paths, combo):
                    _set(cfg, path, val)
                for path, dom in domains:
                    _set(cfg, path, dom.sample(self.rng))
                yield cfg
