"""Search-space primitives (``python/ray/tune/search/sample.py`` analog)."""

from __future__ import annotations

import random
from typing import Any, Dict, List, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, low: float, high: float, log: bool = False):
        self.low, self.high, self.log = low, high, log

    def sample(self, rng: random.Random) -> float:
        if self.log:
            import math

            return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))
        return rng.uniform(self.low, self.high)


class Integer(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng: random.Random) -> int:
        return rng.randrange(self.low, self.high)


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


def uniform(low: float, high: float) -> Float:
    return Float(low, high)


def loguniform(low: float, high: float) -> Float:
    return Float(low, high, log=True)


def randint(low: int, high: int) -> Integer:
    return Integer(low, high)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def grid_search(values: Sequence[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}
