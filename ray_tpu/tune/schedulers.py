"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

Analog of ``python/ray/tune/schedulers/*``: the runner feeds each reported
result to ``on_trial_result`` and acts on CONTINUE/STOP decisions;
PBT additionally mutates bottom-quantile trials from top-quantile
checkpoints (``schedulers/pbt.py`` behavior).
"""

from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, runner, trial, result: Dict[str, Any]) -> None:
        pass


class ASHAScheduler(FIFOScheduler):
    """Asynchronous successive halving (``schedulers/async_hyperband.py``):
    at each rung (grace_period * reduction_factor^k iterations) a trial
    survives only if it is in the top 1/reduction_factor of results seen at
    that rung."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 100, grace_period: int = 1, reduction_factor: int = 4,
                 time_attr: str = "training_iteration"):
        self.metric, self.mode = metric, mode
        self.max_t, self.grace, self.rf = max_t, grace_period, reduction_factor
        self.time_attr = time_attr
        self.rungs: Dict[int, List[float]] = defaultdict(list)
        self._passed: Dict[str, int] = defaultdict(int)  # trial -> rungs cleared

    def _milestones(self) -> List[int]:
        ms, t = [], self.grace
        while t < self.max_t:
            ms.append(t)
            t *= self.rf
        return ms

    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        score = -value if self.mode == "min" else value
        # Compare at the first result with t >= milestone (results need not
        # land exactly on grace*rf^k).  Only the HIGHEST milestone crossed is
        # recorded — a t=4-matured score folded into rung 1 would inflate the
        # cutoff against trials legitimately reporting at t=1.
        milestones = self._milestones()
        n_cleared = self._passed[trial.trial_id]
        crossed = [m for m in milestones[n_cleared:] if t >= m]
        if not crossed:
            return CONTINUE
        self._passed[trial.trial_id] = n_cleared + len(crossed)
        m = crossed[-1]
        rung = self.rungs[m]
        rung.append(score)
        k = max(1, len(rung) // self.rf)
        cutoff = sorted(rung, reverse=True)[k - 1]
        return STOP if score < cutoff else CONTINUE


class MedianStoppingRule(FIFOScheduler):
    """Stop a trial whose best result is worse than the median of running
    averages (``schedulers/median_stopping_rule.py``)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 3, min_samples_required: int = 3,
                 time_attr: str = "training_iteration"):
        self.metric, self.mode = metric, mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr
        self.histories: Dict[str, List[float]] = defaultdict(list)

    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        score = -value if self.mode == "min" else value
        self.histories[trial.trial_id].append(score)
        t = result.get(self.time_attr, 0)
        if t < self.grace or len(self.histories) < self.min_samples:
            return CONTINUE
        means = [sum(h) / len(h) for tid, h in self.histories.items()
                 if tid != trial.trial_id and h]
        if not means:
            return CONTINUE
        median = sorted(means)[len(means) // 2]
        best = max(self.histories[trial.trial_id])
        return STOP if best < median else CONTINUE


class PopulationBasedTraining(FIFOScheduler):
    """PBT (``schedulers/pbt.py``): every ``perturbation_interval``
    iterations, bottom-quantile trials exploit (clone config+checkpoint of
    a top-quantile trial) and explore (perturb hyperparams)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25, seed: int = 0,
                 time_attr: str = "training_iteration"):
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.rng = random.Random(seed)
        self.time_attr = time_attr
        self.latest: Dict[str, float] = {}
        self.last_perturb: Dict[str, int] = defaultdict(int)

    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        score = -value if self.mode == "min" else value
        self.latest[trial.trial_id] = score
        t = result.get(self.time_attr, 0)
        if t - self.last_perturb[trial.trial_id] < self.interval:
            return CONTINUE
        self.last_perturb[trial.trial_id] = t
        if len(self.latest) < 2:
            return CONTINUE
        ranked = sorted(self.latest.items(), key=lambda kv: kv[1], reverse=True)
        k = max(1, int(len(ranked) * self.quantile))
        top = [tid for tid, _ in ranked[:k]]
        bottom = {tid for tid, _ in ranked[-k:]}
        if trial.trial_id in bottom and trial.trial_id not in top:
            donor_id = self.rng.choice(top)
            donor = runner.get_trial(donor_id)
            if donor is not None:
                runner.exploit_trial(trial, donor, self._explore(donor.config))
        return CONTINUE

    def _explore(self, config: Dict) -> Dict:
        new = dict(config)
        for key, spec in self.mutations.items():
            if isinstance(spec, list):
                new[key] = self.rng.choice(spec)
            elif callable(spec):
                new[key] = spec()
            elif key in new and isinstance(new[key], (int, float)):
                factor = self.rng.choice([0.8, 1.2])
                new[key] = type(new[key])(new[key] * factor)
        return new
