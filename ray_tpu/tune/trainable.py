"""Trainables: the unit of work a trial executes.

``Trainable`` mirrors the reference's class API
(``python/ray/tune/trainable/trainable.py``): ``setup/step/
save_checkpoint/load_checkpoint/cleanup``.  ``wrap_function`` turns a
``fn(config)`` using ``session.report`` into a Trainable whose ``step()``
yields one reported result at a time (``tune/trainable/function_trainable
.py`` analog).
"""

from __future__ import annotations

import queue
import threading
import traceback
from typing import Any, Callable, Dict, Optional

from ray_tpu.air import Checkpoint
from ray_tpu.air import session as air_session

DONE = "done"
TRAINING_ITERATION = "training_iteration"


class Trainable:
    def __init__(self, config: Optional[Dict] = None):
        self.config = config or {}
        self.iteration = 0
        self.setup(self.config)

    # -- subclass API --------------------------------------------------
    def setup(self, config: Dict) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self) -> Optional[Dict]:
        return None

    def load_checkpoint(self, state: Dict) -> None:
        pass

    def cleanup(self) -> None:
        pass

    def reset_config(self, new_config: Dict) -> bool:
        """PBT exploit hook; return True if handled without re-setup."""
        return False

    # -- runner-facing -------------------------------------------------
    def train(self) -> Dict[str, Any]:
        result = self.step()
        self.iteration += 1
        result.setdefault(TRAINING_ITERATION, self.iteration)
        result.setdefault(DONE, False)
        return result

    def save(self) -> Optional[Checkpoint]:
        state = self.save_checkpoint()
        if state is None:
            return None
        state["_iteration"] = self.iteration
        return Checkpoint.from_dict(state)

    def restore(self, ckpt: Checkpoint) -> None:
        state = ckpt.to_dict()
        self.iteration = state.pop("_iteration", 0)
        self.load_checkpoint(state)

    def stop(self) -> None:
        self.cleanup()


class _SessionStopped(BaseException):
    """Raised inside a superseded runner thread at its next report."""


class FunctionTrainable(Trainable):
    """Runs ``fn(config)`` on a thread; each ``step()`` is the next
    ``session.report`` payload."""

    _fn: Callable = None  # set by wrap_function subclassing

    def setup(self, config: Dict) -> None:
        self._queue: "queue.Queue" = queue.Queue()
        self._latest_ckpt: Optional[Checkpoint] = None
        self._restored_ckpt: Optional[Checkpoint] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()

    def _ensure_started(self) -> None:
        if self._thread is not None:
            return
        stop_event = self._stop_event

        def report_fn(metrics, checkpoint):
            if stop_event.is_set():
                raise _SessionStopped
            self._queue.put(("report", metrics, checkpoint))

        sess = air_session._Session(
            checkpoint=self._restored_ckpt, report_fn=report_fn,
            stop_event=stop_event,
        )

        def runner():
            air_session._set_session(sess)
            try:
                self._fn(self.config)
                self._queue.put(("finished", None, None))
            except _SessionStopped:
                pass
            except BaseException:  # noqa: BLE001
                self._queue.put(("error", traceback.format_exc(), None))
            finally:
                air_session._set_session(None)

        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()

    def step(self) -> Dict[str, Any]:
        self._ensure_started()
        kind, payload, ckpt = self._queue.get(timeout=600)
        if kind == "error":
            raise RuntimeError(f"trial function failed:\n{payload}")
        if kind == "finished":
            return {DONE: True}
        if ckpt is not None:
            self._latest_ckpt = ckpt
        result = dict(payload)
        result.setdefault(DONE, False)
        return result

    def save_checkpoint(self) -> Optional[Dict]:
        return self._latest_ckpt.to_dict() if self._latest_ckpt else None

    def load_checkpoint(self, state: Dict) -> None:
        self._restored_ckpt = Checkpoint.from_dict(state)

    def stop(self) -> None:
        """Signal the runner thread to die at its next report and join it,
        so a PBT ``reset`` never races a stale fn still training."""
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        super().stop()


def wrap_function(fn: Callable) -> type:
    """fn(config) -> Trainable subclass (``tune/trainable`` wrap_function)."""
    return type(f"Func_{getattr(fn, '__name__', 'trainable')}",
                (FunctionTrainable,), {"_fn": staticmethod(fn)})


def wrap_trainer(trainer) -> type:
    """BaseTrainer -> Trainable: each trial runs trainer.fit() with the
    trial config merged into train_loop_config (base_trainer.py:352-397)."""
    import copy
    import uuid

    def fn(config):
        t = copy.copy(trainer)
        # Each trial gets its own storage dir: a shared run_config would have
        # every trial's checkpoint bookkeeping writing/deleting the same
        # checkpoint_00000N paths and clobbering each other.
        rc = copy.copy(t.run_config)
        rc.name = f"{rc.name or 'train'}_{uuid.uuid4().hex[:8]}"
        t.run_config = rc
        # A restored/donor checkpoint (failure restore, PBT exploit) must
        # seed the trainer, or the trial silently retrains from step 0.
        restored = air_session.get_checkpoint()
        if restored is not None:
            t.resume_from_checkpoint = restored
        if getattr(t, "train_loop_config", None) is not None:
            merged = dict(t.train_loop_config)
            merged.update(config)
            t.train_loop_config = merged
        elif config:
            t.train_loop_config = dict(config)
        result = t.fit()
        if result.error is not None:
            raise result.error
        air_session.report(result.metrics or {DONE: True},
                           checkpoint=result.checkpoint)

    return wrap_function(fn)
