"""Tune: experiment runner — trial scheduling, search, checkpointing.

Analog of ``python/ray/tune`` (``Tuner`` ``tune/tuner.py:44``, ``tune.run``
``tune/tune.py:131``, ``TrialRunner`` ``execution/trial_runner.py:320``):
trials run as actors, schedulers (ASHA/PBT/median-stopping) make
continue/stop decisions on reported results, and Train runs on Tune via
``BaseTrainer.as_trainable``.
"""

from ray_tpu.tune.callback import Callback, CSVLoggerCallback, JSONLoggerCallback
from ray_tpu.tune.search.searcher import Searcher, TPESearcher
from ray_tpu.tune.trainable import Trainable, wrap_function
from ray_tpu.tune.search.sample import (
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.tuner import TuneConfig, Tuner, run
from ray_tpu.tune.result_grid import ResultGrid

__all__ = [
    "Trainable",
    "wrap_function",
    "Callback",
    "CSVLoggerCallback",
    "JSONLoggerCallback",
    "Searcher",
    "TPESearcher",
    "uniform",
    "loguniform",
    "choice",
    "randint",
    "grid_search",
    "FIFOScheduler",
    "ASHAScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "Tuner",
    "TuneConfig",
    "run",
    "ResultGrid",
]
