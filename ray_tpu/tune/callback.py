"""Experiment callbacks + logger callbacks.

Analog of the reference's ``python/ray/tune/callback.py`` (Callback hooks
driven by the TrialRunner loop) and ``tune/logger/`` (CSV/JSON per-trial
result logging).
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, List, Optional


class Callback:
    """Hook points the TrialRunner invokes (``tune/callback.py`` analog)."""

    def on_trial_start(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial) -> None:
        pass

    def on_trial_error(self, trial) -> None:
        pass

    def on_experiment_end(self, trials: List) -> None:
        pass


def _scalars(result: Dict[str, Any]) -> Dict[str, Any]:
    return {
        k: v for k, v in result.items()
        if isinstance(v, (int, float, str, bool)) or v is None
    }


class JSONLoggerCallback(Callback):
    """One ``result.json`` (JSON lines) per trial (``tune/logger/json.py``
    analog)."""

    def __init__(self, exp_dir: str):
        self._dir = exp_dir

    def _path(self, trial) -> str:
        d = os.path.join(self._dir, f"trial_{trial.trial_id}")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, "result.json")

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        with open(self._path(trial), "a") as f:
            json.dump(_scalars(result), f, default=str)
            f.write("\n")


class CSVLoggerCallback(Callback):
    """One ``progress.csv`` per trial (``tune/logger/csv.py`` analog).
    The header is fixed by the first result; later extra keys are dropped
    (the reference's behavior)."""

    def __init__(self, exp_dir: str):
        self._dir = exp_dir
        self._fields: Dict[str, List[str]] = {}

    def _path(self, trial) -> str:
        d = os.path.join(self._dir, f"trial_{trial.trial_id}")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, "progress.csv")

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        row = _scalars(result)
        path = self._path(trial)
        fields = self._fields.get(trial.trial_id)
        if fields is None:
            fields = self._fields[trial.trial_id] = list(row)
            with open(path, "w", newline="") as f:
                csv.DictWriter(f, fieldnames=fields).writeheader()
        with open(path, "a", newline="") as f:
            csv.DictWriter(f, fieldnames=fields, extrasaction="ignore").writerow(row)
