"""Trial state (``python/ray/tune/experiment/trial.py:207`` analog)."""

from __future__ import annotations

import dataclasses
import uuid
from typing import Any, Dict, Optional

from ray_tpu.air import Checkpoint

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclasses.dataclass
class Trial:
    config: Dict[str, Any]
    trial_id: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex[:8])
    status: str = PENDING
    last_result: Optional[Dict[str, Any]] = None
    checkpoint: Optional[Checkpoint] = None
    error: Optional[str] = None
    num_failures: int = 0
    # runtime handles (not persisted)
    actor: Any = None
    future: Any = None
    # wall time the in-flight train() future was armed (deadline tracking)
    future_started: Optional[float] = None

    @property
    def is_finished(self) -> bool:
        return self.status in (TERMINATED, ERROR)
