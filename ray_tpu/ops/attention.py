"""Attention implementations with one contract — ``[B, H, T, D]`` q/k/v.

:func:`attention` dispatches by shape (measured on v5e, see each impl's
docstring):

- :func:`causal_skip_attention` — the causal production path at moderate
  T: unrolled q-blocks contracting only visible keys (~40% FLOPs saved),
  bf16 matmuls with f32 accumulation.  Fastest measured fwd+bwd.
- :func:`full_attention` — masked materialized-scores path (non-causal,
  or shapes causal-skip can't take).
- :func:`blockwise_attention` — online-softmax ``lax.scan`` over k/v
  blocks; O(block) memory, any length (pads+masks), differentiable; also
  the inner block the ring-attention layer reuses.

Not in the dispatch:

- :func:`mha_reference` — naive O(T²) f32 attention; numerical ground
  truth for tests.
- :func:`flash_attention_tpu` — our pallas MXU-tiled kernel with a
  blockwise-recompute backward.  Benchmarked SLOWER than the XLA paths
  above at GPT-2 shapes (d_head=64) on v5e — kept as an explicit opt-in
  and as the starting point for long-context kernel work, not selected
  automatically.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def mha_reference(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Naive O(T²) attention, the numerical ground truth."""
    *_, t_q, d = q.shape
    t_k = k.shape[-2]
    scale = scale if scale is not None else d ** -0.5
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t_q, t_k), dtype=bool), t_k - t_q)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p.astype(v.dtype), v)


def _block_update(carry, s, v_blk):
    """One online-softmax step: fold scores ``s`` (f32, [..., q, kb]) and
    values ``v_blk`` into the running (out, max, denom)."""
    o, m, l = carry
    m_new = jnp.maximum(m, s.max(axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "...qk,...kd->...qd", p, v_blk.astype(jnp.float32)
    )
    return o_new, m_new, l_new


def blockwise_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = False,
    scale: Optional[float] = None, block_k: int = 512,
) -> jax.Array:
    """Flash-style attention as a ``lax.scan`` over k/v blocks.

    O(T_k / block_k) sequential steps, O(block) memory per step; jax AD
    differentiates through the scan, and ``jax.checkpoint`` around the
    caller gives full rematerialization.  Also correct when ``t_k != t_q``
    (used by ring attention, where k/v rotate around the ``sp`` ring).
    """
    *_, t_q, d = q.shape
    t_k = k.shape[-2]
    scale = scale if scale is not None else d ** -0.5
    block_k = min(block_k, t_k)
    # Lengths that don't divide block_k are padded (padded keys masked out
    # below) rather than shrinking the block — a prime t_k with block_k=1
    # would mean t_k sequential 1-wide matmul steps.
    pad = (-t_k) % block_k
    if pad:
        widths = [(0, 0)] * (k.ndim - 2) + [(0, pad), (0, 0)]
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    n_blocks = (t_k + pad) // block_k

    qf = q.astype(jnp.float32) * scale
    k_blocks = k.reshape(*k.shape[:-2], n_blocks, block_k, d)
    v_blocks = v.reshape(*v.shape[:-2], n_blocks, block_k, d)
    # scan over the block axis: move it to front
    k_blocks = jnp.moveaxis(k_blocks, -3, 0)
    v_blocks = jnp.moveaxis(v_blocks, -3, 0)

    q_pos = jnp.arange(t_q) + (t_k - t_q)  # align causal diagonal

    def step(carry, blk):
        idx, k_blk, v_blk = blk
        s = jnp.einsum("...qd,...kd->...qk", qf, k_blk.astype(jnp.float32))
        k_pos = idx * block_k + jnp.arange(block_k)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            if pad:
                mask &= (k_pos < t_k)[None, :]
            s = jnp.where(mask, s, NEG_INF)
        elif pad:
            s = jnp.where((k_pos < t_k)[None, :], s, NEG_INF)
        return _block_update(carry, s, v_blk), None

    o0 = jnp.zeros((*q.shape[:-1], d), jnp.float32)
    m0 = jnp.full(q.shape[:-1], NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1], jnp.float32)
    (o, m, l), _ = lax.scan(
        step, (o0, m0, l0), (jnp.arange(n_blocks), k_blocks, v_blocks)
    )
    return (o / l[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

try:  # pallas import is deferred-safe: CPU-only envs may lack the TPU bits
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                  *, scale: float, causal: bool, block_q: int, block_k: int):
    """Grid = (batch*heads, n_q_blocks, n_k_blocks); the k axis is the
    innermost (sequential) dimension, so the f32 scratch (acc, m, l)
    carries the online softmax across k steps of one q block."""
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]  # [block_q, d]
    k = k_ref[0]  # [block_k, d]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale

    if causal:
        qi = pl.program_id(1)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)

    m_prev = m_ref[:, 0]
    m_new = jnp.maximum(m_prev, s.max(axis=-1))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[:, None])
    l_ref[:, 0] = l_ref[:, 0] * alpha + p.sum(axis=-1)
    m_ref[:, 0] = m_new
    acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ki == nk - 1)
    def _():
        o_ref[0] = (acc_ref[:] / l_ref[:, 0][:, None]).astype(o_ref.dtype)


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool, scale: float,
    block_q: int, block_k: int, interpret: bool,
) -> jax.Array:
    b, h, t_q, d = q.shape
    t_k = k.shape[-2]
    bq, bk = min(block_q, t_q), min(block_k, t_k)
    if t_q % bq or t_k % bk:
        raise ValueError(f"seq lens ({t_q},{t_k}) not divisible by blocks ({bq},{bk})")
    qr = q.reshape(b * h, t_q, d)
    kr = k.reshape(b * h, t_k, d)
    vr = v.reshape(b * h, t_k, d)
    grid = (b * h, t_q // bq, t_k // bk)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),   # running max
            pltpu.VMEM((bq, 1), jnp.float32),   # running denom
            pltpu.VMEM((bq, d), jnp.float32),   # output accumulator
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, t_q, d)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def flash_attention_tpu(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = False, scale: Optional[float] = None,
    block_q: int = 128, block_k: int = 128, interpret: bool = False,
) -> jax.Array:
    """Pallas flash attention.  Forward runs the MXU-tiled kernel; backward
    recomputes with :func:`blockwise_attention` (flash-style memory) and
    differentiates that."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash_forward(
        q, k, v, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = flash_attention_tpu(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(
            q, k, v, causal=causal, scale=scale, block_k=block_k
        ),
        q, k, v,
    )
    return vjp(g)


flash_attention_tpu.defvjp(_flash_fwd, _flash_bwd)


def attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = False,
    scale: Optional[float] = None, block_q: int = 128, block_k: int = 128,
) -> jax.Array:
    """Dispatch to the fastest correct implementation for the shape.
    Single entry point used by the model zoo.

    - causal, square, block-divisible, moderate T → :func:`causal_skip_attention`
    - moderate T → :func:`full_attention` (masked, MXU dtypes)
    - long T → :func:`blockwise_attention` (O(block) memory, pads+masks
      any length; ring attention covers sharded-T)
    """
    t_q, t_k = q.shape[-2], k.shape[-2]
    if t_q <= _MAX_MATERIALIZED_T and t_k <= _MAX_MATERIALIZED_T:
        if causal and t_q == t_k and t_q % 256 == 0 and t_q >= 512:
            return causal_skip_attention(q, k, v, scale=scale, block=256)
        return full_attention(q, k, v, causal=causal, scale=scale)
    return blockwise_attention(
        q, k, v, causal=causal, scale=scale, block_k=block_k
    )


def _scores(q, k, scale: float) -> jax.Array:
    """Q·Kᵀ in the input dtype with f32 accumulation (MXU-friendly)."""
    bdims = tuple(range(q.ndim - 2))
    return lax.dot_general(
        q, k, (((q.ndim - 1,), (k.ndim - 1,)), (bdims, bdims)),
        preferred_element_type=jnp.float32,
    ) * scale


def _weighted_values(p: jax.Array, v: jax.Array) -> jax.Array:
    """softmax(P)·V with P cast back to V's dtype for the MXU."""
    bdims = tuple(range(p.ndim - 2))
    return lax.dot_general(
        p.astype(v.dtype), v,
        (((p.ndim - 1,), (v.ndim - 2,)), (bdims, bdims)),
    )


def full_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Materialized-scores attention with MXU-friendly dtypes: inputs stay
    in their dtype (bf16 in the models), scores accumulate in f32
    (``preferred_element_type``), softmax in f32, P@V back in input dtype.

    Measured faster fwd+bwd on v5e at moderate T than our pallas kernel,
    jax's in-tree pallas flash, and f32 blockwise (XLA fuses the masked
    softmax; head_dim=64 tiles fine).
    """
    *_, t_q, d = q.shape
    t_k = k.shape[-2]
    scale = scale if scale is not None else d ** -0.5
    s = _scores(q, k, scale)
    if causal:
        mask = jnp.tril(jnp.ones((t_q, t_k), dtype=bool), t_k - t_q)
        s = jnp.where(mask, s, NEG_INF)
    return _weighted_values(jax.nn.softmax(s, axis=-1), v)


def causal_skip_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *,
    scale: Optional[float] = None, block: int = 256,
) -> jax.Array:
    """Causal attention that skips fully-masked key blocks: an unrolled
    loop over q blocks where block i only contracts keys ``[0:(i+1)*block]``
    — ~40% fewer FLOPs than masked full attention at T=1024, every matmul
    shape static so XLA tiles each branch onto the MXU.  Requires
    ``t_q == t_k`` divisible by ``block``.

    One dot + one full-width masked select per q block, deliberately: an
    A/B with separate unmasked-prefix/masked-diagonal dots measured ~7%
    SLOWER end-to-end (XLA fuses the select into the softmax for free, but
    two dots + concat fuse worse than one).  Measured ~2.5x faster fwd+bwd
    than both pallas flash kernels (ours and jax's in-tree) at GPT-2
    shapes on v5e — which is why this, not the pallas path, is the
    dispatcher's causal default.
    """
    *_, t, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    n = t // block
    outs = []
    for i in range(n):
        qi = lax.slice_in_dim(q, i * block, (i + 1) * block, axis=-2)
        kv_len = (i + 1) * block
        ki = lax.slice_in_dim(k, 0, kv_len, axis=-2)
        vi = lax.slice_in_dim(v, 0, kv_len, axis=-2)
        q_pos = i * block + jnp.arange(block)
        mask = q_pos[:, None] >= jnp.arange(kv_len)[None, :]
        s = jnp.where(mask, _scores(qi, ki, scale), NEG_INF)
        outs.append(_weighted_values(jax.nn.softmax(s, axis=-1), vi))
    return jnp.concatenate(outs, axis=-2)


# Above this, materialized scores risk HBM pressure; the O(block) blockwise
# path takes over.
_MAX_MATERIALIZED_T = 4096
