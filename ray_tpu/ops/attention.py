"""Attention implementations with one contract — ``[B, H, T, D]`` q/k/v.

:func:`attention` dispatches by shape (measured on v5e, see each impl's
docstring):

- :func:`causal_skip_attention` — the causal production path at moderate
  T: unrolled q-blocks contracting only visible keys (~40% FLOPs saved),
  bf16 matmuls with f32 accumulation.  Fastest measured fwd+bwd.
- :func:`full_attention` — masked materialized-scores path (non-causal,
  or shapes causal-skip can't take).
- :func:`blockwise_attention` — online-softmax ``lax.scan`` over k/v
  blocks; O(block) memory, any length (pads+masks), differentiable; also
  the inner block the ring-attention layer reuses.

- :func:`flash_attention_tpu` — pallas MXU-tiled kernels for BOTH forward
  and backward (dq/dk/dv rebuilt from the saved logsumexp, recompute-free).
  Slower than the XLA paths at GPT-2 shapes (d_head=64, T≤4k) but fastest
  from ~8k tokens — the dispatch selects it for long context on TPU.

Not in the dispatch:

- :func:`mha_reference` — naive O(T²) f32 attention; numerical ground
  truth for tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def mha_reference(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Naive O(T²) attention, the numerical ground truth."""
    *_, t_q, d = q.shape
    t_k = k.shape[-2]
    scale = scale if scale is not None else d ** -0.5
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t_q, t_k), dtype=bool), t_k - t_q)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p.astype(v.dtype), v)


def _block_update(carry, s, v_blk):
    """One online-softmax step: fold scores ``s`` (f32, [..., q, kb]) and
    values ``v_blk`` into the running (out, max, denom)."""
    o, m, l = carry
    m_new = jnp.maximum(m, s.max(axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "...qk,...kd->...qd", p, v_blk.astype(jnp.float32)
    )
    return o_new, m_new, l_new


def blockwise_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = False,
    scale: Optional[float] = None, block_k: int = 512,
) -> jax.Array:
    """Flash-style attention as a ``lax.scan`` over k/v blocks.

    O(T_k / block_k) sequential steps, O(block) memory per step; jax AD
    differentiates through the scan, and ``jax.checkpoint`` around the
    caller gives full rematerialization.  Also correct when ``t_k != t_q``
    (used by ring attention, where k/v rotate around the ``sp`` ring).
    """
    *_, t_q, d = q.shape
    t_k = k.shape[-2]
    scale = scale if scale is not None else d ** -0.5
    block_k = min(block_k, t_k)
    # Lengths that don't divide block_k are padded (padded keys masked out
    # below) rather than shrinking the block — a prime t_k with block_k=1
    # would mean t_k sequential 1-wide matmul steps.
    pad = (-t_k) % block_k
    if pad:
        widths = [(0, 0)] * (k.ndim - 2) + [(0, pad), (0, 0)]
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    n_blocks = (t_k + pad) // block_k

    qf = q.astype(jnp.float32) * scale
    k_blocks = k.reshape(*k.shape[:-2], n_blocks, block_k, d)
    v_blocks = v.reshape(*v.shape[:-2], n_blocks, block_k, d)
    # scan over the block axis: move it to front
    k_blocks = jnp.moveaxis(k_blocks, -3, 0)
    v_blocks = jnp.moveaxis(v_blocks, -3, 0)

    q_pos = jnp.arange(t_q) + (t_k - t_q)  # align causal diagonal

    def step(carry, blk):
        idx, k_blk, v_blk = blk
        s = jnp.einsum("...qd,...kd->...qk", qf, k_blk.astype(jnp.float32))
        k_pos = idx * block_k + jnp.arange(block_k)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            if pad:
                mask &= (k_pos < t_k)[None, :]
            s = jnp.where(mask, s, NEG_INF)
        elif pad:
            s = jnp.where((k_pos < t_k)[None, :], s, NEG_INF)
        return _block_update(carry, s, v_blk), None

    o0 = jnp.zeros((*q.shape[:-1], d), jnp.float32)
    m0 = jnp.full(q.shape[:-1], NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1], jnp.float32)
    (o, m, l), _ = lax.scan(
        step, (o0, m0, l0), (jnp.arange(n_blocks), k_blocks, v_blocks)
    )
    return (o / l[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

try:  # pallas import is deferred-safe: CPU-only envs may lack the TPU bits
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _masked_scores(q_ref, k_ref, qi, ki, *, scale, causal, block_q, block_k,
                   q_offset):
    """scale·QKᵀ for one (q block, k block) cell, causal-masked with the
    bottom-right-aligned diagonal.  Shared by the forward and both backward
    kernels so masking semantics can never desynchronize."""
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    return s


def _block_visible(qi, ki, *, block_q, block_k, q_offset):
    """True iff the (qi, ki) cell has any unmasked element — cells fully
    above the causal diagonal are skipped (≈2x MXU work saved at long T)."""
    return ki * block_k <= q_offset + (qi + 1) * block_q - 1


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
                  *, scale: float, causal: bool, block_q: int, block_k: int,
                  q_offset: int):
    """Grid = (batch*heads, n_q_blocks, n_k_blocks); the k axis is the
    innermost (sequential) dimension, so the f32 scratch (acc, m, l)
    carries the online softmax across k steps of one q block."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    visible = (
        _block_visible(qi, ki, block_q=block_q, block_k=block_k, q_offset=q_offset)
        if causal else ki >= 0
    )

    @pl.when(visible)
    def _():
        s = _masked_scores(q_ref, k_ref, qi, ki, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k, q_offset=q_offset)
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_ref[:, 0] = l_ref[:, 0] * alpha + p.sum(axis=-1)
        m_ref[:, 0] = m_new
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _():
        o_ref[0] = (acc_ref[:] / l_ref[:, 0][:, None]).astype(o_ref.dtype)
        # logsumexp residual: the backward kernels rebuild P = exp(S - LSE)
        # from it without re-running the online softmax.  Kept as a
        # [bq, 1] column (TPU blocks want the sublane dim divisible by 8).
        lse_ref[0, :, 0] = m_ref[:, 0] + jnp.log(l_ref[:, 0])


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool, scale: float,
    block_q: int, block_k: int, interpret: bool,
):
    """Returns (out [B,H,Tq,D], lse [B,H,Tq] f32)."""
    b, h, t_q, d = q.shape
    t_k = k.shape[-2]
    bq, bk = min(block_q, t_q), min(block_k, t_k)
    if t_q % bq or t_k % bk:
        raise ValueError(f"seq lens ({t_q},{t_k}) not divisible by blocks ({bq},{bk})")
    qr = q.reshape(b * h, t_q, d)
    kr = k.reshape(b * h, t_k, d)
    vr = v.reshape(b * h, t_k, d)
    grid = (b * h, t_q // bq, t_k // bk)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        q_offset=t_k - t_q,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, t_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),   # running max
            pltpu.VMEM((bq, 1), jnp.float32),   # running denom
            pltpu.VMEM((bq, d), jnp.float32),   # output accumulator
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, t_q, d), lse.reshape(b, h, t_q)


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                     dq_acc, *, scale: float, causal: bool,
                     block_q: int, block_k: int, q_offset: int):
    """dQ: grid (bh, n_q, n_k), k innermost; one q block accumulates
    dQ = sum_k dS @ K with dS = P * (dO Vᵀ - Δ) * scale, P = exp(S - LSE)
    rebuilt from the forward's logsumexp (recompute-free backward,
    FlashAttention-2 eq. 13-16)."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    visible = (
        _block_visible(qi, ki, block_q=block_q, block_k=block_k, q_offset=q_offset)
        if causal else ki >= 0
    )

    @pl.when(visible)
    def _():
        s = _masked_scores(q_ref, k_ref, qi, ki, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k, q_offset=q_offset)
        p = jnp.exp(s - lse_ref[0])               # [bq,1] bcast -> [bq, bk]
        do = do_ref[0]
        dp = jax.lax.dot_general(                 # dO @ Vᵀ  [bq, bk]
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0]) * scale
        k = k_ref[0]
        dq_acc[:] += jax.lax.dot_general(         # dS @ K  [bq, d]
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                      causal: bool, block_q: int, block_k: int,
                      q_offset: int):
    """dK/dV: grid (bh, n_k, n_q), q innermost; one k block accumulates
    dV = sum_q Pᵀ @ dO and dK = sum_q dSᵀ @ Q."""
    kbi = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    visible = (
        _block_visible(qi, kbi, block_q=block_q, block_k=block_k, q_offset=q_offset)
        if causal else qi >= 0
    )

    @pl.when(visible)
    def _():
        s = _masked_scores(q_ref, k_ref, qi, kbi, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k, q_offset=q_offset)
        p = jnp.exp(s - lse_ref[0])               # [bq,1] bcast -> [bq, bk]
        do = do_ref[0]
        dv_acc[:] += jax.lax.dot_general(         # Pᵀ @ dO  [bk, d]
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0]) * scale
        q = q_ref[0]
        dk_acc[:] += jax.lax.dot_general(         # dSᵀ @ Q  [bk, d]
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, *, causal, scale,
                    block_q, block_k, interpret):
    b, h, t_q, d = q.shape
    t_k = k.shape[-2]
    bq, bk = min(block_q, t_q), min(block_k, t_k)
    qr = q.reshape(b * h, t_q, d)
    kr = k.reshape(b * h, t_k, d)
    vr = v.reshape(b * h, t_k, d)
    dor = g.reshape(b * h, t_q, d)
    lser = lse.reshape(b * h, t_q, 1)
    # Δ = rowsum(dO ⊙ O): one fused elementwise reduce, cheap in XLA
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).reshape(b * h, t_q, 1)

    q_spec = pl.BlockSpec((1, bq, d), lambda bh, a, b2: (bh, a, 0))
    row_spec = pl.BlockSpec((1, bq, 1), lambda bh, a, b2: (bh, a, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, q_offset=t_k - t_q),
        grid=(b * h, t_q // bq, t_k // bk),
        in_specs=[
            q_spec,                                                # q by qi
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
            q_spec,                                                # dO by qi
            row_spec,                                              # lse
            row_spec,                                              # delta
        ],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta)

    k_spec = pl.BlockSpec((1, bk, d), lambda bh, ki, qi: (bh, ki, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, q_offset=t_k - t_q),
        grid=(b * h, t_k // bk, t_q // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, ki, qi: (bh, qi, 0)),  # q
            k_spec,                                                    # k
            k_spec,                                                    # v
            pl.BlockSpec((1, bq, d), lambda bh, ki, qi: (bh, qi, 0)),  # dO
            pl.BlockSpec((1, bq, 1), lambda bh, ki, qi: (bh, qi, 0)),  # lse
            pl.BlockSpec((1, bq, 1), lambda bh, ki, qi: (bh, qi, 0)),  # delta
        ],
        out_specs=[k_spec, k_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_k, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta)
    return (
        dq.reshape(b, h, t_q, d),
        dk.reshape(b, h, t_k, d),
        dv.reshape(b, h, t_k, d),
    )


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def flash_attention_tpu(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = False, scale: Optional[float] = None,
    block_q: int = 128, block_k: int = 128, interpret: bool = False,
) -> jax.Array:
    """Pallas flash attention: MXU-tiled forward AND backward.  The
    backward is recompute-free — P is rebuilt from the forward's saved
    logsumexp, never materializing the full score matrix (the standard
    dq/dk/dv flash backward)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    out, _ = _flash_forward(
        q, k, v, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    out, lse = _flash_forward(
        q, k, v, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash_backward(
        q, k, v, out, lse, g, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )


flash_attention_tpu.defvjp(_flash_fwd, _flash_bwd)


def attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = False,
    scale: Optional[float] = None, block_q: int = 128, block_k: int = 128,
) -> jax.Array:
    """Dispatch to the fastest correct implementation for the shape.
    Single entry point used by the model zoo.

    - causal, square, block-divisible, moderate T → :func:`causal_skip_attention`
    - moderate T → :func:`full_attention` (masked, MXU dtypes)
    - T ≥ 8k on TPU, block-divisible → :func:`flash_attention_tpu`
      (pallas fwd + recompute-free bwd kernels; measured crossover on v5e)
    - other long T → :func:`blockwise_attention` (O(block) memory,
      pads+masks any length; ring attention covers sharded-T)
    """
    t_q, t_k = q.shape[-2], k.shape[-2]
    if t_q <= _MAX_MATERIALIZED_T and t_k <= _MAX_MATERIALIZED_T:
        if causal and t_q == t_k and t_q % 256 == 0 and t_q >= 512:
            return causal_skip_attention(q, k, v, scale=scale, block=256)
        return full_attention(q, k, v, causal=causal, scale=scale)
    if (
        _HAS_PALLAS
        and q.ndim == 4
        and t_k >= 8192  # measured crossover vs the XLA paths on v5e
        and t_q % block_q == 0
        and t_k % block_k == 0
        and jax.default_backend() == "tpu"
    ):
        # long context: the pallas kernel pair (fwd + recompute-free bwd)
        return flash_attention_tpu(
            q, k, v, causal, scale, block_q, block_k, False
        )
    return blockwise_attention(
        q, k, v, causal=causal, scale=scale, block_k=block_k
    )


def _scores(q, k, scale: float) -> jax.Array:
    """Q·Kᵀ in the input dtype with f32 accumulation (MXU-friendly)."""
    bdims = tuple(range(q.ndim - 2))
    return lax.dot_general(
        q, k, (((q.ndim - 1,), (k.ndim - 1,)), (bdims, bdims)),
        preferred_element_type=jnp.float32,
    ) * scale


def _weighted_values(p: jax.Array, v: jax.Array) -> jax.Array:
    """softmax(P)·V with P cast back to V's dtype for the MXU."""
    bdims = tuple(range(p.ndim - 2))
    return lax.dot_general(
        p.astype(v.dtype), v,
        (((p.ndim - 1,), (v.ndim - 2,)), (bdims, bdims)),
    )


def full_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Materialized-scores attention with MXU-friendly dtypes: inputs stay
    in their dtype (bf16 in the models), scores accumulate in f32
    (``preferred_element_type``), softmax in f32, P@V back in input dtype.

    Measured faster fwd+bwd on v5e at moderate T than our pallas kernel,
    jax's in-tree pallas flash, and f32 blockwise (XLA fuses the masked
    softmax; head_dim=64 tiles fine).
    """
    *_, t_q, d = q.shape
    t_k = k.shape[-2]
    scale = scale if scale is not None else d ** -0.5
    s = _scores(q, k, scale)
    if causal:
        mask = jnp.tril(jnp.ones((t_q, t_k), dtype=bool), t_k - t_q)
        s = jnp.where(mask, s, NEG_INF)
    return _weighted_values(jax.nn.softmax(s, axis=-1), v)


def causal_skip_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *,
    scale: Optional[float] = None, block: int = 256,
) -> jax.Array:
    """Causal attention that skips fully-masked key blocks: an unrolled
    loop over q blocks where block i only contracts keys ``[0:(i+1)*block]``
    — ~40% fewer FLOPs than masked full attention at T=1024, every matmul
    shape static so XLA tiles each branch onto the MXU.  Requires
    ``t_q == t_k`` divisible by ``block``.

    One dot + one full-width masked select per q block, deliberately: an
    A/B with separate unmasked-prefix/masked-diagonal dots measured ~7%
    SLOWER end-to-end (XLA fuses the select into the softmax for free, but
    two dots + concat fuse worse than one).  Measured ~2.5x faster fwd+bwd
    than both pallas flash kernels (ours and jax's in-tree) at GPT-2
    shapes on v5e — which is why this, not the pallas path, is the
    dispatcher's causal default.
    """
    *_, t, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    n = t // block
    outs = []
    for i in range(n):
        qi = lax.slice_in_dim(q, i * block, (i + 1) * block, axis=-2)
        kv_len = (i + 1) * block
        ki = lax.slice_in_dim(k, 0, kv_len, axis=-2)
        vi = lax.slice_in_dim(v, 0, kv_len, axis=-2)
        q_pos = i * block + jnp.arange(block)
        mask = q_pos[:, None] >= jnp.arange(kv_len)[None, :]
        s = jnp.where(mask, _scores(qi, ki, scale), NEG_INF)
        outs.append(_weighted_values(jax.nn.softmax(s, axis=-1), vi))
    return jnp.concatenate(outs, axis=-2)


# Above this, materialized scores risk HBM pressure; the O(block) blockwise
# path takes over.
_MAX_MATERIALIZED_T = 4096
