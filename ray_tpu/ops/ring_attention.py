"""Ring attention: sequence-parallel exact attention over the ``sp`` axis.

The long-context capability the reference lacks entirely (SURVEY §5.7 —
"Absent ... implement as a first-class capability"): each device holds a
sequence chunk of q/k/v; k/v rotate around the mesh-axis ring with
``lax.ppermute`` (ICI neighbour hops on TPU) while every device folds each
visiting chunk into its online-softmax accumulator.  Peak memory is
O(T/n_sp), compute overlaps communication across ring steps, and the
result is bitwise-equivalent math to full attention.

Must run inside ``shard_map`` with the sequence dimension sharded over
``axis_name``; :func:`ring_attention` is the per-device program.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops.attention import NEG_INF, _block_update


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, axis_name: str = "sp",
    causal: bool = False, scale: Optional[float] = None,
) -> jax.Array:
    """Per-device exact attention over a ring.  q/k/v: local ``[B,H,t,D]``
    chunks of the globally sharded ``[B,H,T,D]`` arrays (t = T / n_sp)."""
    *_, t, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)

    qf = q.astype(jnp.float32) * scale
    q_pos = my * t + jnp.arange(t)

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        # the chunk visiting us at step i originated on device (my - i) % n
        src = (my - i) % n
        s = jnp.einsum("...qd,...kd->...qk", qf, k_cur.astype(jnp.float32))
        if causal:
            k_pos = src * t + jnp.arange(t)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, NEG_INF)
        o, m, l = _block_update((o, m, l), s, v_cur)
        # rotate k/v to the next device (receive from the previous) — on a
        # TPU slice this is a neighbour hop on the ICI ring
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt), None

    o0 = jnp.zeros((*q.shape[:-1], d), jnp.float32)
    m0 = jnp.full(q.shape[:-1], NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1], jnp.float32)
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(n))
    # fully-masked rows (causal, first chunk) have l == 0
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l[..., None]).astype(q.dtype)


def ulysses_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, axis_name: str = "sp",
    causal: bool = False, scale: Optional[float] = None,
) -> jax.Array:
    """Ulysses-style sequence parallelism: all-to-all head<->sequence
    re-sharding so each device computes full-sequence attention for a
    subset of heads, then the inverse all-to-all.  Cheaper than the ring
    when heads % n_sp == 0 and the sequence fits after gathering.

    Local shapes: ``[B, H, t, D]`` in, same out.
    """
    b, h, t, d = q.shape
    n = lax.psum(1, axis_name)
    if h % n:
        raise ValueError(f"heads={h} not divisible by sp axis size {n}")

    def scatter_heads(x):
        # [B, H, t, D] -> [B, H/n, T, D]: shard heads, gather sequence
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def gather_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    from ray_tpu.ops.attention import blockwise_attention

    ql, kl, vl = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    # blockwise pads+masks non-dividing lengths internally
    out = blockwise_attention(ql, kl, vl, causal=causal, scale=scale, block_k=512)
    return gather_heads(out)
