"""TPU-native operator library (pallas kernels + jax ops).

The hot ops of the framework's compute path.  The reference has no kernel
library (it orchestrates torch/CUDA code it doesn't own); here the kernels
are first-class: flash attention (pallas, MXU-tiled), blockwise attention
(pure-jax online softmax, differentiable and rematerializable), ring
attention over the ``sp`` mesh axis for long-context (SURVEY §5.7), and
fused normalization/loss layers.
"""

from ray_tpu.ops.attention import (
    attention,
    blockwise_attention,
    causal_skip_attention,
    flash_attention_tpu,
    full_attention,
    mha_reference,
)
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.ops.moe import moe_ffn
from ray_tpu.ops.layers import (
    cross_entropy_loss,
    layernorm,
    rmsnorm,
    rope,
)

__all__ = [
    "moe_ffn",
    "attention",
    "blockwise_attention",
    "causal_skip_attention",
    "full_attention",
    "flash_attention_tpu",
    "mha_reference",
    "ring_attention",
    "rmsnorm",
    "layernorm",
    "rope",
    "cross_entropy_loss",
]
