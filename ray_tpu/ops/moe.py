"""Mixture-of-Experts FFN with expert parallelism over the ``ep`` axis.

The reference has no MoE/expert-parallel code (SURVEY §2.5 row EP:
"Absent"); this is the TPU-native build target — "expert-axis sharding +
``all_to_all`` over ICI".  Switch-Transformer-style top-1 routing with a
fixed per-expert capacity, expressed as dense dispatch/combine einsums
(the GShard formulation): expert weights carry an ``expert`` logical axis
mapped to the mesh's ``ep`` axis, the token batch is sharded over
dp/fsdp, and XLA lowers the ``[tokens] x [experts]`` dispatch einsum into
the ep-axis all_to_all/all_gather pair — collectives ride ICI, nothing is
hand-scheduled.

Shapes are static (capacity = ceil(cf * tokens / E)), so the whole thing
jits once; dropped tokens (over capacity) fall through the residual
connection, as in Switch.  The load-balance auxiliary loss is the Switch
eq. (4): ``E * sum_e f_e * P_e``, minimized at uniform routing.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _constrain(x: jax.Array, mesh: Optional[Mesh], spec: P) -> jax.Array:
    if mesh is None:
        return x
    try:
        if jax.typeof(x).vma:
            # inside a manual region (e.g. the pp pipeline's shard_map):
            # constraints on varying arrays are rejected; sharding still
            # propagates from the ep-sharded expert weights.
            return x
    except AttributeError:
        pass
    # drop axes the mesh doesn't have
    parts = tuple(a if (a in mesh.axis_names) else None for a in spec)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*parts)))


def moe_ffn(
    x: jax.Array,
    router_w: jax.Array,
    w1: jax.Array,
    b1: jax.Array,
    w2: jax.Array,
    b2: jax.Array,
    *,
    capacity_factor: float = 2.0,
    mesh: Optional[Mesh] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-1 (Switch) MoE feed-forward.

    Args:
        x: ``[B, T, D]`` activations (compute dtype).
        router_w: ``[D, E]`` router weights (kept f32 for stable softmax).
        w1, b1: ``[E, D, F]``, ``[E, F]`` expert up-projections.
        w2, b2: ``[E, F, D]``, ``[E, D]`` expert down-projections.
        capacity_factor: per-expert buffer = ``cf * tokens / E``.
        mesh: optional mesh; expert dims get an ``ep`` sharding constraint.

    Returns:
        ``(y, aux)`` — ``[B, T, D]`` output and the scalar load-balance
        loss (add ``aux_weight * aux`` to the training loss).
    """
    B, T, D = x.shape
    E = w1.shape[0]
    S = B * T
    C = max(1, math.ceil(capacity_factor * S / E))
    xf = x.reshape(S, D)

    logits = xf.astype(jnp.float32) @ router_w.astype(jnp.float32)  # [S, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate = probs.max(axis=-1)          # [S] top-1 gate value
    expert = probs.argmax(axis=-1)     # [S] chosen expert

    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)       # [S, E]
    # arrival order within each expert's queue; tokens past C are dropped
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot            # [S, E]
    pos_tok = pos.sum(axis=-1)                                   # [S]
    keep = (pos_tok < C).astype(jnp.float32)
    dispatch = onehot * keep[:, None]                            # [S, E]
    pos_onehot = jax.nn.one_hot(pos_tok.astype(jnp.int32), C, dtype=jnp.float32)
    disp = dispatch[..., None] * pos_onehot[:, None, :]          # [S, E, C]

    # dispatch: tokens -> per-expert buffers.  With x sharded over
    # dp/fsdp and the E dim constrained to ep this einsum IS the ep
    # all_to_all (XLA inserts it under GSPMD).
    expert_in = jnp.einsum("sec,sd->ecd", disp.astype(x.dtype), xf)
    expert_in = _constrain(expert_in, mesh, P("ep", None, None))

    h = jnp.einsum("ecd,edf->ecf", expert_in, w1) + b1[:, None, :]
    h = jax.nn.gelu(h, approximate=True)
    out = jnp.einsum("ecf,efd->ecd", h, w2) + b2[:, None, :]
    out = _constrain(out, mesh, P("ep", None, None))

    # combine: per-expert buffers -> tokens, weighted by the gate (the
    # gate factor keeps the router differentiable — Switch eq. 2)
    combine = disp * (gate * keep)[:, None, None]                # [S, E, C]
    y = jnp.einsum("sec,ecd->sd", combine.astype(out.dtype), out)

    # Switch load-balance loss: E * sum_e (token fraction)_e * (prob mass)_e
    f = onehot.mean(axis=0)
    Pm = probs.mean(axis=0)
    aux = E * jnp.sum(f * Pm)
    return y.reshape(B, T, D).astype(x.dtype), aux


def init_moe_params(
    key: jax.Array, n_layers: int, d_model: int, d_ff: int, n_experts: int,
    *, std: float = 0.02, res_std: Optional[float] = None,
) -> Dict[str, jax.Array]:
    """Layer-stacked expert params ``[L, E, ...]`` (router kept f32)."""
    L, D, F, E = n_layers, d_model, d_ff, n_experts
    res_std = res_std if res_std is not None else std / (2 * L) ** 0.5
    kr, k1, k2 = jax.random.split(key, 3)
    return {
        "router": jax.random.normal(kr, (L, D, E)) * std,
        "ew1": jax.random.normal(k1, (L, E, D, F)) * std,
        "eb1": jnp.zeros((L, E, F)),
        "ew2": jax.random.normal(k2, (L, E, F, D)) * res_std,
        "eb2": jnp.zeros((L, E, D)),
    }


def moe_logical_axes() -> Dict[str, Tuple]:
    """Logical axes for :func:`init_moe_params` (expert -> ep)."""
    return {
        "router": ("layers", "embed", None),
        "ew1": ("layers", "expert", "embed", "mlp"),
        "eb1": ("layers", "expert", "mlp"),
        "ew2": ("layers", "expert", "mlp", "embed"),
        "eb2": ("layers", "expert", "embed"),
    }
