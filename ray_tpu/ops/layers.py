"""Fused layer ops: norms, rotary embeddings, losses.

Plain jnp compositions written so XLA fuses them into neighbouring matmuls
(f32 accumulation, bf16 storage) — per the guide, hand-scheduling what the
compiler already fuses is an anti-pattern, so pallas is reserved for the
attention inner loop.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def rmsnorm(x: jax.Array, weight: jax.Array, *, eps: float = 1e-6) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight


def layernorm(
    x: jax.Array, weight: jax.Array, bias: Optional[jax.Array] = None,
    *, eps: float = 1e-5,
) -> jax.Array:
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps)
    out = out.astype(x.dtype) * weight
    if bias is not None:
        out = out + bias
    return out


def rope(
    x: jax.Array, positions: jax.Array, *, base: float = 10000.0,
) -> jax.Array:
    """Rotary position embedding. x: [..., T, D] with D even.

    positions: [T] (shared across batch — training) or [B, T] (per-sequence
    absolute positions — KV-cache decode, where each slot sits at its own
    offset).  x is [B, H, T, D] in the batched case."""
    d = x.shape[-1]
    inv_freq = 1.0 / (base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    if positions.ndim == 2:  # [B, T] -> angles [B, 1, T, D/2]
        angles = positions.astype(jnp.float32)[:, None, :, None] * inv_freq
    else:
        angles = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    out = jnp.stack([out1, out2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def cross_entropy_loss(
    logits: jax.Array, labels: jax.Array, *, ignore_index: int = -100,
    z_loss: float = 0.0,
) -> jax.Array:
    """Token-level cross entropy with optional z-loss (logit drift control).

    logits: [..., V] (any dtype; reduced in f32), labels: [...] int.
    """
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    label_logit = jnp.take_along_axis(
        logits, jnp.maximum(labels, 0)[..., None], axis=-1
    )[..., 0]
    nll = lse - label_logit
    if z_loss:
        nll = nll + z_loss * lse**2
    valid = labels != ignore_index
    nll = jnp.where(valid, nll, 0.0)
    return nll.sum() / jnp.maximum(valid.sum(), 1)
