"""Public exception types (analog of ``python/ray/exceptions.py``)."""

from __future__ import annotations


class RayError(Exception):
    """Base class for all framework errors."""


class RayTaskError(RayError):
    """Wraps an exception raised by user code in a task/actor method.

    Like the reference's RayTaskError, it is stored as the task's return
    object and re-raised on ``get`` with the remote traceback in the message.
    """

    def __init__(self, message: str, cause: BaseException | None = None):
        super().__init__(message)
        self.cause = cause


class RayActorError(RayError):
    """The actor died before or while executing the method."""


class WorkerCrashedError(RayError):
    """The worker process executing the task died unexpectedly."""


class GetTimeoutError(RayError, TimeoutError):
    """``get`` exceeded its timeout."""


class ObjectLostError(RayError):
    """The object's value was lost and could not be recovered."""


class ActorDiedError(RayActorError):
    pass


class TaskCancelledError(RayError):
    """The task was cancelled via ``ray_tpu.cancel`` (reference
    ``python/ray/exceptions.py`` TaskCancelledError; cancel path
    ``python/ray/_private/worker.py:2573``).  Raised by ``get`` on the
    cancelled task's returns."""
