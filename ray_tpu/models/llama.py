"""Llama-family decoder LM: RMSNorm + RoPE + SwiGLU + grouped-query
attention, pure jax.

Same design rules as :mod:`ray_tpu.models.gpt2` (the reference delegates
model parallelism to torch; here sharding annotations ARE the
parallelism): stacked ``[L, ...]`` block params scanned with one remat'd
body, bf16 compute over f32 master weights, logical axes feeding
:mod:`ray_tpu.parallel.sharding` (heads/mlp → tp, embed → fsdp, sequence →
sp ring attention when the mesh has an ``sp`` axis).  GQA shares each KV
head across ``n_heads // n_kv_heads`` query heads — the standard
long-context memory saver (KV cache and KV projections shrink by that
factor).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.models.gpt2 import make_optimizer  # same AdamW recipe
from ray_tpu.models.transformer import make_train_step_from_loss
from ray_tpu.ops.layers import cross_entropy_loss, rmsnorm, rope
from ray_tpu.parallel.sharding import ShardingRules, logical_to_sharding

__all__ = [
    "LlamaConfig", "init", "apply", "loss_fn", "make_train_step",
    "init_state", "num_params", "logical_axes", "param_shardings",
    "make_optimizer",
]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32_000
    n_layers: int = 12
    n_heads: int = 12
    n_kv_heads: int = 4
    d_model: int = 768
    d_ff: int = 2048
    max_seq_len: int = 2048
    rope_base: float = 10_000.0
    rms_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # "dots" saves matmul outputs and recomputes elementwise (measured
    # +3-6% over full remat at these shapes on v5e — same policy the
    # shared transformer core uses); "full" recomputes everything
    remat_policy: str = "dots"

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def q_per_kv(self) -> int:
        assert self.n_heads % self.n_kv_heads == 0
        return self.n_heads // self.n_kv_heads

    @staticmethod
    def llama_125m(**kw) -> "LlamaConfig":
        return LlamaConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        base = dict(vocab_size=512, n_layers=2, n_heads=4, n_kv_heads=2,
                    d_model=64, d_ff=128, max_seq_len=128, remat=False)
        base.update(kw)
        return LlamaConfig(**base)


def _dense(key, n_in, n_out, scale=1.0):
    return jax.random.normal(key, (n_in, n_out)) * scale / jnp.sqrt(n_in)


def init(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Stacked block params: every leaf carries a leading [L] axis."""
    k_emb, k_blocks = jax.random.split(key)
    L, D, H, KV, hd, F = (cfg.n_layers, cfg.d_model, cfg.n_heads,
                          cfg.n_kv_heads, cfg.head_dim, cfg.d_ff)
    ks = jax.random.split(k_blocks, 7)

    def stack(k, *shape, scale=1.0):
        keys = jax.random.split(k, L)
        return jnp.stack([_dense(kk, *shape, scale=scale) for kk in keys])

    blocks = {
        "wq": stack(ks[0], D, H * hd),
        "wk": stack(ks[1], D, KV * hd),
        "wv": stack(ks[2], D, KV * hd),
        "wo": stack(ks[3], H * hd, D, scale=0.02),
        # SwiGLU: gate + up fused side by side, then down
        "w_gate": stack(ks[4], D, F),
        "w_up": stack(ks[5], D, F),
        "w_down": stack(ks[6], F, D, scale=0.02),
        "attn_norm": jnp.ones((L, D)),
        "ffn_norm": jnp.ones((L, D)),
    }
    return {
        "tok_emb": jax.random.normal(k_emb, (cfg.vocab_size, D)) * 0.02,
        "blocks": blocks,
        "final_norm": jnp.ones(D),
    }


def logical_axes(cfg: Optional[LlamaConfig] = None) -> Dict[str, Any]:
    return {
        "tok_emb": ("vocab", "embed"),
        "blocks": {
            "wq": (None, "embed", "heads"),
            "wk": (None, "embed", "heads"),
            "wv": (None, "embed", "heads"),
            "wo": (None, "heads", "embed"),
            "w_gate": (None, "embed", "mlp"),
            "w_up": (None, "embed", "mlp"),
            "w_down": (None, "mlp", "embed"),
            "attn_norm": (None, "embed"),
            "ffn_norm": (None, "embed"),
        },
        "final_norm": ("embed",),
    }


def param_shardings(mesh: Mesh, rules: ShardingRules, cfg: Optional[LlamaConfig] = None):
    return logical_to_sharding(logical_axes(cfg), mesh, rules)


def _block(x, p, cfg: LlamaConfig, mesh: Optional[Mesh], positions):
    B, T, D = x.shape
    H, KV, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    dt = cfg.dtype

    h = rmsnorm(x, p["attn_norm"].astype(dt), eps=cfg.rms_eps)
    q = (h @ p["wq"].astype(dt)).reshape(B, T, H, hd)
    k = (h @ p["wk"].astype(dt)).reshape(B, T, KV, hd)
    v = (h @ p["wv"].astype(dt)).reshape(B, T, KV, hd)
    q = rope(q.transpose(0, 2, 1, 3), positions, base=cfg.rope_base)  # [B,H,T,hd]
    k = rope(k.transpose(0, 2, 1, 3), positions, base=cfg.rope_base)  # [B,KV,T,hd]
    v = v.transpose(0, 2, 1, 3)
    # GQA: each KV head serves q_per_kv query heads
    if KV != H:
        k = jnp.repeat(k, cfg.q_per_kv, axis=1)
        v = jnp.repeat(v, cfg.q_per_kv, axis=1)
    # the shared transformer-core seam shard_maps ring attention when the
    # mesh has sp > 1
    from ray_tpu.models.transformer import _attend

    o = _attend(q, k, v, causal=True, mesh=mesh)  # [B, H, T, hd]
    o = o.transpose(0, 2, 1, 3).reshape(B, T, H * hd)
    x = x + o @ p["wo"].astype(dt)

    h = rmsnorm(x, p["ffn_norm"].astype(dt), eps=cfg.rms_eps)
    gated = jax.nn.silu(h @ p["w_gate"].astype(dt)) * (h @ p["w_up"].astype(dt))
    return x + gated @ p["w_down"].astype(dt)


def apply(params: Dict[str, Any], tokens: jax.Array, cfg: LlamaConfig,
          mesh: Optional[Mesh] = None) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, V] f32 (tied embeddings)."""
    B, T = tokens.shape
    x = params["tok_emb"][tokens].astype(cfg.dtype)
    positions = jnp.arange(T)

    def body(h, layer_params):
        return _block(h, layer_params, cfg, mesh, positions), None

    if cfg.remat:
        if cfg.remat_policy == "dots":
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            )
        elif cfg.remat_policy == "full":
            body = jax.checkpoint(body)
        else:
            raise ValueError(
                f"unknown remat_policy {cfg.remat_policy!r} (use 'dots' or 'full')"
            )
    x, _ = jax.lax.scan(body, x, params["blocks"])
    x = rmsnorm(x, params["final_norm"].astype(cfg.dtype), eps=cfg.rms_eps)
    return (x @ params["tok_emb"].T.astype(cfg.dtype)).astype(jnp.float32)


def loss_fn(params, batch, cfg: LlamaConfig, mesh: Optional[Mesh] = None):
    if "tokens" in batch:
        inputs, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    return cross_entropy_loss(apply(params, inputs, cfg, mesh), targets)


def make_train_step(cfg: LlamaConfig, optimizer, mesh: Optional[Mesh] = None):
    return make_train_step_from_loss(loss_fn, cfg, optimizer, mesh)


def init_state(cfg: LlamaConfig, key: jax.Array, optimizer) -> Dict[str, Any]:
    params = init(cfg, key)
    return {"params": params, "opt_state": optimizer.init(params),
            "step": jnp.zeros((), jnp.int32)}


def num_params(params: Dict[str, Any]) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
