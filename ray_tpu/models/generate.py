"""KV-cache autoregressive generation for the decoder LMs (GPT-2, Llama).

The reference snapshot has no inference engine at all — serving wraps a
plain forward (``python/ray/serve/_private/replica.py:250`` calls the user
callable); generation/KV-cache is delegated to user code.  Here decode is a
first-class TPU path, designed for XLA:

- **Static shapes everywhere**: the cache is a fixed ``[L, B, KV, S, dh]``
  buffer; positions are dynamic *values*, never dynamic shapes, so the
  decode step compiles once and runs for every token.
- **Layer-stacked cache + ``lax.scan``**: the per-layer cache rides the
  same scan as the stacked block params — one compiled block body.
- **Per-slot positions**: each batch slot sits at its own offset (``pos``
  vector), which is what iteration-level continuous batching needs
  (Orca-style; see :mod:`ray_tpu.serve.llm`).
- **Chunked decode**: ``decode_chunk`` runs N decode+sample steps inside
  one device computation (``lax.scan``) so the host syncs once per chunk,
  not per token — host<->device latency is the decode killer on a
  tunneled chip.

Cache writes land at each slot's current position via a vmapped
``dynamic_update_slice``; finished/idle slots simply keep writing at their
frozen position, which is harmless because a slot's attention mask never
reaches an index its own ``pos`` hasn't covered and prefill overwrites
``[0, len)`` when a slot is reused.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.gpt2 import GPT2Config
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.ops.layers import layernorm, rmsnorm, rope


def family_of(cfg) -> str:
    if isinstance(cfg, LlamaConfig):
        return "llama"
    if isinstance(cfg, GPT2Config):
        return "gpt2"
    raise TypeError(f"no generation support for config {type(cfg).__name__}")


def kv_heads(cfg) -> int:
    return cfg.n_kv_heads if isinstance(cfg, LlamaConfig) else cfg.n_heads


def init_cache(cfg, n_slots: int, max_len: int) -> Dict[str, jax.Array]:
    """Fixed-size KV cache: k/v ``[L, B, KV, S, dh]`` plus per-slot ``pos``."""
    shape = (cfg.n_layers, n_slots, kv_heads(cfg), max_len, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "pos": jnp.zeros((n_slots,), jnp.int32),
    }


def _write_kv(cache_l: jax.Array, new: jax.Array, pos: jax.Array) -> jax.Array:
    """Write ``new [B, KV, T, dh]`` into ``cache_l [B, KV, S, dh]`` at each
    slot's ``pos [B]`` (vmapped dynamic_update_slice -> one scatter)."""

    def upd(c, n, p):
        return lax.dynamic_update_slice(c, n.astype(c.dtype), (0, p, 0))

    return jax.vmap(upd)(cache_l, new, pos)


def _decode_attend(q, k_cache, v_cache, pos) -> jax.Array:
    """q ``[B, H, 1, dh]`` against the full cache ``[B, KV, S, dh]`` with a
    per-slot length mask ``j <= pos``.  GQA folds the query heads onto
    their KV head by reshape (no materialized repeat)."""
    B, H, _, dh = q.shape
    KV = k_cache.shape[1]
    S = k_cache.shape[2]
    q = q.reshape(B, KV, H // KV, dh)
    # keep the cache reads in bf16 (f32 accumulation via
    # preferred_element_type) — upcasting the whole cache each step would
    # double the dominant HBM traffic of decode
    scores = jnp.einsum(
        "bkgd,bksd->bkgs", q, k_cache.astype(q.dtype),
        preferred_element_type=jnp.float32,
    ) / (dh ** 0.5)
    mask = jnp.arange(S)[None, None, None, :] <= pos[:, None, None, None]
    scores = jnp.where(mask, scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgs,bksd->bkgd", w.astype(v_cache.dtype), v_cache,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, H, 1, dh)


# ---------------------------------------------------------------------------
# per-family block math (prefill captures K/V; decode reads the cache)
# ---------------------------------------------------------------------------

def _gpt2_block(x, p, cfg: GPT2Config, *, cache_kv=None, pos=None):
    """One GPT-2 block.  Prefill mode (cache_kv None): full causal self-
    attention over ``x [B, T, D]``, returns ``(x, (k, v))``.  Decode mode:
    ``x [B, 1, D]`` attends over the cache, returns ``(x, (k_cache,
    v_cache))`` with the new K/V written at ``pos``."""
    B, T, D = x.shape
    H, dh = cfg.n_heads, cfg.head_dim
    c = lambda w: w.astype(cfg.dtype)

    h = layernorm(x, c(p["ln1_w"]), c(p["ln1_b"]))
    qkv = h @ c(p["wqkv"]) + c(p["bqkv"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    to_heads = lambda t: t.reshape(B, T, H, dh).transpose(0, 2, 1, 3)
    q, k, v = to_heads(q), to_heads(k), to_heads(v)
    if cache_kv is None:
        from ray_tpu.ops.attention import attention

        out = attention(q, k, v, causal=True)
        saved = (k, v)
    else:
        k_cache = _write_kv(cache_kv[0], k, pos)
        v_cache = _write_kv(cache_kv[1], v, pos)
        out = _decode_attend(q, k_cache, v_cache, pos)
        saved = (k_cache, v_cache)
    out = out.transpose(0, 2, 1, 3).reshape(B, T, D).astype(cfg.dtype)
    x = x + out @ c(p["wo"]) + c(p["bo"])
    h = layernorm(x, c(p["ln2_w"]), c(p["ln2_b"]))
    h = jax.nn.gelu(h @ c(p["w1"]) + c(p["b1"]), approximate=True)
    x = x + h @ c(p["w2"]) + c(p["b2"])
    return x, saved


def _llama_block(x, p, cfg: LlamaConfig, positions, *, cache_kv=None, pos=None):
    """One Llama block (RMSNorm/RoPE/GQA/SwiGLU); same two modes as
    :func:`_gpt2_block`.  The cache stores post-RoPE keys in the KV-head
    layout (``n_kv_heads`` rows — the GQA memory saving)."""
    B, T, D = x.shape
    H, KV, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    dt = cfg.dtype

    h = rmsnorm(x, p["attn_norm"].astype(dt), eps=cfg.rms_eps)
    q = (h @ p["wq"].astype(dt)).reshape(B, T, H, dh).transpose(0, 2, 1, 3)
    k = (h @ p["wk"].astype(dt)).reshape(B, T, KV, dh).transpose(0, 2, 1, 3)
    v = (h @ p["wv"].astype(dt)).reshape(B, T, KV, dh).transpose(0, 2, 1, 3)
    q = rope(q, positions, base=cfg.rope_base)
    k = rope(k, positions, base=cfg.rope_base)
    if cache_kv is None:
        kr = jnp.repeat(k, cfg.q_per_kv, axis=1)
        vr = jnp.repeat(v, cfg.q_per_kv, axis=1)
        from ray_tpu.ops.attention import attention

        out = attention(q, kr, vr, causal=True)
        saved = (k, v)
    else:
        k_cache = _write_kv(cache_kv[0], k, pos)
        v_cache = _write_kv(cache_kv[1], v, pos)
        out = _decode_attend(q, k_cache, v_cache, pos)
        saved = (k_cache, v_cache)
    out = out.transpose(0, 2, 1, 3).reshape(B, T, H * dh).astype(dt)
    x = x + out @ p["wo"].astype(dt)
    h = rmsnorm(x, p["ffn_norm"].astype(dt), eps=cfg.rms_eps)
    gated = jax.nn.silu(h @ p["w_gate"].astype(dt)) * (h @ p["w_up"].astype(dt))
    return x + gated @ p["w_down"].astype(dt), saved


# ---------------------------------------------------------------------------
# prefill / decode over the stacked layers
# ---------------------------------------------------------------------------

def _embed(params, tokens, cfg, positions):
    if family_of(cfg) == "gpt2":
        x = params["wte"][tokens] + jnp.take(params["wpe"], positions, axis=0)
    else:
        x = params["tok_emb"][tokens]
    return x.astype(cfg.dtype)


def _unembed(params, x, cfg):
    if family_of(cfg) == "gpt2":
        x = layernorm(x, params["lnf_w"].astype(cfg.dtype),
                      params["lnf_b"].astype(cfg.dtype))
        w = params["wte"]
    else:
        x = rmsnorm(x, params["final_norm"].astype(cfg.dtype), eps=cfg.rms_eps)
        w = params["tok_emb"]
    return (x @ w.T.astype(cfg.dtype)).astype(jnp.float32)


def prefill_at(params, cfg, tokens: jax.Array, lengths: jax.Array,
               cache: Dict[str, jax.Array], slots: jax.Array) -> Tuple[jax.Array, Dict]:
    """Run the prompts ``tokens [B, Tp]`` (right-padded; true lengths
    ``lengths [B]``) and write K/V into cache slots ``slots [B]`` (any
    subset — one compiled program admits a whole batch of requests, which
    matters when each device dispatch pays tunnel latency).  Returns
    ``(last_logits [B, V], cache)``.  Positions are 0..Tp-1, so a slot must
    be prefilled from scratch (pos resets to ``lengths``)."""
    fam = family_of(cfg)
    B, Tp = tokens.shape
    positions = jnp.arange(Tp)
    x = _embed(params, tokens, cfg, positions)

    if fam == "gpt2":
        def body(h, p):
            h, kv = _gpt2_block(h, p, cfg)
            return h, kv
    else:
        def body(h, p):
            h, kv = _llama_block(h, p, cfg, positions)
            return h, kv

    x, (ks, vs) = lax.scan(body, x, params["blocks"])  # ks [L, B, KV, Tp, dh]
    # single advanced index keeps its axis position: one scatter per tensor
    cache_k = cache["k"].at[:, slots, :, :Tp, :].set(ks.astype(cache["k"].dtype))
    cache_v = cache["v"].at[:, slots, :, :Tp, :].set(vs.astype(cache["v"].dtype))
    pos = cache["pos"].at[slots].set(lengths.astype(jnp.int32))
    last = _unembed(params, jnp.take_along_axis(
        x, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1), cfg)
    return last[:, 0, :], {"k": cache_k, "v": cache_v, "pos": pos}


def prefill(params, cfg, tokens: jax.Array, lengths: jax.Array,
            cache: Dict[str, jax.Array], slot: jax.Array) -> Tuple[jax.Array, Dict]:
    """:func:`prefill_at` with contiguous slots ``slot + [0..B)``."""
    B = tokens.shape[0]
    return prefill_at(params, cfg, tokens, lengths, cache,
                      slot + jnp.arange(B, dtype=jnp.int32))


def decode_step(params, cfg, cache: Dict[str, jax.Array], tokens: jax.Array,
                active: jax.Array) -> Tuple[jax.Array, Dict]:
    """One token for every slot.  ``tokens [B]`` are each slot's last
    emitted token, written at ``pos`` then attended; ``active [B]`` bool
    gates the position advance.  Returns ``(logits [B, V], cache)``."""
    fam = family_of(cfg)
    pos = cache["pos"]
    x = _embed(params, tokens[:, None], cfg, pos[:, None])  # [B, 1, D]

    if fam == "gpt2":
        def body(h, xs):
            p, k_l, v_l = xs
            h, (k_l, v_l) = _gpt2_block(h, p, cfg, cache_kv=(k_l, v_l), pos=pos)
            return h, (k_l, v_l)
    else:
        positions = pos[:, None]  # [B, 1] per-slot rope offsets
        def body(h, xs):
            p, k_l, v_l = xs
            h, (k_l, v_l) = _llama_block(
                h, p, cfg, positions, cache_kv=(k_l, v_l), pos=pos)
            return h, (k_l, v_l)

    x, (ks, vs) = lax.scan(body, x, (params["blocks"], cache["k"], cache["v"]))
    logits = _unembed(params, x, cfg)[:, 0, :]
    return logits, {
        "k": ks, "v": vs,
        "pos": pos + active.astype(jnp.int32),
    }


def sample_logits(logits: jax.Array, key: jax.Array, *, temperature: float = 0.0,
                  top_k: int = 0) -> jax.Array:
    """Greedy (temperature 0) or temperature/top-k categorical sampling."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def decode_chunk(params, cfg, cache, tokens, active, key, *, steps: int,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_id: Optional[int] = None):
    """Run ``steps`` decode+sample iterations in one device computation.
    Returns ``(emitted [B, steps], cache, active, key)``.  A slot that
    emits ``eos_id`` flips inactive mid-chunk (its pos freezes)."""

    def step(carry, _):
        cache, toks, act, k = carry
        k, sub = jax.random.split(k)
        logits, cache = decode_step(params, cfg, cache, toks, act)
        nxt = sample_logits(logits, sub, temperature=temperature, top_k=top_k)
        nxt = jnp.where(act, nxt, toks)
        if eos_id is not None:
            act = act & (nxt != eos_id)
        return (cache, nxt, act, k), nxt

    (cache, _, active, key), emitted = lax.scan(
        step, (cache, tokens, active, key), None, length=steps)
    return emitted.T, cache, active, key  # [B, steps]


def generate(params, cfg, prompts: jax.Array, lengths: jax.Array, *,
             max_new_tokens: int, key: Optional[jax.Array] = None,
             temperature: float = 0.0, top_k: int = 0,
             eos_id: Optional[int] = None) -> jax.Array:
    """One-shot batched generation (prefill + fused decode loop).  Returns
    ``[B, max_new_tokens]`` generated tokens (post-EOS positions repeat the
    EOS token).  For the serving path use :mod:`ray_tpu.serve.llm`, which
    runs the same kernels under iteration-level continuous batching."""
    B, Tp = prompts.shape
    if key is None:
        key = jax.random.PRNGKey(0)
    cache = init_cache(cfg, B, Tp + max_new_tokens)
    last_logits, cache = prefill(
        params, cfg, prompts, lengths, cache, jnp.int32(0))
    key, sub = jax.random.split(key)
    first = sample_logits(last_logits, sub, temperature=temperature, top_k=top_k)
    active = jnp.ones((B,), bool)
    if eos_id is not None:
        active = active & (first != eos_id)
    rest, _, _, _ = decode_chunk(
        params, cfg, cache, first, active, key,
        steps=max_new_tokens - 1, temperature=temperature, top_k=top_k,
        eos_id=eos_id)
    return jnp.concatenate([first[:, None], rest], axis=1)
