"""KV-cache autoregressive generation for the decoder LMs (GPT-2, Llama).

The reference snapshot has no inference engine at all — serving wraps a
plain forward (``python/ray/serve/_private/replica.py:250`` calls the user
callable); generation/KV-cache is delegated to user code.  Here decode is a
first-class TPU path, designed for XLA:

- **Static shapes everywhere**: the cache is a fixed ``[L, B, KV, S, dh]``
  buffer; positions are dynamic *values*, never dynamic shapes, so the
  decode step compiles once and runs for every token.
- **In-place cache**: the decode layer loop is a ``fori_loop`` carrying
  the full cache; each layer writes only its new K/V column with one
  scatter, and XLA's while-loop buffer aliasing keeps the cache in place
  (a scan that re-emits the cache per step measured ~1.3 ms/step of pure
  rewrite traffic at GPT-2 125M on v5e).
- **Per-slot positions**: each batch slot sits at its own offset (``pos``
  vector), which is what iteration-level continuous batching needs
  (Orca-style; see :mod:`ray_tpu.serve.llm`).
- **Chunked decode**: ``decode_chunk`` runs N decode+sample steps inside
  one device computation (``lax.scan``) so the host syncs once per chunk,
  not per token — host<->device latency is the decode killer on a
  tunneled chip.

Cache columns of finished/idle slots keep being written at their frozen
position, which is harmless: a slot's attention mask never reaches an
index its own ``pos`` hasn't covered, and prefill overwrites ``[0, len)``
when a slot is reused.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.gpt2 import GPT2Config
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.ops.layers import layernorm, rmsnorm, rope


def family_of(cfg) -> str:
    if isinstance(cfg, LlamaConfig):
        return "llama"
    if isinstance(cfg, GPT2Config):
        return "gpt2"
    raise TypeError(f"no generation support for config {type(cfg).__name__}")


def kv_heads(cfg) -> int:
    return cfg.n_kv_heads if isinstance(cfg, LlamaConfig) else cfg.n_heads


def init_cache(cfg, n_slots: int, max_len: int) -> Dict[str, jax.Array]:
    """Fixed-size KV cache: k/v ``[L, B, KV, S, dh]`` plus per-slot ``pos``."""
    shape = (cfg.n_layers, n_slots, kv_heads(cfg), max_len, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "pos": jnp.zeros((n_slots,), jnp.int32),
    }


def _decode_attend(q, k_cache, v_cache, pos) -> jax.Array:
    """q ``[B, H, 1, dh]`` against the full cache ``[B, KV, S, dh]`` with a
    per-slot length mask ``j <= pos``.  GQA folds the query heads onto
    their KV head by reshape (no materialized repeat)."""
    B, H, _, dh = q.shape
    KV = k_cache.shape[1]
    S = k_cache.shape[2]
    q = q.reshape(B, KV, H // KV, dh)
    # keep the cache reads in bf16 (f32 accumulation via
    # preferred_element_type) — upcasting the whole cache each step would
    # double the dominant HBM traffic of decode
    scores = jnp.einsum(
        "bkgd,bksd->bkgs", q, k_cache.astype(q.dtype),
        preferred_element_type=jnp.float32,
    ) / (dh ** 0.5)
    mask = jnp.arange(S)[None, None, None, :] <= pos[:, None, None, None]
    scores = jnp.where(mask, scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgs,bksd->bkgd", w.astype(v_cache.dtype), v_cache,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, H, 1, dh)


# ---------------------------------------------------------------------------
# per-family block math — ONE implementation serves prefill and decode:
# _qkv projects (post-rope, [B, heads, T, dh]), _post_attn applies the
# output projection + FFN residuals; only the attention middle differs
# (full causal for prefill, cache-masked for decode)
# ---------------------------------------------------------------------------

def _gpt2_qkv(x, p, cfg: GPT2Config):
    """x [B, T, D] -> q, k, v [B, H, T, dh]."""
    B, T, _ = x.shape
    H, dh = cfg.n_heads, cfg.head_dim
    c = lambda w: w.astype(cfg.dtype)
    h = layernorm(x, c(p["ln1_w"]), c(p["ln1_b"]))
    qkv = h @ c(p["wqkv"]) + c(p["bqkv"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    to_heads = lambda t: t.reshape(B, T, H, dh).transpose(0, 2, 1, 3)
    return to_heads(q), to_heads(k), to_heads(v)


def _gpt2_post_attn(x, out, p, cfg: GPT2Config):
    """out [B, T, D] (attention result, head-merged) -> next x."""
    c = lambda w: w.astype(cfg.dtype)
    x = x + out @ c(p["wo"]) + c(p["bo"])
    h = layernorm(x, c(p["ln2_w"]), c(p["ln2_b"]))
    h = jax.nn.gelu(h @ c(p["w1"]) + c(p["b1"]), approximate=True)
    return x + h @ c(p["w2"]) + c(p["b2"])


def _llama_qkv(x, p, cfg: LlamaConfig, positions):
    """x [B, T, D] -> post-rope q [B, H, T, dh], k/v [B, KV, T, dh] (the
    GQA KV-head layout the cache stores)."""
    B, T, _ = x.shape
    H, KV, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    dt = cfg.dtype
    h = rmsnorm(x, p["attn_norm"].astype(dt), eps=cfg.rms_eps)
    q = (h @ p["wq"].astype(dt)).reshape(B, T, H, dh).transpose(0, 2, 1, 3)
    k = (h @ p["wk"].astype(dt)).reshape(B, T, KV, dh).transpose(0, 2, 1, 3)
    v = (h @ p["wv"].astype(dt)).reshape(B, T, KV, dh).transpose(0, 2, 1, 3)
    return (rope(q, positions, base=cfg.rope_base),
            rope(k, positions, base=cfg.rope_base), v)


def _llama_post_attn(x, out, p, cfg: LlamaConfig):
    dt = cfg.dtype
    x = x + out @ p["wo"].astype(dt)
    h = rmsnorm(x, p["ffn_norm"].astype(dt), eps=cfg.rms_eps)
    gated = jax.nn.silu(h @ p["w_gate"].astype(dt)) * (h @ p["w_up"].astype(dt))
    return x + gated @ p["w_down"].astype(dt)


def _gpt2_block(x, p, cfg: GPT2Config):
    """One GPT-2 prefill block: full causal self-attention over
    ``x [B, T, D]``; returns ``(x, (k, v))`` for the cache."""
    B, T, D = x.shape
    q, k, v = _gpt2_qkv(x, p, cfg)
    from ray_tpu.ops.attention import attention

    out = attention(q, k, v, causal=True)
    out = out.transpose(0, 2, 1, 3).reshape(B, T, D).astype(cfg.dtype)
    return _gpt2_post_attn(x, out, p, cfg), (k, v)


def _llama_block(x, p, cfg: LlamaConfig, positions):
    """One Llama prefill block (RMSNorm/RoPE/GQA/SwiGLU); the cache stores
    post-RoPE keys in the KV-head layout (the GQA memory saving)."""
    B, T, _ = x.shape
    H, dh = cfg.n_heads, cfg.head_dim
    q, k, v = _llama_qkv(x, p, cfg, positions)
    kr = jnp.repeat(k, cfg.q_per_kv, axis=1)
    vr = jnp.repeat(v, cfg.q_per_kv, axis=1)
    from ray_tpu.ops.attention import attention

    out = attention(q, kr, vr, causal=True)
    out = out.transpose(0, 2, 1, 3).reshape(B, T, H * dh).astype(cfg.dtype)
    return _llama_post_attn(x, out, p, cfg), (k, v)


# ---------------------------------------------------------------------------
# prefill / decode over the stacked layers
# ---------------------------------------------------------------------------

def _embed(params, tokens, cfg, positions):
    if family_of(cfg) == "gpt2":
        x = params["wte"][tokens] + jnp.take(params["wpe"], positions, axis=0)
    else:
        x = params["tok_emb"][tokens]
    return x.astype(cfg.dtype)


def _unembed(params, x, cfg):
    if family_of(cfg) == "gpt2":
        x = layernorm(x, params["lnf_w"].astype(cfg.dtype),
                      params["lnf_b"].astype(cfg.dtype))
        w = params["wte"]
    else:
        x = rmsnorm(x, params["final_norm"].astype(cfg.dtype), eps=cfg.rms_eps)
        w = params["tok_emb"]
    return (x @ w.T.astype(cfg.dtype)).astype(jnp.float32)


def prefill_at(params, cfg, tokens: jax.Array, lengths: jax.Array,
               cache: Dict[str, jax.Array], slots: jax.Array) -> Tuple[jax.Array, Dict]:
    """Run the prompts ``tokens [B, Tp]`` (right-padded; true lengths
    ``lengths [B]``) and write K/V into cache slots ``slots [B]`` (any
    subset — one compiled program admits a whole batch of requests, which
    matters when each device dispatch pays tunnel latency).  Returns
    ``(last_logits [B, V], cache)``.  Positions are 0..Tp-1, so a slot must
    be prefilled from scratch (pos resets to ``lengths``)."""
    fam = family_of(cfg)
    B, Tp = tokens.shape
    positions = jnp.arange(Tp)
    x = _embed(params, tokens, cfg, positions)

    if fam == "gpt2":
        def body(h, p):
            h, kv = _gpt2_block(h, p, cfg)
            return h, kv
    else:
        def body(h, p):
            h, kv = _llama_block(h, p, cfg, positions)
            return h, kv

    x, (ks, vs) = lax.scan(body, x, params["blocks"])  # ks [L, B, KV, Tp, dh]
    # single advanced index keeps its axis position: one scatter per tensor
    cache_k = cache["k"].at[:, slots, :, :Tp, :].set(ks.astype(cache["k"].dtype))
    cache_v = cache["v"].at[:, slots, :, :Tp, :].set(vs.astype(cache["v"].dtype))
    pos = cache["pos"].at[slots].set(lengths.astype(jnp.int32))
    last = _unembed(params, jnp.take_along_axis(
        x, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1), cfg)
    return last[:, 0, :], {"k": cache_k, "v": cache_v, "pos": pos}


def prefill(params, cfg, tokens: jax.Array, lengths: jax.Array,
            cache: Dict[str, jax.Array], slot: jax.Array) -> Tuple[jax.Array, Dict]:
    """:func:`prefill_at` with contiguous slots ``slot + [0..B)``."""
    B = tokens.shape[0]
    return prefill_at(params, cfg, tokens, lengths, cache,
                      slot + jnp.arange(B, dtype=jnp.int32))


def decode_step(params, cfg, cache: Dict[str, jax.Array], tokens: jax.Array,
                active: jax.Array) -> Tuple[jax.Array, Dict]:
    """One token for every slot.  ``tokens [B]`` are each slot's last
    emitted token, written at ``pos`` then attended; ``active [B]`` bool
    gates the position advance.  Returns ``(logits [B, V], cache)``.

    The layer loop is a ``fori_loop`` carrying the FULL cache and writing
    each layer's new K/V column with one scatter — XLA's while-loop buffer
    aliasing keeps the cache in place.  (The earlier scan-with-outputs
    version rebuilt the whole cache every step: measured ~1.3 ms/step of
    pure rewrite traffic on v5e at GPT-2 125M, on top of the ~1.2 ms
    weight-streaming floor.)"""
    fam = family_of(cfg)
    pos = cache["pos"]
    B = tokens.shape[0]
    H, dh = cfg.n_heads, cfg.head_dim
    KV = kv_heads(cfg)
    x = _embed(params, tokens[:, None], cfg, pos[:, None])  # [B, 1, D]
    blocks = params["blocks"]
    iota_b = jnp.arange(B)[:, None]
    iota_kv = jnp.arange(KV)[None, :]
    positions = pos[:, None]  # [B, 1] per-slot offsets (rope)

    def layer(l, carry):
        x, k_all, v_all = carry  # x [B, 1, D]
        p = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, l, 0, keepdims=False),
            blocks)
        if fam == "gpt2":
            q, k, v = _gpt2_qkv(x, p, cfg)  # [B, heads, 1, dh]
        else:
            q, k, v = _llama_qkv(x, p, cfg, positions)
        # ONE scatter per tensor writes only the new column (l, b, :, pos_b)
        k_all = k_all.at[l, iota_b, iota_kv, positions, :].set(
            k[:, :, 0, :].astype(k_all.dtype))
        v_all = v_all.at[l, iota_b, iota_kv, positions, :].set(
            v[:, :, 0, :].astype(v_all.dtype))
        k_c = lax.dynamic_index_in_dim(k_all, l, 0, keepdims=False)
        v_c = lax.dynamic_index_in_dim(v_all, l, 0, keepdims=False)
        out = _decode_attend(q, k_c, v_c, pos)  # [B, H, 1, dh]
        out = out.transpose(0, 2, 1, 3).reshape(B, 1, H * dh).astype(cfg.dtype)
        if fam == "gpt2":
            x = _gpt2_post_attn(x, out, p, cfg)
        else:
            x = _llama_post_attn(x, out, p, cfg)
        return x, k_all, v_all

    x, k_all, v_all = lax.fori_loop(
        0, cfg.n_layers, layer, (x, cache["k"], cache["v"]))
    logits = _unembed(params, x, cfg)[:, 0, :]
    return logits, {
        "k": k_all, "v": v_all,
        "pos": pos + active.astype(jnp.int32),
    }


def sample_logits(logits: jax.Array, key: jax.Array, *, temperature: float = 0.0,
                  top_k: int = 0) -> jax.Array:
    """Greedy (temperature 0) or temperature/top-k categorical sampling."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def decode_chunk(params, cfg, cache, tokens, active, key, *, steps: int,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_id: Optional[int] = None):
    """Run ``steps`` decode+sample iterations in one device computation.
    Returns ``(emitted [B, steps], cache, active, key)``.  A slot that
    emits ``eos_id`` flips inactive mid-chunk (its pos freezes)."""

    def step(carry, _):
        cache, toks, act, k = carry
        k, sub = jax.random.split(k)
        logits, cache = decode_step(params, cfg, cache, toks, act)
        nxt = sample_logits(logits, sub, temperature=temperature, top_k=top_k)
        nxt = jnp.where(act, nxt, toks)
        if eos_id is not None:
            act = act & (nxt != eos_id)
        return (cache, nxt, act, k), nxt

    (cache, _, active, key), emitted = lax.scan(
        step, (cache, tokens, active, key), None, length=steps)
    return emitted.T, cache, active, key  # [B, steps]


def generate(params, cfg, prompts: jax.Array, lengths: jax.Array, *,
             max_new_tokens: int, key: Optional[jax.Array] = None,
             temperature: float = 0.0, top_k: int = 0,
             eos_id: Optional[int] = None) -> jax.Array:
    """One-shot batched generation (prefill + fused decode loop).  Returns
    ``[B, max_new_tokens]`` generated tokens (post-EOS positions repeat the
    EOS token).  For the serving path use :mod:`ray_tpu.serve.llm`, which
    runs the same kernels under iteration-level continuous batching."""
    B, Tp = prompts.shape
    if key is None:
        key = jax.random.PRNGKey(0)
    cache = init_cache(cfg, B, Tp + max_new_tokens)
    last_logits, cache = prefill(
        params, cfg, prompts, lengths, cache, jnp.int32(0))
    key, sub = jax.random.split(key)
    first = sample_logits(last_logits, sub, temperature=temperature, top_k=top_k)
    active = jnp.ones((B,), bool)
    if eos_id is not None:
        active = active & (first != eos_id)
    rest, _, _, _ = decode_chunk(
        params, cfg, cache, first, active, key,
        steps=max_new_tokens - 1, temperature=temperature, top_k=top_k,
        eos_id=eos_id)
    return jnp.concatenate([first[:, None], rest], axis=1)
