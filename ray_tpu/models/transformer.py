"""Shared transformer core: stacked-layer params, scan-over-layers forward.

Design choices, all TPU-motivated:

- **Layer stacking**: every block parameter carries a leading ``[L, ...]``
  layer axis and the forward is one ``lax.scan`` over it — one compiled
  block body regardless of depth (fast compiles, friendly to pipeline
  sharding later).
- **Remat**: the scanned body is wrapped in ``jax.checkpoint`` so
  activations are recomputed in the backward pass — HBM for FLOPs.
- **bf16 compute, f32 master weights**: params live in f32; matmuls run in
  ``config.dtype`` (bfloat16 by default) with f32 accumulation inside the
  attention/softmax path.
- **Logical axes**: a parallel pytree of axis-name tuples feeds
  :mod:`ray_tpu.parallel.sharding` — ``embed``→fsdp, ``heads``/``mlp``→tp,
  sequence→sp (ring attention when the mesh has an ``sp`` axis).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import attention
from ray_tpu.ops.layers import layernorm
from ray_tpu.ops.moe import init_moe_params, moe_ffn, moe_logical_axes
from ray_tpu.ops.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50304  # GPT-2's 50257 padded up to a multiple of 128
    n_layers: int = 12
    n_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    max_seq_len: int = 1024
    causal: bool = True
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # "dots": save matmul outputs, recompute elementwise (measured ~+6%
    # over full remat at GPT-2 shapes on v5e — the backward re-reads saved
    # MXU outputs instead of re-running them); "full": recompute all.
    remat_policy: str = "dots"
    # pre-LN (GPT-2 style) by default; post-LN matches original BERT so
    # HF checkpoints load faithfully.
    post_ln: bool = False
    # MoE: >0 replaces every block's FFN with a Switch-style top-1 MoE of
    # this many experts (expert axis shards over the mesh's ep axis).
    n_experts: int = 0
    capacity_factor: float = 2.0
    moe_aux_weight: float = 0.01
    # pipeline parallelism: microbatch count when the mesh has pp > 1
    # (0 = one microbatch per stage).
    pp_microbatches: int = 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_block_params(cfg: TransformerConfig, key: jax.Array) -> Dict[str, jax.Array]:
    """Stacked block params, GPT-2 init (normal 0.02, residual projections
    scaled by 1/sqrt(2L))."""
    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    ks = jax.random.split(key, 5)
    std, res_std = 0.02, 0.02 / (2 * L) ** 0.5
    p = {
        "ln1_w": jnp.ones((L, D)), "ln1_b": jnp.zeros((L, D)),
        "wqkv": jax.random.normal(ks[0], (L, D, 3 * D)) * std,
        "bqkv": jnp.zeros((L, 3 * D)),
        "wo": jax.random.normal(ks[1], (L, D, D)) * res_std,
        "bo": jnp.zeros((L, D)),
        "ln2_w": jnp.ones((L, D)), "ln2_b": jnp.zeros((L, D)),
    }
    if cfg.n_experts > 0:
        p.update(init_moe_params(ks[4], L, D, F, cfg.n_experts,
                                 std=std, res_std=res_std))
    else:
        p.update({
            "w1": jax.random.normal(ks[2], (L, D, F)) * std,
            "b1": jnp.zeros((L, F)),
            "w2": jax.random.normal(ks[3], (L, F, D)) * res_std,
            "b2": jnp.zeros((L, D)),
        })
    return p


def block_logical_axes(n_experts: int = 0) -> Dict[str, Tuple]:
    """Logical axis names for the stacked block params.  The leading
    ``layers`` axis is the scan axis; it shards over ``pp`` (and only
    ``pp``) when the mesh pipelines."""
    axes = {
        "ln1_w": ("layers", "embed"), "ln1_b": ("layers", "embed"),
        "wqkv": ("layers", "embed", "heads"),
        "bqkv": ("layers", "heads"),
        "wo": ("layers", "heads", "embed"),
        "bo": ("layers", "embed"),
        "ln2_w": ("layers", "embed"), "ln2_b": ("layers", "embed"),
    }
    if n_experts > 0:
        axes.update(moe_logical_axes())
    else:
        axes.update({
            "w1": ("layers", "embed", "mlp"),
            "b1": ("layers", "mlp"),
            "w2": ("layers", "mlp", "embed"),
            "b2": ("layers", "embed"),
        })
    return axes


def make_train_step_from_loss(loss_fn, cfg, optimizer, mesh: Optional[Mesh] = None):
    """Shared train-step recipe for every model family: value_and_grad of
    ``loss_fn(params, batch, cfg, mesh)`` + optimizer update.  One place to
    fix donation/metrics for all models."""
    import optax

    def train_step(state, batch):
        params, opt_state, step = state["params"], state["opt_state"], state["step"]
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg, mesh)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return ({"params": params, "opt_state": opt_state, "step": step + 1},
                {"loss": loss, "step": step + 1})

    return train_step


def _attend(q, k, v, *, causal: bool, mesh: Optional[Mesh]) -> jax.Array:
    """Pick the sequence-parallel path when the mesh has an sp axis."""
    if mesh is not None and "sp" in mesh.axis_names and mesh.shape["sp"] > 1:
        batch = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names) or None
        heads = "tp" if "tp" in mesh.axis_names else None
        spec = P(batch, heads, "sp", None)
        sm = jax.shard_map(
            partial(ring_attention, axis_name="sp", causal=causal),
            mesh=mesh,
            in_specs=(spec,) * 3,
            out_specs=spec,
            check_vma=False,
        )
        return sm(q, k, v)
    return attention(q, k, v, causal=causal)


def apply_block(
    x: jax.Array, p: Dict[str, jax.Array], cfg: TransformerConfig,
    mesh: Optional[Mesh] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One transformer block, pre-LN or post-LN.  x: [B, T, D] in cfg.dtype.
    Returns ``(x, aux)`` — aux is the MoE load-balance loss (0 when dense)."""
    B, T, D = x.shape
    H, dh = cfg.n_heads, cfg.head_dim
    c = lambda w: w.astype(cfg.dtype)
    aux = jnp.zeros((), jnp.float32)

    def attn(h):
        qkv = h @ c(p["wqkv"]) + c(p["bqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(B, T, H, dh).transpose(0, 2, 1, 3)
        out = _attend(to_heads(q), to_heads(k), to_heads(v), causal=cfg.causal, mesh=mesh)
        out = out.transpose(0, 2, 1, 3).reshape(B, T, D)
        return out @ c(p["wo"]) + c(p["bo"])

    if cfg.n_experts > 0:
        def ffn(h):
            nonlocal aux
            y, a = moe_ffn(h, p["router"], c(p["ew1"]), c(p["eb1"]),
                           c(p["ew2"]), c(p["eb2"]),
                           capacity_factor=cfg.capacity_factor, mesh=mesh)
            aux = aux + a
            return y
    else:
        def ffn(h):
            h = jax.nn.gelu(h @ c(p["w1"]) + c(p["b1"]), approximate=True)
            return h @ c(p["w2"]) + c(p["b2"])

    if cfg.post_ln:  # original-BERT residual->norm order
        x = layernorm(x + attn(x), c(p["ln1_w"]), c(p["ln1_b"]))
        x = layernorm(x + ffn(x), c(p["ln2_w"]), c(p["ln2_b"]))
    else:  # GPT-2 pre-LN
        x = x + attn(layernorm(x, c(p["ln1_w"]), c(p["ln1_b"])))
        x = x + ffn(layernorm(x, c(p["ln2_w"]), c(p["ln2_b"])))
    return x, aux


def apply_stack(
    x: jax.Array, blocks: Dict[str, jax.Array], cfg: TransformerConfig,
    mesh: Optional[Mesh] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Run the stacked layers; returns ``(x, aux)``.

    Without ``pp`` the stack is one remat'd ``lax.scan`` over the layer
    axis.  With a ``pp > 1`` mesh axis, the layer axis is sharded into
    stages and the scan runs inside the GPipe engine
    (:func:`ray_tpu.parallel.pipeline.gpipe`) — same math, microbatched.
    """

    def body(x, layer_params):
        return apply_block(x, layer_params, cfg, mesh)

    if cfg.remat:
        if cfg.remat_policy == "dots":
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            )
        elif cfg.remat_policy == "full":
            body = jax.checkpoint(body)
        else:
            raise ValueError(
                f"unknown remat_policy {cfg.remat_policy!r} (use 'dots' or 'full')"
            )

    def stage(local_blocks, h):
        h, auxs = lax.scan(body, h, local_blocks)
        return h, auxs.sum()

    from ray_tpu.parallel.pipeline import gpipe, pp_size

    if mesh is not None and pp_size(mesh) > 1:
        return gpipe(stage, blocks, x, mesh=mesh,
                     n_microbatches=cfg.pp_microbatches)
    return stage(blocks, x)
