"""BERT-style bidirectional encoder classifier (BASELINE config 5).

The Serve-replica model: sequence classification with a [CLS] pooled head.
Same stacked-layer transformer core as GPT-2 but non-causal, plus
``from_hf`` to load real ``bert-base-uncased`` weights from a local
HuggingFace checkpoint when one is available.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.models.transformer import (
    TransformerConfig,
    apply_stack,
    block_logical_axes,
    init_block_params,
)
from ray_tpu.ops.layers import layernorm


@dataclasses.dataclass(frozen=True)
class BertConfig(TransformerConfig):
    vocab_size: int = 30592  # 30522 padded to a multiple of 128
    n_layers: int = 12
    n_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    max_seq_len: int = 512
    causal: bool = False
    post_ln: bool = True  # original BERT is post-LN; HF weights load faithfully
    num_classes: int = 2
    type_vocab_size: int = 2

    @staticmethod
    def base(**kw) -> "BertConfig":
        return BertConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "BertConfig":
        return BertConfig(
            vocab_size=512, n_layers=2, n_heads=4, d_model=64, d_ff=256,
            max_seq_len=128, remat=False, **kw,
        )


def init(cfg: BertConfig, key: jax.Array) -> Dict[str, Any]:
    ks = jax.random.split(key, 6)
    D = cfg.d_model
    return {
        "wte": jax.random.normal(ks[0], (cfg.vocab_size, D)) * 0.02,
        "wpe": jax.random.normal(ks[1], (cfg.max_seq_len, D)) * 0.02,
        "wtype": jax.random.normal(ks[2], (cfg.type_vocab_size, D)) * 0.02,
        "ln_emb_w": jnp.ones(D), "ln_emb_b": jnp.zeros(D),
        "blocks": init_block_params(cfg, ks[3]),
        "pool_w": jax.random.normal(ks[4], (D, D)) * 0.02,
        "pool_b": jnp.zeros(D),
        "cls_w": jax.random.normal(ks[5], (D, cfg.num_classes)) * 0.02,
        "cls_b": jnp.zeros(cfg.num_classes),
    }


def logical_axes(cfg: Optional["BertConfig"] = None) -> Dict[str, Any]:
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "wtype": (None, "embed"),
        "ln_emb_w": ("embed",), "ln_emb_b": ("embed",),
        "blocks": block_logical_axes(cfg.n_experts if cfg else 0),
        "pool_w": ("embed", "embed"),
        "pool_b": ("embed",),
        "cls_w": ("embed", None),
        "cls_b": (None,),
    }


def apply(
    params: Dict[str, Any], tokens: jax.Array, cfg: BertConfig,
    token_types: Optional[jax.Array] = None, mesh: Optional[Mesh] = None,
) -> jax.Array:
    """tokens [B, T] -> class logits [B, num_classes]."""
    B, T = tokens.shape
    x = params["wte"][tokens] + params["wpe"][:T]
    if token_types is not None:
        x = x + params["wtype"][token_types]
    x = layernorm(x, params["ln_emb_w"], params["ln_emb_b"]).astype(cfg.dtype)
    x, _ = apply_stack(x, params["blocks"], cfg, mesh)
    cls = jnp.tanh(x[:, 0].astype(jnp.float32) @ params["pool_w"] + params["pool_b"])
    return cls @ params["cls_w"] + params["cls_b"]


def from_hf(model_name: str = "bert-base-uncased", num_classes: int = 2):
    """Load HF torch weights into this layout (requires a local checkpoint;
    the image has transformers but no network)."""
    import numpy as np
    from transformers import AutoModel

    hf = AutoModel.from_pretrained(model_name)
    sd = {k: np.asarray(v) for k, v in hf.state_dict().items()}
    cfg = BertConfig(num_classes=num_classes,
                     vocab_size=sd["embeddings.word_embeddings.weight"].shape[0])
    L, D = cfg.n_layers, cfg.d_model
    g = lambda k: jnp.asarray(sd[k])
    stack = lambda fmt, t=False: jnp.stack(
        [g(fmt.format(i)).T if t else g(fmt.format(i)) for i in range(L)]
    )
    params = {
        "wte": g("embeddings.word_embeddings.weight"),
        "wpe": g("embeddings.position_embeddings.weight"),
        "wtype": g("embeddings.token_type_embeddings.weight"),
        "ln_emb_w": g("embeddings.LayerNorm.weight"),
        "ln_emb_b": g("embeddings.LayerNorm.bias"),
        "blocks": {
            "ln1_w": stack("encoder.layer.{}.attention.output.LayerNorm.weight"),
            "ln1_b": stack("encoder.layer.{}.attention.output.LayerNorm.bias"),
            "wqkv": jnp.concatenate([
                stack("encoder.layer.{}.attention.self.query.weight", t=True),
                stack("encoder.layer.{}.attention.self.key.weight", t=True),
                stack("encoder.layer.{}.attention.self.value.weight", t=True),
            ], axis=-1),
            "bqkv": jnp.concatenate([
                stack("encoder.layer.{}.attention.self.query.bias"),
                stack("encoder.layer.{}.attention.self.key.bias"),
                stack("encoder.layer.{}.attention.self.value.bias"),
            ], axis=-1),
            "wo": stack("encoder.layer.{}.attention.output.dense.weight", t=True),
            "bo": stack("encoder.layer.{}.attention.output.dense.bias"),
            "ln2_w": stack("encoder.layer.{}.output.LayerNorm.weight"),
            "ln2_b": stack("encoder.layer.{}.output.LayerNorm.bias"),
            "w1": stack("encoder.layer.{}.intermediate.dense.weight", t=True),
            "b1": stack("encoder.layer.{}.intermediate.dense.bias"),
            "w2": stack("encoder.layer.{}.output.dense.weight", t=True),
            "b2": stack("encoder.layer.{}.output.dense.bias"),
        },
        "pool_w": g("pooler.dense.weight").T,
        "pool_b": g("pooler.dense.bias"),
        "cls_w": jnp.zeros((D, num_classes)),
        "cls_b": jnp.zeros(num_classes),
    }
    return cfg, params
