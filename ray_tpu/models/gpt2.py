"""GPT-2: the flagship decoder LM (BASELINE config 3 — GPT-2 125M).

Pure-jax (init, apply, loss, train_step) over dict pytrees with logical
sharding axes; trains data/fsdp/tensor/sequence-parallel purely through
sharding annotations — the reference delegates all of this to torch
(``python/ray/train/torch/train_loop_utils.py:51`` prepare_model wraps
DDP/FSDP); here the sharding *is* the model's parallelism.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from ray_tpu.models.transformer import (
    TransformerConfig,
    apply_stack,
    block_logical_axes,
    init_block_params,
)
from ray_tpu.ops.layers import cross_entropy_loss, layernorm
from ray_tpu.parallel.sharding import ShardingRules, logical_to_sharding


@dataclasses.dataclass(frozen=True)
class GPT2Config(TransformerConfig):
    causal: bool = True

    @staticmethod
    def gpt2_small(**kw) -> "GPT2Config":
        """The 124M-parameter headline model (any field overridable)."""
        base = dict(vocab_size=50304, n_layers=12, n_heads=12, d_model=768,
                    d_ff=3072, max_seq_len=1024)
        base.update(kw)
        return GPT2Config(**base)

    @staticmethod
    def tiny(**kw) -> "GPT2Config":
        """Test/dry-run sized (any field overridable)."""
        base = dict(vocab_size=512, n_layers=2, n_heads=4, d_model=64,
                    d_ff=256, max_seq_len=128, remat=False)
        base.update(kw)
        return GPT2Config(**base)


def init(cfg: GPT2Config, key: jax.Array) -> Dict[str, Any]:
    k_emb, k_pos, k_blocks = jax.random.split(key, 3)
    return {
        "wte": jax.random.normal(k_emb, (cfg.vocab_size, cfg.d_model)) * 0.02,
        "wpe": jax.random.normal(k_pos, (cfg.max_seq_len, cfg.d_model)) * 0.01,
        "blocks": init_block_params(cfg, k_blocks),
        "lnf_w": jnp.ones(cfg.d_model),
        "lnf_b": jnp.zeros(cfg.d_model),
    }


def logical_axes(cfg: Optional[GPT2Config] = None) -> Dict[str, Any]:
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "blocks": block_logical_axes(cfg.n_experts if cfg else 0),
        "lnf_w": ("embed",),
        "lnf_b": ("embed",),
    }


def param_shardings(mesh: Mesh, rules: ShardingRules, cfg: Optional[GPT2Config] = None):
    return logical_to_sharding(logical_axes(cfg), mesh, rules)


def apply(
    params: Dict[str, Any], tokens: jax.Array, cfg: GPT2Config,
    mesh: Optional[Mesh] = None, *, return_aux: bool = False,
):
    """tokens [B, T] int32 -> logits [B, T, V] (f32).

    With ``return_aux=True`` returns ``(logits, aux)`` where aux is the
    MoE load-balance loss (0 for dense configs)."""
    B, T = tokens.shape
    x = params["wte"][tokens] + params["wpe"][:T]
    x = x.astype(cfg.dtype)
    x, aux = apply_stack(x, params["blocks"], cfg, mesh)
    x = layernorm(x, params["lnf_w"].astype(cfg.dtype), params["lnf_b"].astype(cfg.dtype))
    # tied embeddings for the LM head
    logits = (x @ params["wte"].T.astype(cfg.dtype)).astype(jnp.float32)
    return (logits, aux) if return_aux else logits


def loss_fn(
    params: Dict[str, Any], batch: Dict[str, jax.Array], cfg: GPT2Config,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """Next-token cross entropy. batch: {"tokens": [B, T+1]} or
    {"inputs": [B,T], "targets": [B,T]}."""
    if "tokens" in batch:
        inputs, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    logits, aux = apply(params, inputs, cfg, mesh, return_aux=True)
    loss = cross_entropy_loss(logits, targets)
    if cfg.n_experts > 0:
        loss = loss + cfg.moe_aux_weight * aux
    return loss


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.1,
                   warmup: int = 100, total_steps: int = 10000):
    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup, max(total_steps, warmup + 1), lr * 0.1
    )
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay,
                    mask=lambda p: jax.tree.map(lambda x: x.ndim >= 2, p)),
    )


def make_train_step(cfg: GPT2Config, optimizer, mesh: Optional[Mesh] = None):
    """Returns train_step(state, batch) -> (state, metrics); jit/pjit-able,
    donate state for in-place updates."""
    from ray_tpu.models.transformer import make_train_step_from_loss

    return make_train_step_from_loss(loss_fn, cfg, optimizer, mesh)


def init_state(cfg: GPT2Config, key: jax.Array, optimizer) -> Dict[str, Any]:
    params = init(cfg, key)
    return {
        "params": params,
        "opt_state": optimizer.init(params),
        "step": jnp.zeros((), jnp.int32),
    }


def num_params(params: Dict[str, Any]) -> int:
    return sum(x.size for x in jax.tree.leaves(params))
