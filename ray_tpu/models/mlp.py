"""MLP classifier (BASELINE config 2: MNIST MLP, data-parallel psum).

Small enough that its whole train step is one fused XLA program; used by
the Train tests as the canonical DP workload.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: Tuple[int, ...] = (512, 256)
    num_classes: int = 10
    dtype: Any = jnp.float32


def init(cfg: MLPConfig, key: jax.Array) -> Dict[str, Any]:
    dims = (cfg.in_dim, *cfg.hidden, cfg.num_classes)
    params = {}
    for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        key, sub = jax.random.split(key)
        params[f"w{i}"] = jax.random.normal(sub, (d_in, d_out)) * (2.0 / d_in) ** 0.5
        params[f"b{i}"] = jnp.zeros(d_out)
    return params


def apply(params: Dict[str, Any], x: jax.Array, cfg: MLPConfig) -> jax.Array:
    n_layers = len(cfg.hidden) + 1
    h = x.astype(cfg.dtype)
    for i in range(n_layers):
        h = h @ params[f"w{i}"] + params[f"b{i}"]
        if i < n_layers - 1:
            h = jax.nn.relu(h)
    return h


def loss_fn(params, batch, cfg: MLPConfig) -> jax.Array:
    logits = apply(params, batch["x"], cfg)
    labels = jax.nn.one_hot(batch["y"], cfg.num_classes)
    return optax.softmax_cross_entropy(logits, labels).mean()


def accuracy(params, batch, cfg: MLPConfig) -> jax.Array:
    return (apply(params, batch["x"], cfg).argmax(-1) == batch["y"]).mean()
