"""Model zoo: pure-jax pytree models with logical sharding axes.

Models are (init, apply) pairs over plain dict pytrees — no framework
classes on the hot path, so pjit sees exactly the arrays and the sharding
rules in :mod:`ray_tpu.parallel.sharding` apply mechanically.  Families:

- :mod:`ray_tpu.models.gpt2` — the flagship decoder LM (BASELINE config 3:
  GPT-2 125M, FSDP/TP/SP-shardable, ring attention for long context).
- :mod:`ray_tpu.models.bert` — bidirectional encoder classifier
  (BASELINE config 5: the Serve replica model).
- :mod:`ray_tpu.models.llama` — Llama-family decoder (RMSNorm/RoPE/
  SwiGLU/grouped-query attention; long-context + GQA KV savings).
- :mod:`ray_tpu.models.mlp` — MNIST-class MLP (BASELINE config 2).
"""

from ray_tpu.models import bert, gpt2, llama, mlp  # noqa: F401
from ray_tpu.models.gpt2 import GPT2Config
from ray_tpu.models.bert import BertConfig
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.models.mlp import MLPConfig

__all__ = [
    "gpt2", "bert", "llama", "mlp",
    "GPT2Config", "BertConfig", "LlamaConfig", "MLPConfig",
]
