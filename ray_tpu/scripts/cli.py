"""ray_tpu CLI — ``ray start/stop/status/...`` analog.

Reference: ``python/ray/scripts/scripts.py`` (cluster lifecycle) and
``dashboard/modules/job/cli.py`` (job commands).  Run as
``python -m ray_tpu <command>``:

    start --head [--num-cpus N --num-tpus N]   run a head in the foreground
    start --address host:port [--authkey HEX]  join as a worker node agent
    stop                                       kill the last started head
    status                                     cluster resources/state
    list {actors,tasks,nodes,objects,workers,placement_groups,jobs}
    submit -- <entrypoint...>                  submit a job
    job-logs <job_id> / job-stop <job_id>
    logs [STREAM] [--follow --errors --grep P] cluster log plane (tailed
         [--job J --task T --actor A           worker/driver files, context-
          --node N --pid P --tail N]           stamped, from the head store)
    timeline [--out FILE]                      chrome-trace of task events
    events [--source S --severity L --limit N] flight-recorder event table
    trace [TRACE_ID]                           span tree + critical path
    doctor [--live]                            pathology analysis (exit 1 on findings;
                                               --live reads the watchdog's incident set)
    incidents [--follow --history --ack ID]    watchdog incident lifecycle
    slo                                        declared SLOs + burn-rate state
    debug dump                                 write a whole-cluster post-mortem bundle
    top [--interval S --iterations N --sort K] live nodes/workers resource view
    memory [--limit N --json]                  object-ownership audit (`ray memory`)
    metrics [NAME] [--window S --step S]       TSDB directory / time-series query
    perf [--window S --json]                   step-phase breakdown, MFU, compiles, HBM
    profile [--duration N --worker-id HEX]     on-demand sampling profile
    profile --live [--window S --origin O]     always-on flamegraph (folded stacks)
    profile diff WINDOW_A WINDOW_B             differential folded stacks
    profile ledger [--window S]                per-task CPU cost ledger
    profile list                               origins with profile history
    serve-status                               serve deployments + autoscaling
    lint [--rule R4 --json --update-baseline]  raylint static-analysis gate
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

SESSION_FILE = "/tmp/ray_tpu/last_session.json"


def _session() -> dict:
    try:
        with open(SESSION_FILE) as f:
            return json.load(f)
    except OSError:
        raise SystemExit("no running ray_tpu session found (start one with "
                         "`python -m ray_tpu start --head`)")


def _connect():
    import ray_tpu

    ray_tpu.init(address="auto")
    return ray_tpu


def cmd_start(args) -> None:
    if args.head:
        import ray_tpu

        ray_tpu.init(num_cpus=args.num_cpus, num_tpus=args.num_tpus)
        from ray_tpu._private.worker import global_worker

        node = global_worker.node
        host, port = node.tcp_address
        print(f"ray_tpu head running: tcp://{host}:{port}")
        print(f"authkey: {node.authkey.hex()}")
        if node.dashboard:
            print("dashboard: http://%s:%d" % tuple(node.dashboard.address))
        print("join with: python -m ray_tpu start "
              f"--address {host}:{port} --authkey {node.authkey.hex()}")
        print("Ctrl-C to stop.")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            ray_tpu.shutdown()
    elif args.address:
        from ray_tpu._private.node_agent import NodeAgent

        authkey = bytes.fromhex(args.authkey or os.environ["RAY_TPU_AUTHKEY"])
        agent = NodeAgent(
            args.address, authkey, num_cpus=args.num_cpus,
            num_tpus=args.num_tpus, shm_dir=args.shm_dir,
        )
        agent.serve_forever()
    else:
        raise SystemExit("start needs --head or --address")


def cmd_up(args) -> None:
    """``ray up`` analog: start head + join workers per the YAML."""
    from ray_tpu.autoscaler.commands import load_cluster_config, up

    out = up(load_cluster_config(args.config))
    print(json.dumps(out, indent=2))
    print(f"cluster up: {out['address']} "
          f"({len(out['workers'])} worker nodes joining)")


def cmd_down(args) -> None:
    from ray_tpu.autoscaler.commands import down, load_cluster_config

    down(load_cluster_config(args.config))
    print("cluster down")


def cmd_stop(_args) -> None:
    sess = _session()
    pid = sess.get("pid")
    try:
        os.kill(pid, signal.SIGTERM)
        print(f"sent SIGTERM to head pid {pid}")
    except OSError as e:
        print(f"head pid {pid}: {e}")


def cmd_status(_args) -> None:
    rt = _connect()
    snap = rt._private.worker.global_worker.client.request(
        {"type": "state_snapshot"})["value"]
    print(json.dumps({
        "cluster_resources": snap["cluster_resources"],
        "available_resources": snap["available_resources"],
        "object_store": snap["object_store"],
        "nodes": len(snap["nodes"]),
        "actors": len(snap["actors"]),
        "tasks": len(snap["tasks"]),
    }, indent=2, default=repr))


def cmd_list(args) -> None:
    _connect()
    from ray_tpu.experimental.state import api as state

    page = state.list_state_page(args.what, limit=args.limit)
    print(json.dumps(page["rows"], indent=2, default=repr))
    if page["truncated"]:
        # loud, and on stderr so piped JSON stays parseable — a capped
        # listing must never masquerade as the complete table
        print(f"# truncated: showing {len(page['rows'])} of "
              f"{page['total']} rows (use --limit {page['total']})",
              file=sys.stderr)


def cmd_submit(args) -> None:
    sess = _session()
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(sess["address"],
                                 authkey=bytes.fromhex(sess["authkey"]))
    import shlex

    parts = args.entrypoint
    if parts and parts[0] == "--":  # argparse.REMAINDER keeps the separator
        parts = parts[1:]
    entry = shlex.join(parts)  # preserve each argv token through the shell
    job_id = client.submit_job(entrypoint=entry)
    print(f"submitted {job_id}: {entry}")
    if args.wait:
        status = client.wait_until_finish(job_id, timeout=args.timeout)
        print(client.get_job_logs(job_id), end="")
        print(f"job {job_id}: {status}")
        sys.exit(0 if status == "SUCCEEDED" else 1)


def cmd_job_logs(args) -> None:
    """Job driver logs from the head's log store — the same surface
    ``ray_tpu logs job-<id>`` reads (one log plane for job drivers and
    workers; the head falls back to the complete on-disk job file when
    the ring has aged out)."""
    _connect()
    from ray_tpu.experimental.state import api as state

    reply = state.get_log(stream=f"job-{args.job_id}", limit=100_000)
    for r in reply["records"]:
        print(r["line"])


def cmd_job_stop(args) -> None:
    sess = _session()
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(sess["address"], authkey=bytes.fromhex(sess["authkey"]))
    print("stopped" if client.stop_job(args.job_id) else "not running")


def cmd_logs(args) -> None:
    """Cluster log plane (``ray logs`` analog): with no stream and no
    filters, one row per captured stream in the head's store; otherwise
    the matching records, each prefixed ``(stream pid=… node=…)``.
    Every filter matches the per-line context stamps, so ``--task``/
    ``--actor``/``--job`` find a plain ``print()`` from inside that
    execution.  ``--follow`` keeps polling the head's cursor."""
    _connect()
    from ray_tpu.experimental.state import api as state

    filtered = any((args.stream, args.job, args.task, args.actor,
                    args.node, args.pid, args.grep, args.errors))
    if not filtered and not args.follow:
        rows = state.list_logs(limit=args.limit)
        if not rows:
            print("(no log streams captured yet)")
            return
        print(f"{'STREAM':<28} {'NODE':<12} {'PID':>7} {'LINES':>7} "
              f"{'BYTES':>9}  STATE")
        for r in rows:
            print(f"{r['stream']:<28} {str(r.get('node') or '-'):<12} "
                  f"{str(r.get('pid') or '-'):>7} {r['lines']:>7} "
                  f"{r['bytes']:>9}  "
                  f"{'retired' if r.get('retired') else 'live'}")
        return

    def emit(records):
        for r in records:
            print(f"({r['stream']} pid={r.get('pid')}, "
                  f"node={r.get('node')}) {r['line']}")

    reply = state.get_log(
        stream=args.stream, job=args.job, task=args.task, actor=args.actor,
        node=args.node, pid=args.pid, grep=args.grep, errors=args.errors,
        limit=args.tail)
    emit(reply["records"])
    if not args.follow:
        return
    cursor = reply["cursor"]
    try:
        while True:
            time.sleep(args.interval)
            reply = state.get_log(
                stream=args.stream, job=args.job, task=args.task,
                actor=args.actor, node=args.node, pid=args.pid,
                grep=args.grep, errors=args.errors,
                since_seq=cursor, limit=100_000)
            emit(reply["records"])
            cursor = reply["cursor"]
    except KeyboardInterrupt:
        pass


def cmd_timeline(args) -> None:
    _connect()
    from ray_tpu.util.timeline import timeline_dump

    path = timeline_dump(args.out)
    print(f"wrote chrome trace to {path} (open in chrome://tracing)")


def cmd_events(args) -> None:
    """Flight-recorder events (``ray list cluster-events`` analog): the
    head's merged per-source event table — dispatch decisions, spills,
    OOM kills, stalls, admissions — as JSON lines or a summary."""
    _connect()
    from ray_tpu.experimental.state import api as state

    if args.summary:
        print(json.dumps(state.summarize_events(), indent=2))
        return
    rows = state.list_events(limit=args.limit, source=args.source,
                             severity=args.severity)
    for r in rows:
        print(json.dumps(r, default=repr))


def cmd_trace(args) -> None:
    """Request traces: without an id, list recent traces; with one, the
    assembled span tree + per-phase critical-path attribution."""
    _connect()
    from ray_tpu.experimental.state import api as state

    if not args.trace_id:
        rows = state.list_traces(limit=args.limit)
        if args.json:
            print(json.dumps(rows, indent=2, default=repr))
            return
        if not rows:
            print("(no traces recorded — run a workload inside "
                  "ray_tpu.util.tracing.trace(), or send serve traffic)")
            return
        for r in rows:
            print(f"{r['trace_id']}  {r['duration_s'] * 1e3:9.2f}ms  "
                  f"{r['num_spans']:4d} spans  {r['name']}")
        return
    trace = state.get_trace(args.trace_id)
    if trace is None:
        raise SystemExit(f"unknown trace {args.trace_id!r} (see "
                         f"`ray_tpu trace` for recent ids)")
    from ray_tpu.util.trace_analysis import analyze, render_trace

    analysis = analyze(trace)
    if args.json:
        trace["analysis"] = analysis
        print(json.dumps(trace, indent=2, default=repr))
    else:
        print(render_trace(trace, analysis))
        logs = trace.get("logs") or []
        if logs:
            print(f"\nlogs ({len(logs)} records stamped with this trace):")
            for r in logs:
                print(f"  ({r['stream']}) {r['line']}")


def _repo_root() -> str:
    """The checkout root (where raylint_baseline.json lives): the parent
    of the ray_tpu package, falling back to the cwd when the package is
    installed elsewhere but the cwd looks like a checkout (has the
    package dir + a baseline)."""
    import ray_tpu

    root = os.path.dirname(os.path.dirname(os.path.abspath(
        ray_tpu.__file__)))
    if not os.path.exists(os.path.join(root, "raylint_baseline.json")) \
            and os.path.isdir(os.path.join(os.getcwd(), "ray_tpu")) \
            and os.path.exists(os.path.join(os.getcwd(),
                                            "raylint_baseline.json")):
        return os.getcwd()
    return root


def _static_findings(rules=None, update_baseline=False, root=None):
    """Run the raylint gate over the repo; returns the GateResult."""
    from ray_tpu.devtools.raylint import run_gate

    return run_gate(root or _repo_root(), rules=rules,
                    update_baseline=update_baseline)


def cmd_lint(args) -> None:
    """raylint: the 8-rule static-analysis gate (no cluster needed).
    Exit 1 on findings the checked-in baseline doesn't grandfather."""
    from ray_tpu.devtools.raylint.runner import render_report, to_json

    rules = None
    if args.rule:
        rules = sorted({r.strip().upper() for spec in args.rule
                        for r in spec.split(",") if r.strip()})
    try:
        result = _static_findings(rules=rules,
                                  update_baseline=args.update_baseline,
                                  root=args.root)
    except ValueError as e:  # bad --rule id / --update-baseline subset
        raise SystemExit(f"ray_tpu lint: {e}")
    if args.json:
        print(json.dumps(to_json(result), indent=1))
    else:
        print(render_report(result, verbose=args.verbose))
    if not result.ok:
        sys.exit(1)


def cmd_doctor(args) -> None:
    """Rule-based pathology analysis over the recorded event/task state;
    exits non-zero when findings exist so CI can gate on it.  With
    --static, raylint's non-baselined findings join the report (one
    command for "is this cluster AND this tree healthy")."""
    findings = []
    if args.static:
        lint = _static_findings(root=args.root)
        findings.extend({
            "severity": "WARNING",
            "rule": f"raylint/{f.rule}",
            "summary": f"{f.location()}: {f.message}",
            "remedy": f.remedy,
            "evidence": [{"file": f.path, "line": f.line}],
            "count": 1,
        } for f in lint.new)
        # stale baseline keys fail `ray_tpu lint` (the baseline only
        # burns down) — doctor --static must agree with the gate
        findings.extend({
            "severity": "WARNING",
            "rule": "raylint/baseline",
            "summary": f"stale baseline entry (finding fixed): {key}",
            "remedy": "remove it via `ray_tpu lint --update-baseline`",
            "evidence": [{"baseline_key": key}],
            "count": 1,
        } for key in lint.stale_keys)
    _connect()
    from ray_tpu.util.doctor import render, run_doctor

    if getattr(args, "live", False):
        # report from the watchdog's CURRENT incident set instead of
        # re-diagnosing — what the continuous loop already concluded
        from ray_tpu.experimental.state import api as state

        findings.extend({
            "severity": inc["severity"], "rule": inc["rule"],
            "summary": f"[{inc['state']}] {inc['summary']}",
            "remedy": inc.get("remedy", ""),
            "count": inc.get("count", 1),
            "evidence": [{"incident_id": inc["id"],
                          "bundle_dir": inc.get("bundle_dir")}],
        } for inc in state.list_incidents()
            if inc["state"] in ("open", "ack"))
    else:
        findings.extend(run_doctor())
    if args.json:
        print(json.dumps(findings, indent=2, default=repr))
    else:
        print(render(findings))
    if findings:
        sys.exit(1)


def _render_incident_row(inc: dict) -> str:
    age = time.time() - inc.get("opened_at", time.time())
    flags = ""
    if inc.get("escalated"):
        flags += "!"
    if inc.get("reopen_count"):
        flags += f" x{inc['reopen_count'] + 1}"
    return (f"{inc['state']:<9} {inc['severity']:<8} "
            f"{int(age):>6}s {inc['id'][:48]:<50}{flags:<6} "
            f"{inc['summary'][:90]}")


def cmd_incidents(args) -> None:
    """Watchdog incident lifecycle: the tracked set, one incident's
    transition history, ack, or --follow transitions live."""
    _connect()
    from ray_tpu.experimental.state import api as state

    if args.ack:
        inc = state.ack_incident(args.ack)
        print(f"acked {inc['id']} ({inc['severity']}: "
              f"{inc['summary'][:100]})")
        return
    if args.history:
        inc = state.get_incident(args.history)
        if args.json:
            print(json.dumps(inc, indent=2, default=repr))
            return
        print(_render_incident_row(inc))
        if inc.get("bundle_dir"):
            print(f"  bundle: {inc['bundle_dir']}")
        for h in inc.get("history", []):
            ts = time.strftime("%H:%M:%S", time.localtime(h["ts"]))
            print(f"  {ts} {h['transition']:<9} {h.get('summary', '')[:100]}")
        return
    seen: dict = {}

    def _page():
        rows = state.list_incidents(limit=args.limit)
        rows.sort(key=lambda r: r.get("opened_at", 0.0))
        return rows

    rows = _page()
    if args.json:
        print(json.dumps(rows, indent=2, default=repr))
        return
    if not rows:
        print("no incidents")
    else:
        print(f"{'STATE':<9} {'SEV':<8} {'AGE':>7} {'INCIDENT':<56} SUMMARY")
        for inc in rows:
            print(_render_incident_row(inc))
            seen[inc["id"]] = (inc["state"], len(inc.get("history", [])))
    if not args.follow:
        return
    try:
        while True:
            time.sleep(args.interval)
            for inc in _page():
                key = (inc["state"], len(inc.get("history", [])))
                if seen.get(inc["id"]) != key:
                    seen[inc["id"]] = key
                    print(_render_incident_row(inc))
    except KeyboardInterrupt:
        pass


def cmd_slo(args) -> None:
    """Declared SLOs with their live multi-window burn-rate state."""
    _connect()
    from ray_tpu.experimental.state import api as state

    rows = state.list_slos()
    if args.json:
        print(json.dumps(rows, indent=2, default=repr))
        return
    print(f"{'SLO':<16} {'STATE':<8} {'OBJECTIVE':<44} "
          f"{'FAST':>10} {'SLOW':>10}")
    for s in rows:
        obj = f"{s['metric']} {s.get('op', '<=')} {s['threshold']}"
        if s.get("kind") == "ratio":
            obj = f"{s['metric']} ratio <= {s['threshold']}"

        def _w(w):
            if not w or not w.get("evaluable"):
                return "no-data"
            return f"{w['value']}{'*' if w['breach'] else ''}"

        state_s = "BURNING" if s.get("burning") else "ok"
        print(f"{s['name']:<16} {state_s:<8} {obj:<44} "
              f"{_w(s.get('fast')):>10} {_w(s.get('slow')):>10}")
    if any(s.get("burning") for s in rows):
        sys.exit(1)


def cmd_debug(args) -> None:
    """`debug dump`: one-shot whole-cluster post-mortem bundle."""
    if args.what != "dump":
        raise SystemExit(f"unknown debug subcommand {args.what!r}")
    _connect()
    from ray_tpu.experimental.state import api as state

    print(state.debug_dump(label=args.label))


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TiB"


def _render_hbm_rows(hbm) -> list:
    """Device-memory watermark table lines (shared by ``top`` and
    ``perf`` — one formatter, so the two surfaces can never disagree)."""
    out = [f"{'DEVICE MEMORY':<30} {'IN-USE':>10} {'LIMIT':>10} "
           f"{'PEAK':>10}"]
    for row in hbm:
        t = row.get("tags", {})
        label = (f"{t.get('kind', '?')}/dev{t.get('device', '?')} "
                 f"@{t.get('origin', 'head')}")
        limit = row.get("bytes_limit")
        peak = row.get("peak_bytes_in_use")
        out.append(
            f"{label[:29]:<30} "
            f"{_fmt_bytes(row.get('bytes_in_use')):>10} "
            f"{_fmt_bytes(limit) if limit is not None else '-':>10} "
            f"{_fmt_bytes(peak) if peak is not None else '-':>10}")
    return out


def _render_top(snap: dict, sort: str) -> str:
    """One ``top`` frame as text (htop-style, data from the head's
    per-entity sampler + ownership audit)."""
    out = []
    tasks = snap.get("tasks", {})
    store = snap.get("store", {})
    out.append(
        f"ray_tpu top — nodes {len(snap['nodes'])}  "
        f"workers {len(snap['workers'])}  "
        f"tasks P/R/F: {tasks.get('PENDING', 0)}/{tasks.get('RUNNING', 0)}/"
        f"{tasks.get('FINISHED', 0)}  "
        f"store {_fmt_bytes(store.get('bytes_used'))} "
        f"in {store.get('num_objects', 0)} objects"
        + (f"  ORPHANED {_fmt_bytes(snap['orphan_bytes'])}"
           if snap.get("orphan_bytes") else ""))
    out.append("")
    out.append(f"{'NODE':<22} {'ALIVE':<6} {'UTIL':>5} {'LOAD1':>6} "
               f"{'MEM-AVAIL':>10}")
    for n in snap["nodes"]:
        hs = n.get("host_stats") or {}
        out.append(
            f"{n['node_id']:<22} {str(n['alive']):<6} "
            f"{n['utilization'] * 100:>4.0f}% "
            f"{hs.get('load_1m', 0):>6.2f} "
            f"{hs.get('mem_available_mb', 0):>8.0f}MB")
    out.append("")
    key = {"cpu": lambda w: -(w.get("cpu_pct") or 0),
           "rss": lambda w: -(w.get("rss_mb") or 0),
           "pinned": lambda w: -(w.get("pinned_bytes") or 0)}[sort]
    out.append(f"{'WORKER':<18} {'KIND':<18} {'NODE':<14} {'PID':>7} "
               f"{'STATE':<9} {'CPU%':>6} {'RSS':>9} {'FDS':>5} {'PINNED':>10}")
    for w in sorted(snap["workers"], key=key):
        kind = w.get("actor_class") or w["kind"]
        rss = w.get("rss_mb")
        cpu = w.get("cpu_pct")
        out.append(
            f"{w['worker_id'][:16]:<18} {kind[:17]:<18} "
            f"{w['node_id'][:13]:<14} {w.get('pid') or '-':>7} "
            f"{w['state']:<9} "
            f"{f'{cpu:.1f}' if cpu is not None else '-':>6} "
            f"{f'{rss:.0f}MB' if rss is not None else '-':>9} "
            f"{int(w['open_fds']) if w.get('open_fds') is not None else '-':>5} "
            f"{_fmt_bytes(w.get('pinned_bytes')):>10}")
    hbm = snap.get("hbm") or []
    if hbm:
        out.append("")
        out.extend(_render_hbm_rows(hbm))
    owners = snap.get("owners") or []
    if owners:
        out.append("")
        out.append(f"{'OWNER (pinned bytes)':<40} {'BYTES':>10} {'OBJECTS':>8}")
        for o in owners[:10]:
            label = o.get("owner_label", o["owner"])
            flag = "  [ORPHAN]" if o.get("orphan") else ""
            out.append(f"{label[:39]:<40} {_fmt_bytes(o['bytes']):>10} "
                       f"{o['objects']:>8}{flag}")
    namespaces = snap.get("namespaces") or []
    if namespaces:
        # per-tenant rollup: one row per namespace — a tenant's pinned
        # bytes and live actor count read off a single line
        out.append("")
        out.append(f"{'NAMESPACE':<28} {'BYTES':>10} {'OBJECTS':>8} "
                   f"{'ACTORS':>7} {'JOBS':>5}")
        for r in namespaces[:10]:
            out.append(f"{r['namespace'][:27]:<28} "
                       f"{_fmt_bytes(r['bytes']):>10} {r['objects']:>8} "
                       f"{r['actors']:>7} {r['jobs']:>5}")
    return "\n".join(out)


def cmd_top(args) -> None:
    """Live cluster resource view (``htop`` for the cluster): nodes,
    workers/actors sorted by CPU/RSS/pinned bytes, refreshed in place."""
    _connect()
    from ray_tpu.experimental.state import api as state

    i = 0
    try:
        while True:
            frame = _render_top(state.top_snapshot(), args.sort)
            if args.iterations != 1 and sys.stdout.isatty():
                print("\x1b[2J\x1b[H", end="")  # clear + home
            print(frame)
            i += 1
            if args.iterations and i >= args.iterations:
                return
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass


def cmd_slices(args) -> None:
    """Failure-domain view: one line per TPU slice with member health,
    draining state and the degraded flag doctor watches."""
    _connect()
    from ray_tpu.experimental.state import api as state

    rows = state.list_slices(limit=args.limit)
    if args.json:
        print(json.dumps(rows, indent=2, default=repr))
        return
    if not rows:
        print("no slices (no node joined with a slice id)")
        return
    print(f"{'SLICE':<28} {'HOSTS':>5} {'ALIVE':>5} {'DEAD':>4} STATE")
    for r in rows:
        state_s = ("DEGRADED" if r["degraded"]
                   else "draining" if r["draining"]
                   else "healthy" if r["dead_members"] == 0 else "dead")
        print(f"{r['slice_id']:<28} {len(r['members']):>5} "
              f"{r['alive_members']:>5} {r['dead_members']:>4} {state_s}")


def cmd_memory(args) -> None:
    """Object-ownership audit (``ray memory`` analog): bytes by owner and
    pin reason, per-object rows, orphan flags."""
    _connect()
    from ray_tpu.experimental.state import api as state

    audit = state.memory_summary(limit=args.limit)
    if args.json:
        print(json.dumps(audit, indent=2, default=repr))
        return
    frac = audit["attributed_frac"] * 100.0
    print(f"ray_tpu memory — {_fmt_bytes(audit['total_bytes'])} sealed in "
          f"{audit['num_objects']} objects; {frac:.1f}% attributed to an "
          f"owner; orphaned {_fmt_bytes(audit['orphan_bytes'])}")
    reasons = ", ".join(f"{r}={_fmt_bytes(b)}" for r, b in
                        sorted(audit["by_pin_reason"].items()))
    if reasons:
        print(f"pinned by: {reasons}")
    print()
    print(f"{'OWNER':<40} {'KIND':<8} {'BYTES':>10} {'OBJECTS':>8}")
    for o in audit["by_owner"]:
        flag = "  [ORPHAN: owner dead]" if o.get("orphan") else ""
        print(f"{o['owner_label'][:39]:<40} {o['owner_kind']:<8} "
              f"{_fmt_bytes(o['bytes']):>10} {o['objects']:>8}{flag}")
    namespaces = audit.get("by_namespace") or []
    if namespaces:
        print()
        print(f"{'NAMESPACE':<28} {'BYTES':>10} {'OBJECTS':>8} "
              f"{'ACTORS':>7} {'JOBS':>5}")
        for r in namespaces:
            print(f"{r['namespace'][:27]:<28} {_fmt_bytes(r['bytes']):>10} "
                  f"{r['objects']:>8} {r['actors']:>7} {r['jobs']:>5}")
    rows = audit.get("rows") or []
    if rows:
        print()
        # full object ids: they share a per-process prefix, so a truncated
        # id renders every row identical
        print(f"{'OBJECT':<34} {'SIZE':>10} {'WHERE':<10} {'OWNER':<28} "
              f"{'PIN':<10} {'AGE':>8}")
        for r in rows:
            flag = " [ORPHAN]" if r.get("orphan") else ""
            print(f"{r['object_id']:<34} {_fmt_bytes(r['size']):>10} "
                  f"{r['where'][:9]:<10} "
                  f"{r.get('owner_label', r['owner'])[:27]:<28} "
                  f"{r['pin_reason']:<10} {r['age_s']:>7.0f}s{flag}")


def cmd_metrics(args) -> None:
    """TSDB surface: without a name, the metric directory; with one, the
    queried series as JSON."""
    _connect()
    from ray_tpu.experimental.state import api as state

    if not args.name:
        for m in state.list_metrics():
            print(f"{m['name']:<44} {m['type']:<10} "
                  f"{m['num_series']:>4} series  "
                  f"origins: {', '.join(m['origins'][:4])}")
        return
    result = state.query_metric(args.name, window_s=args.window,
                                step_s=args.step, agg=args.agg)
    print(json.dumps(result, indent=2))


def cmd_perf(args) -> None:
    """Performance observability report: the step-phase breakdown
    (phases sum exactly to the profiled step wall), live MFU per rank +
    the TSDB trend, the jit compile-cache table, the HBM watermark, and
    decode attribution (TTFT/ITL + prefill interference)."""
    _connect()
    from ray_tpu.experimental.state import api as state

    s = state.perf_summary(window_s=args.window)
    if args.json:
        print(json.dumps(s, indent=2, default=repr))
        return
    st = s["steps"]
    out = [f"ray_tpu perf — {st['count']} profiled steps, "
           f"wall {st['wall_s']:.3f}s, {st['tokens']} tokens"]
    if st["phases"]:
        out.append("")
        out.append(f"{'PHASE':<12} {'SECONDS':>10} {'SHARE':>7}")
        for name, p in st["phases"].items():
            out.append(f"{name:<12} {p['s']:>10.4f} {p['frac'] * 100:>6.1f}%")
        total = sum(p["s"] for p in st["phases"].values())
        out.append(f"{'total':<12} {total:>10.4f} {'100.0%':>7}"
                   f"  (phases sum to measured step wall)")
    if st["last_mfu"]:
        mfus = ", ".join(f"{k}={v:.4f}"
                         for k, v in sorted(st["last_mfu"].items()))
        out.append("")
        out.append(f"live MFU: {mfus}")
    for series in (s.get("mfu_trend") or [])[:4]:
        pts = series.get("points") or []
        if pts:
            out.append(f"  trend {series.get('tags', {})}: {pts[0][1]:.4f} "
                       f"-> {pts[-1][1]:.4f} over {len(pts)} samples")
    comp = s.get("compiles") or []
    if comp:
        out.append("")
        out.append(f"{'JIT FN':<24} {'ORIGIN':<10} {'COMPILES':>8} "
                   f"{'SIGS':>5} {'HITS':>8} {'COMPILE-S':>10}")
        for e in comp[:12]:
            out.append(f"{e['fn'][:23]:<24} {e['origin'][:9]:<10} "
                       f"{e['compiles']:>8} {e['n_sigs']:>5} "
                       f"{e['hits']:>8} {e['compile_s']:>10.3f}")
    hbm = s.get("hbm") or []
    if hbm:
        out.append("")
        out.extend(_render_hbm_rows(hbm))

    def _pct(h, key, digits):
        # a percentile whose mass fell in the +inf overflow bucket has
        # no honest upper bound — render "> last_bound" instead
        v = h.get(key)
        if v is not None:
            return f"<={v * 1e3:.{digits}f}ms"
        return f">{(h.get('last_bound_s') or 0) * 1e3:.{digits}f}ms"

    dec = s.get("decode") or {}
    ttft, itl = dec.get("ttft"), dec.get("itl")
    interference = dec.get("interference") or {}
    if ttft or itl or interference:
        out.append("")
        out.append("decode attribution:")
        if ttft:
            out.append(
                f"  TTFT: {ttft['count']} samples, "
                f"mean {ttft['mean_s'] * 1e3:.1f}ms, "
                f"p50{_pct(ttft, 'p50_est_s', 1)} "
                f"p99{_pct(ttft, 'p99_est_s', 1)}")
        if itl:
            out.append(
                f"  ITL:  {itl['count']} samples, "
                f"mean {itl['mean_s'] * 1e3:.2f}ms, "
                f"p50{_pct(itl, 'p50_est_s', 2)} "
                f"p99{_pct(itl, 'p99_est_s', 2)}")
        for eid, m in interference.items():
            billed = m.get("excess_billed_to_prefill")
            billed_s = (f"{billed * 100:.0f}% of tick excess billed to "
                        f"prefill" if billed is not None
                        else "excess share n/a: no decode-only baseline")
            out.append(
                f"  {eid}: interference {m.get('interference_s', 0):.3f}s "
                f"({(m.get('interference_frac') or 0) * 100:.1f}% of "
                f"decode tick time; {billed_s}) over "
                f"{m.get('interleaved_ticks')} interleaved ticks")
    if not (st["count"] or comp or hbm or ttft or itl or interference):
        out.append("(no perf data recorded — run a StepProfiler-"
                   "instrumented train loop or serve LLM traffic; see "
                   "README 'Performance observability')")
    print("\n".join(out))


def cmd_profile(args) -> None:
    """Profiles, on demand and continuous.

    Default: dense on-demand sampling via the dashboard's /api/profile.
    ``--live`` reads the always-on plane instead (head ProfileStore —
    no new sampling, the history is already there); ``profile diff A B``
    emits differential folded stacks between the trailing B seconds and
    the A-second baseline before them; ``profile ledger`` prints the
    per-task CPU cost columns; ``profile list`` the origins with
    retained history.  ``--format collapsed`` (default for the
    continuous modes) is speedscope / flamegraph.pl ready."""
    import urllib.request

    rt = _connect()
    mode = args.rest[0] if args.rest else None
    if mode not in (None, "diff", "ledger", "list"):
        raise SystemExit(f"unknown profile mode {mode!r} "
                         "(expected: diff, ledger, list)")
    if args.live or mode in ("diff", "ledger", "list"):
        from ray_tpu.experimental.state import api as state

        if mode == "list":
            rows = state.list_profiles()
            print(json.dumps(rows, indent=2))
            return
        if mode == "ledger":
            led = state.profile_ledger(window_s=args.window)
            if args.format == "json":
                print(json.dumps(led, indent=2))
                return
            wall = led["per_task_wall_us"]
            print(f"per-task CPU ledger over the last {led['window_s']:.0f}s "
                  f"({led['tasks']} tasks, {wall:.1f}us wall/task):")
            for col, us in led["columns"].items():
                pct = 100.0 * us / wall if wall else 0.0
                print(f"  {col:20s} {us:10.2f}us  {pct:5.1f}%")
            print(f"  {'sum':20s} {led['sum_us']:10.2f}us  "
                  f"{led['sum_over_wall'] * 100:5.1f}%  (exactness check)")
            print(f"  overlapped worker CPU (pipelined, not on the wall): "
                  f"{led['overlapped_worker_cpu_us']:.2f}us/task")
            return
        if mode == "diff":
            if len(args.rest) != 3:
                raise SystemExit(
                    "usage: ray_tpu profile diff WINDOW_A WINDOW_B "
                    "(seconds; trailing B vs the A-long baseline before it)")
            d = state.profile_diff(window_a=float(args.rest[1]),
                                   window_b=float(args.rest[2]),
                                   origin=args.origin)
            body = (json.dumps(d, indent=2) if args.format == "json"
                    else d["collapsed"])
        else:  # --live
            q = state.get_profile(window_s=args.window, origin=args.origin)
            if args.format == "json":
                body = json.dumps(q, indent=2)
            else:
                body = "\n".join(
                    f"{stack.replace('|', ';')} {n}"
                    for stack, n in sorted(q["folded"].items(),
                                           key=lambda kv: -kv[1]))
        if args.out:
            with open(args.out, "w") as f:
                f.write(body + "\n")
            print(f"wrote profile to {args.out}")
        else:
            print(body)
        return
    snap = rt._private.worker.global_worker.client.request(
        {"type": "state_snapshot"})["value"]
    dash = snap.get("dashboard")
    if not dash:
        raise SystemExit("head has no dashboard; profiling needs it "
                         "(RAY_TPU_DASHBOARD_PORT >= 0)")
    duration = args.duration
    if duration > 30.0:
        # the dashboard clamps server-side; say so instead of silently
        # returning a shorter profile than asked for
        print("note: profile duration is capped at 30s by the dashboard",
              file=sys.stderr)
        duration = 30.0
    url = ("http://%s:%d/api/profile?duration=%s&format=%s"
           % (dash[0], dash[1], duration, args.format or "json"))
    if args.worker_id:
        url += f"&worker_id={args.worker_id}"
    with urllib.request.urlopen(url, timeout=duration + 60) as resp:
        body = resp.read().decode()
    if args.out:
        with open(args.out, "w") as f:
            f.write(body)
        print(f"wrote profile to {args.out}")
    else:
        print(body, end="" if body.endswith("\n") else "\n")


def cmd_serve_status(_args) -> None:
    """``serve status`` analog over the running cluster."""
    rt = _connect()
    from ray_tpu.serve._private.controller import (
        CONTROLLER_NAME, SERVE_NAMESPACE)

    try:
        controller = rt.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
    except Exception:
        print(json.dumps({}))  # serve not running
        return
    status = rt.get(controller.get_status.remote(), timeout=30)
    # submit all metric fetches, one shared deadline (dashboard._serve_status
    # shape — a slow controller costs one timeout, not one per deployment)
    refs = {n: controller.get_autoscaling_metrics.remote(n) for n in status}
    try:
        metrics = rt.get(list(refs.values()), timeout=10)
        for (name, _), m in zip(refs.items(), metrics):
            status[name]["autoscaling_metrics"] = m
    except Exception as e:  # noqa: BLE001
        status["_autoscaling_metrics_error"] = f"{type(e).__name__}: {e}"
    try:
        goal = rt.get(controller.get_deploy_config.remote(), timeout=10)
        if goal:  # goal (declarative config) vs actual (status above)
            status["_goal_config"] = goal
    except Exception:
        pass
    print(json.dumps(status, indent=2, default=repr))


def cmd_serve_deploy(args) -> None:
    """``serve deploy config.yaml`` analog: validate the declarative app
    config and PUT it to the head's REST endpoint."""
    import urllib.request

    with open(args.config) as f:
        text = f.read()
    try:
        config = json.loads(text)
    except json.JSONDecodeError:
        try:  # yaml if the environment provides it; never a hard dependency
            import yaml  # type: ignore

            config = yaml.safe_load(text)
        except ImportError:
            raise SystemExit(
                "config must be JSON (no yaml parser in this environment)")
    from ray_tpu.serve.schema import SchemaError, parse_deploy_config

    try:
        parse_deploy_config(config)  # client-side validation, better errors
    except SchemaError as e:
        raise SystemExit(f"invalid config: {e}")
    _connect()
    from ray_tpu._private.worker import global_worker

    snap = global_worker.client.request({"type": "state_snapshot"})["value"]
    dash = snap.get("dashboard")
    if not dash:
        raise SystemExit("head has no dashboard; cannot reach the serve REST API")
    req = urllib.request.Request(
        "http://%s:%d/api/serve/applications" % tuple(dash),
        data=json.dumps(config).encode(),
        headers={"Content-Type": "application/json"}, method="PUT")
    try:
        with urllib.request.urlopen(req, timeout=240) as resp:
            print(resp.read().decode())
    except urllib.error.HTTPError as e:
        # the endpoint's JSON error payload IS the diagnosis; show it
        raise SystemExit(f"deploy failed ({e.code}): {e.read().decode()}")


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="ray_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("start", help="start a head or join as a node")
    s.add_argument("--head", action="store_true")
    s.add_argument("--address", default=None, help="head host:port to join")
    s.add_argument("--authkey", default=None)
    s.add_argument("--num-cpus", type=int, default=None)
    s.add_argument("--num-tpus", type=int, default=None)
    s.add_argument("--shm-dir", default=None)
    s.set_defaults(fn=cmd_start)

    s = sub.add_parser("up", help="launch a cluster from a YAML spec")
    s.add_argument("config", help="cluster YAML (see autoscaler/commands.py)")
    s.set_defaults(fn=cmd_up)

    s = sub.add_parser("down", help="tear down a YAML-launched cluster")
    s.add_argument("config")
    s.set_defaults(fn=cmd_down)

    sub.add_parser("stop", help="stop the last started head").set_defaults(fn=cmd_stop)
    sub.add_parser("status", help="cluster summary").set_defaults(fn=cmd_status)

    s = sub.add_parser("list", help="state API tables")
    s.add_argument("what", choices=["actors", "tasks", "nodes", "objects",
                                    "workers", "placement_groups", "jobs",
                                    "traces", "slices", "tenants", "logs",
                                    "incidents", "slos"])
    s.add_argument("--limit", type=int, default=100)
    s.set_defaults(fn=cmd_list)

    s = sub.add_parser("submit", help="submit a job entrypoint")
    s.add_argument("--wait", action="store_true")
    s.add_argument("--timeout", type=float, default=600.0)
    s.add_argument("entrypoint", nargs=argparse.REMAINDER)
    s.set_defaults(fn=cmd_submit)

    s = sub.add_parser("job-logs")
    s.add_argument("job_id")
    s.set_defaults(fn=cmd_job_logs)

    s = sub.add_parser(
        "logs",
        help="cluster log plane: stream table, or task/actor/trace-"
             "correlated records from every node")
    s.add_argument("stream", nargs="?", default=None,
                   help="one stream (e.g. worker-<id>, job-<id>, head)")
    s.add_argument("--follow", "-f", action="store_true",
                   help="keep polling the head's cursor (Ctrl-C to stop)")
    s.add_argument("--errors", action="store_true",
                   help="only stderr/traceback lines")
    s.add_argument("--grep", default=None, help="substring filter")
    s.add_argument("--job", default=None)
    s.add_argument("--task", default=None, help="task id (hex)")
    s.add_argument("--actor", default=None, help="actor id (hex)")
    s.add_argument("--node", default=None)
    s.add_argument("--pid", type=int, default=None)
    s.add_argument("--tail", type=int, default=1000,
                   help="max records in the initial page")
    s.add_argument("--limit", type=int, default=1000,
                   help="max stream rows in the no-filter table")
    s.add_argument("--interval", type=float, default=1.0,
                   help="--follow poll period (s)")
    s.set_defaults(fn=cmd_logs)

    s = sub.add_parser("job-stop")
    s.add_argument("job_id")
    s.set_defaults(fn=cmd_job_stop)

    s = sub.add_parser("timeline", help="dump chrome-trace task timeline")
    s.add_argument("--out", default=None)
    s.set_defaults(fn=cmd_timeline)

    s = sub.add_parser(
        "events", help="flight-recorder events (cluster event table)")
    s.add_argument("--source", default=None,
                   help="filter: scheduler|object_store|streaming|serve|"
                        "train|actor|worker_pool|node|collective|"
                        "serve_llm|compiled_dag|trace|syncer|chaos|"
                        "autoscaler|perf|client_proxy|rllib")
    s.add_argument("--severity", default=None,
                   help="filter: DEBUG|INFO|WARNING|ERROR")
    s.add_argument("--limit", type=int, default=200)
    s.add_argument("--summary", action="store_true",
                   help="counts by source/severity instead of rows")
    s.set_defaults(fn=cmd_events)

    s = sub.add_parser(
        "trace",
        help="request traces: list, or span tree + critical path for one")
    s.add_argument("trace_id", nargs="?", default=None)
    s.add_argument("--limit", type=int, default=20)
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_trace)

    s = sub.add_parser(
        "doctor",
        help="pathology analysis over recorded events/tasks "
             "(exit 1 on findings)")
    s.add_argument("--json", action="store_true")
    s.add_argument("--live", action="store_true",
                   help="report the watchdog's current open incidents "
                        "instead of re-diagnosing from scratch")
    s.add_argument("--static", action="store_true",
                   help="also run the raylint static gate and fold its "
                        "new findings into the report/exit code")
    s.add_argument("--root", default=None,
                   help="checkout root for --static (default: the "
                        "ray_tpu package's parent, or cwd if the "
                        "baseline lives there)")
    s.set_defaults(fn=cmd_doctor)

    s = sub.add_parser(
        "incidents",
        help="watchdog incident lifecycle: tracked set, history, ack, "
             "or follow transitions live")
    s.add_argument("--follow", "-f", action="store_true",
                   help="keep polling and print state transitions")
    s.add_argument("--ack", default=None, metavar="ID",
                   help="acknowledge one open incident")
    s.add_argument("--history", default=None, metavar="ID",
                   help="one incident's full transition history")
    s.add_argument("--json", action="store_true")
    s.add_argument("--limit", type=int, default=200)
    s.add_argument("--interval", type=float, default=2.0,
                   help="--follow poll period (s)")
    s.set_defaults(fn=cmd_incidents)

    s = sub.add_parser(
        "slo",
        help="declared SLOs + multi-window burn-rate state (exit 1 "
             "when any objective is burning)")
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_slo)

    s = sub.add_parser(
        "debug",
        help="debug dump: write a whole-cluster post-mortem bundle "
             "under <session>/incidents/")
    s.add_argument("what", choices=["dump"])
    s.add_argument("--label", default=None,
                   help="bundle directory name (default dump-<ts>)")
    s.set_defaults(fn=cmd_debug)

    s = sub.add_parser(
        "lint",
        help="raylint static-analysis suite over the repo "
             "(8 invariant rules; exit 1 on non-baselined findings)")
    s.add_argument("--rule", action="append", default=None,
                   metavar="R1[,R2...]",
                   help="run only these rule ids (repeatable)")
    s.add_argument("--json", action="store_true")
    s.add_argument("--verbose", action="store_true",
                   help="also list baselined findings")
    s.add_argument("--update-baseline", action="store_true",
                   help="rewrite raylint_baseline.json from the current "
                        "findings (full-rule runs only)")
    s.add_argument("--root", default=None,
                   help="checkout root to analyze (default: the ray_tpu "
                        "package's parent, or cwd if the baseline lives "
                        "there)")
    s.set_defaults(fn=cmd_lint)

    s = sub.add_parser(
        "top", help="live cluster resource view (nodes, workers, pinned "
                    "bytes; Ctrl-C to exit)")
    s.add_argument("--interval", type=float, default=2.0)
    s.add_argument("--iterations", type=int, default=0,
                   help="frames to render (0 = forever); 1 prints once")
    s.add_argument("--sort", choices=["cpu", "rss", "pinned"], default="cpu")
    s.set_defaults(fn=cmd_top)

    s = sub.add_parser(
        "memory",
        help="object-ownership audit: bytes by owner/pin reason (`ray "
             "memory` analog)")
    s.add_argument("--limit", type=int, default=20,
                   help="per-object rows to show (aggregates cover all)")
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_memory)

    s = sub.add_parser(
        "slices",
        help="TPU slice failure domains: member health, draining, "
             "degraded flags")
    s.add_argument("--limit", type=int, default=100)
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_slices)

    s = sub.add_parser(
        "metrics", help="metrics TSDB: directory, or query one series")
    s.add_argument("name", nargs="?", default=None)
    s.add_argument("--window", type=float, default=3600.0)
    s.add_argument("--step", type=float, default=0.0)
    s.add_argument("--agg", choices=["last", "max", "min", "sum", "avg",
                                     "count"], default=None)
    s.set_defaults(fn=cmd_metrics)

    s = sub.add_parser(
        "perf",
        help="performance observability: step-phase breakdown, live "
             "MFU + trend, compile-cache table, HBM watermark, decode "
             "TTFT/ITL + prefill interference")
    s.add_argument("--window", type=float, default=1800.0,
                   help="MFU-trend window seconds (TSDB query)")
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_perf)

    s = sub.add_parser(
        "profile",
        help="profiles: on-demand sampling, the always-on plane "
             "(--live / diff / ledger / list)")
    s.add_argument("rest", nargs="*",
                   help="mode: diff WINDOW_A WINDOW_B | ledger | list "
                        "(none: on-demand or --live)")
    s.add_argument("--live", action="store_true",
                   help="read the continuous profiler's history instead "
                        "of sampling on demand")
    s.add_argument("--window", type=float, default=300.0,
                   help="trailing window seconds for --live/ledger")
    s.add_argument("--origin", default=None,
                   help="one origin ('head', worker id hex, "
                        "'agent:<node>', 'tenant-<job>'); default: all")
    s.add_argument("--duration", type=float, default=3.0,
                   help="on-demand sampling duration")
    s.add_argument("--worker-id", default=None, help="worker id hex")
    s.add_argument("--format", choices=["json", "collapsed"],
                   default=None)
    s.add_argument("--out", default=None, help="write to file")
    s.set_defaults(fn=cmd_profile)

    sub.add_parser(
        "serve-status", help="serve deployments + autoscaling state"
    ).set_defaults(fn=cmd_serve_status)

    s = sub.add_parser(
        "serve-deploy",
        help="deploy serve applications from a declarative JSON config")
    s.add_argument("config", help="path to the config file")
    s.set_defaults(fn=cmd_serve_deploy)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
