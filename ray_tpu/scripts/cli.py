"""ray_tpu CLI — ``ray start/stop/status/...`` analog.

Reference: ``python/ray/scripts/scripts.py`` (cluster lifecycle) and
``dashboard/modules/job/cli.py`` (job commands).  Run as
``python -m ray_tpu <command>``:

    start --head [--num-cpus N --num-tpus N]   run a head in the foreground
    start --address host:port [--authkey HEX]  join as a worker node agent
    stop                                       kill the last started head
    status                                     cluster resources/state
    list {actors,tasks,nodes,objects,workers,placement_groups,jobs}
    submit -- <entrypoint...>                  submit a job
    job-logs <job_id> / job-stop <job_id>
    timeline [--out FILE]                      chrome-trace of task events
    events [--source S --severity L --limit N] flight-recorder event table
    trace [TRACE_ID]                           span tree + critical path
    doctor                                     pathology analysis (exit 1 on findings)
    profile [--duration N --worker-id HEX]     sampling profile via the dashboard
    serve-status                               serve deployments + autoscaling
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

SESSION_FILE = "/tmp/ray_tpu/last_session.json"


def _session() -> dict:
    try:
        with open(SESSION_FILE) as f:
            return json.load(f)
    except OSError:
        raise SystemExit("no running ray_tpu session found (start one with "
                         "`python -m ray_tpu start --head`)")


def _connect():
    import ray_tpu

    ray_tpu.init(address="auto")
    return ray_tpu


def cmd_start(args) -> None:
    if args.head:
        import ray_tpu

        ray_tpu.init(num_cpus=args.num_cpus, num_tpus=args.num_tpus)
        from ray_tpu._private.worker import global_worker

        node = global_worker.node
        host, port = node.tcp_address
        print(f"ray_tpu head running: tcp://{host}:{port}")
        print(f"authkey: {node.authkey.hex()}")
        if node.dashboard:
            print("dashboard: http://%s:%d" % tuple(node.dashboard.address))
        print("join with: python -m ray_tpu start "
              f"--address {host}:{port} --authkey {node.authkey.hex()}")
        print("Ctrl-C to stop.")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            ray_tpu.shutdown()
    elif args.address:
        from ray_tpu._private.node_agent import NodeAgent

        authkey = bytes.fromhex(args.authkey or os.environ["RAY_TPU_AUTHKEY"])
        agent = NodeAgent(
            args.address, authkey, num_cpus=args.num_cpus,
            num_tpus=args.num_tpus, shm_dir=args.shm_dir,
        )
        agent.serve_forever()
    else:
        raise SystemExit("start needs --head or --address")


def cmd_up(args) -> None:
    """``ray up`` analog: start head + join workers per the YAML."""
    from ray_tpu.autoscaler.commands import load_cluster_config, up

    out = up(load_cluster_config(args.config))
    print(json.dumps(out, indent=2))
    print(f"cluster up: {out['address']} "
          f"({len(out['workers'])} worker nodes joining)")


def cmd_down(args) -> None:
    from ray_tpu.autoscaler.commands import down, load_cluster_config

    down(load_cluster_config(args.config))
    print("cluster down")


def cmd_stop(_args) -> None:
    sess = _session()
    pid = sess.get("pid")
    try:
        os.kill(pid, signal.SIGTERM)
        print(f"sent SIGTERM to head pid {pid}")
    except OSError as e:
        print(f"head pid {pid}: {e}")


def cmd_status(_args) -> None:
    rt = _connect()
    snap = rt._private.worker.global_worker.client.request(
        {"type": "state_snapshot"})["value"]
    print(json.dumps({
        "cluster_resources": snap["cluster_resources"],
        "available_resources": snap["available_resources"],
        "object_store": snap["object_store"],
        "nodes": len(snap["nodes"]),
        "actors": len(snap["actors"]),
        "tasks": len(snap["tasks"]),
    }, indent=2, default=repr))


def cmd_list(args) -> None:
    _connect()
    from ray_tpu.experimental.state import api as state

    rows = getattr(state, f"list_{args.what}")(limit=args.limit)
    print(json.dumps(rows, indent=2, default=repr))


def cmd_submit(args) -> None:
    sess = _session()
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(sess["address"],
                                 authkey=bytes.fromhex(sess["authkey"]))
    import shlex

    parts = args.entrypoint
    if parts and parts[0] == "--":  # argparse.REMAINDER keeps the separator
        parts = parts[1:]
    entry = shlex.join(parts)  # preserve each argv token through the shell
    job_id = client.submit_job(entrypoint=entry)
    print(f"submitted {job_id}: {entry}")
    if args.wait:
        status = client.wait_until_finish(job_id, timeout=args.timeout)
        print(client.get_job_logs(job_id), end="")
        print(f"job {job_id}: {status}")
        sys.exit(0 if status == "SUCCEEDED" else 1)


def cmd_job_logs(args) -> None:
    sess = _session()
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(sess["address"], authkey=bytes.fromhex(sess["authkey"]))
    print(client.get_job_logs(args.job_id), end="")


def cmd_job_stop(args) -> None:
    sess = _session()
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(sess["address"], authkey=bytes.fromhex(sess["authkey"]))
    print("stopped" if client.stop_job(args.job_id) else "not running")


def cmd_timeline(args) -> None:
    _connect()
    from ray_tpu.util.timeline import timeline_dump

    path = timeline_dump(args.out)
    print(f"wrote chrome trace to {path} (open in chrome://tracing)")


def cmd_events(args) -> None:
    """Flight-recorder events (``ray list cluster-events`` analog): the
    head's merged per-source event table — dispatch decisions, spills,
    OOM kills, stalls, admissions — as JSON lines or a summary."""
    _connect()
    from ray_tpu.experimental.state import api as state

    if args.summary:
        print(json.dumps(state.summarize_events(), indent=2))
        return
    rows = state.list_events(limit=args.limit, source=args.source,
                             severity=args.severity)
    for r in rows:
        print(json.dumps(r, default=repr))


def cmd_trace(args) -> None:
    """Request traces: without an id, list recent traces; with one, the
    assembled span tree + per-phase critical-path attribution."""
    _connect()
    from ray_tpu.experimental.state import api as state

    if not args.trace_id:
        rows = state.list_traces(limit=args.limit)
        if args.json:
            print(json.dumps(rows, indent=2, default=repr))
            return
        if not rows:
            print("(no traces recorded — run a workload inside "
                  "ray_tpu.util.tracing.trace(), or send serve traffic)")
            return
        for r in rows:
            print(f"{r['trace_id']}  {r['duration_s'] * 1e3:9.2f}ms  "
                  f"{r['num_spans']:4d} spans  {r['name']}")
        return
    trace = state.get_trace(args.trace_id)
    if trace is None:
        raise SystemExit(f"unknown trace {args.trace_id!r} (see "
                         f"`ray_tpu trace` for recent ids)")
    from ray_tpu.util.trace_analysis import analyze, render_trace

    analysis = analyze(trace)
    if args.json:
        trace["analysis"] = analysis
        print(json.dumps(trace, indent=2, default=repr))
    else:
        print(render_trace(trace, analysis))


def cmd_doctor(args) -> None:
    """Rule-based pathology analysis over the recorded event/task state;
    exits non-zero when findings exist so CI can gate on it."""
    _connect()
    from ray_tpu.util.doctor import render, run_doctor

    findings = run_doctor()
    if args.json:
        print(json.dumps(findings, indent=2, default=repr))
    else:
        print(render(findings))
    if findings:
        sys.exit(1)


def cmd_profile(args) -> None:
    """On-demand sampling profile via the dashboard's /api/profile —
    ``--format collapsed`` emits folded stacks for speedscope /
    flamegraph.pl."""
    import urllib.request

    rt = _connect()
    snap = rt._private.worker.global_worker.client.request(
        {"type": "state_snapshot"})["value"]
    dash = snap.get("dashboard")
    if not dash:
        raise SystemExit("head has no dashboard; profiling needs it "
                         "(RAY_TPU_DASHBOARD_PORT >= 0)")
    duration = args.duration
    if duration > 30.0:
        # the dashboard clamps server-side; say so instead of silently
        # returning a shorter profile than asked for
        print("note: profile duration is capped at 30s by the dashboard",
              file=sys.stderr)
        duration = 30.0
    url = ("http://%s:%d/api/profile?duration=%s&format=%s"
           % (dash[0], dash[1], duration, args.format))
    if args.worker_id:
        url += f"&worker_id={args.worker_id}"
    with urllib.request.urlopen(url, timeout=duration + 60) as resp:
        body = resp.read().decode()
    if args.out:
        with open(args.out, "w") as f:
            f.write(body)
        print(f"wrote profile to {args.out}")
    else:
        print(body, end="" if body.endswith("\n") else "\n")


def cmd_serve_status(_args) -> None:
    """``serve status`` analog over the running cluster."""
    rt = _connect()
    from ray_tpu.serve._private.controller import CONTROLLER_NAME

    try:
        controller = rt.get_actor(CONTROLLER_NAME)
    except Exception:
        print(json.dumps({}))  # serve not running
        return
    status = rt.get(controller.get_status.remote(), timeout=30)
    # submit all metric fetches, one shared deadline (dashboard._serve_status
    # shape — a slow controller costs one timeout, not one per deployment)
    refs = {n: controller.get_autoscaling_metrics.remote(n) for n in status}
    try:
        metrics = rt.get(list(refs.values()), timeout=10)
        for (name, _), m in zip(refs.items(), metrics):
            status[name]["autoscaling_metrics"] = m
    except Exception as e:  # noqa: BLE001
        status["_autoscaling_metrics_error"] = f"{type(e).__name__}: {e}"
    try:
        goal = rt.get(controller.get_deploy_config.remote(), timeout=10)
        if goal:  # goal (declarative config) vs actual (status above)
            status["_goal_config"] = goal
    except Exception:
        pass
    print(json.dumps(status, indent=2, default=repr))


def cmd_serve_deploy(args) -> None:
    """``serve deploy config.yaml`` analog: validate the declarative app
    config and PUT it to the head's REST endpoint."""
    import urllib.request

    with open(args.config) as f:
        text = f.read()
    try:
        config = json.loads(text)
    except json.JSONDecodeError:
        try:  # yaml if the environment provides it; never a hard dependency
            import yaml  # type: ignore

            config = yaml.safe_load(text)
        except ImportError:
            raise SystemExit(
                "config must be JSON (no yaml parser in this environment)")
    from ray_tpu.serve.schema import SchemaError, parse_deploy_config

    try:
        parse_deploy_config(config)  # client-side validation, better errors
    except SchemaError as e:
        raise SystemExit(f"invalid config: {e}")
    _connect()
    from ray_tpu._private.worker import global_worker

    snap = global_worker.client.request({"type": "state_snapshot"})["value"]
    dash = snap.get("dashboard")
    if not dash:
        raise SystemExit("head has no dashboard; cannot reach the serve REST API")
    req = urllib.request.Request(
        "http://%s:%d/api/serve/applications" % tuple(dash),
        data=json.dumps(config).encode(),
        headers={"Content-Type": "application/json"}, method="PUT")
    try:
        with urllib.request.urlopen(req, timeout=240) as resp:
            print(resp.read().decode())
    except urllib.error.HTTPError as e:
        # the endpoint's JSON error payload IS the diagnosis; show it
        raise SystemExit(f"deploy failed ({e.code}): {e.read().decode()}")


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="ray_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("start", help="start a head or join as a node")
    s.add_argument("--head", action="store_true")
    s.add_argument("--address", default=None, help="head host:port to join")
    s.add_argument("--authkey", default=None)
    s.add_argument("--num-cpus", type=int, default=None)
    s.add_argument("--num-tpus", type=int, default=None)
    s.add_argument("--shm-dir", default=None)
    s.set_defaults(fn=cmd_start)

    s = sub.add_parser("up", help="launch a cluster from a YAML spec")
    s.add_argument("config", help="cluster YAML (see autoscaler/commands.py)")
    s.set_defaults(fn=cmd_up)

    s = sub.add_parser("down", help="tear down a YAML-launched cluster")
    s.add_argument("config")
    s.set_defaults(fn=cmd_down)

    sub.add_parser("stop", help="stop the last started head").set_defaults(fn=cmd_stop)
    sub.add_parser("status", help="cluster summary").set_defaults(fn=cmd_status)

    s = sub.add_parser("list", help="state API tables")
    s.add_argument("what", choices=["actors", "tasks", "nodes", "objects",
                                    "workers", "placement_groups", "jobs",
                                    "traces"])
    s.add_argument("--limit", type=int, default=100)
    s.set_defaults(fn=cmd_list)

    s = sub.add_parser("submit", help="submit a job entrypoint")
    s.add_argument("--wait", action="store_true")
    s.add_argument("--timeout", type=float, default=600.0)
    s.add_argument("entrypoint", nargs=argparse.REMAINDER)
    s.set_defaults(fn=cmd_submit)

    s = sub.add_parser("job-logs")
    s.add_argument("job_id")
    s.set_defaults(fn=cmd_job_logs)

    s = sub.add_parser("job-stop")
    s.add_argument("job_id")
    s.set_defaults(fn=cmd_job_stop)

    s = sub.add_parser("timeline", help="dump chrome-trace task timeline")
    s.add_argument("--out", default=None)
    s.set_defaults(fn=cmd_timeline)

    s = sub.add_parser(
        "events", help="flight-recorder events (cluster event table)")
    s.add_argument("--source", default=None,
                   help="filter: scheduler|object_store|streaming|serve|"
                        "train|actor|worker_pool|node|collective|"
                        "serve_llm|compiled_dag|trace")
    s.add_argument("--severity", default=None,
                   help="filter: DEBUG|INFO|WARNING|ERROR")
    s.add_argument("--limit", type=int, default=200)
    s.add_argument("--summary", action="store_true",
                   help="counts by source/severity instead of rows")
    s.set_defaults(fn=cmd_events)

    s = sub.add_parser(
        "trace",
        help="request traces: list, or span tree + critical path for one")
    s.add_argument("trace_id", nargs="?", default=None)
    s.add_argument("--limit", type=int, default=20)
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_trace)

    s = sub.add_parser(
        "doctor",
        help="pathology analysis over recorded events/tasks "
             "(exit 1 on findings)")
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_doctor)

    s = sub.add_parser(
        "profile", help="sampling profile of the head or a worker")
    s.add_argument("--duration", type=float, default=3.0)
    s.add_argument("--worker-id", default=None, help="worker id hex")
    s.add_argument("--format", choices=["json", "collapsed"],
                   default="json")
    s.add_argument("--out", default=None, help="write to file")
    s.set_defaults(fn=cmd_profile)

    sub.add_parser(
        "serve-status", help="serve deployments + autoscaling state"
    ).set_defaults(fn=cmd_serve_status)

    s = sub.add_parser(
        "serve-deploy",
        help="deploy serve applications from a declarative JSON config")
    s.add_argument("config", help="path to the config file")
    s.set_defaults(fn=cmd_serve_deploy)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
