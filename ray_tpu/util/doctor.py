"""``ray_tpu doctor`` — rule-based pathology analysis over recorded state.

The flight recorder (``_private/events.py``), the metric registry, and the
task table already RECORD every known pathology this runtime can hit —
backpressure stalls, spill thrash, OOM kills, gang restarts, split
starvation, poisoned/stuck compiled-graph channels, router saturation,
slow-node skew.  This module closes the loop: ``diagnose()`` runs the
rule set over the recorded rows and returns actionable findings WITH the
evidence rows, so an operator staring at a p99 regression gets "streaming
pump stalled 4.2s on backpressure (budget 1); raise the block budget or
speed up the consumer" instead of a wall of DEBUG events.

Rules are thresholded against healthy baselines (a backpressured streaming
pipeline is the design working, not a pathology — it takes sustained stall
seconds to flag), and a clean run returns ``[]``: the bench harness runs
``diagnose`` at the end as a false-positive gate.

Pure functions over row lists — testable without a cluster; ``run_doctor``
is the thin live-cluster wrapper the CLI uses.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

# finding severities mirror event severities (ERROR > WARNING > INFO)
_SEV_ORDER = {"ERROR": 0, "WARNING": 1, "INFO": 2}

# -- rule thresholds (shared with the tests; module-level so an operator
# can tune them for an unusual deployment) ---------------------------------
STALL_TOTAL_S = 0.5       # cumulative pump stall that counts as a stall
STARVATION_TOTAL_S = 2.0  # cumulative consumer starvation seconds
SPILL_COUNT = 3           # spills before "thrash"
CHANNEL_WAIT_STUCK_S = 5.0  # one channel wait this long = stuck
ROUTER_STALL_COUNT = 1    # saturated-router stalls (replicas > 0)
WORKER_CHURN_COUNT = 3    # unexpected worker deaths
DRAIN_STUCK_S = 15.0      # a drain still open this long after starting
                          # (relative to the newest recorded event)
SKEW_RATIO = 3.0          # slowest-node / fastest-node mean exec ratio
SKEW_MIN_TASKS = 5        # per (task name, node) sample floor
SKEW_MIN_DELTA_S = 0.05   # absolute mean gap floor (noise guard)

# -- trend-rule thresholds (over TSDB series — slopes only a time series
# can express; point-in-time snapshots cannot false-positive OR true-
# positive on any of these) -------------------------------------------------
TREND_MIN_POINTS = 6        # samples before any slope is trusted
RSS_SLOPE_MB_PER_MIN = 5.0  # per-process RSS growth rate to flag
RSS_GROWTH_MIN_MB = 64.0    # absolute growth floor (warmup noise guard)
RSS_MONOTONE_FRAC = 0.8     # fraction of deltas that must be increases
STORE_SLOPE_MB_PER_MIN = 16.0  # object-store bytes growth rate to flag
STORE_GROWTH_MIN_MB = 64.0
QUEUE_CLIMB_MIN_DEPTH = 1.0  # queue never drained below this AND
QUEUE_CLIMB_RATIO = 2.0      # ended >= this multiple of where it started

# -- perf-rule thresholds (over the util/perf.py step profiler's and
# serve/llm.py tick meter's `perf` events — signals only device-time
# attribution can express) ---------------------------------------------------
RECOMPILE_STORM_SIGS = 5     # distinct shape signatures for ONE jit fn
                             # (multi-bucket prefill legitimately holds 4)
INGEST_FRACTION = 0.30       # ingest-wait share of step wall to flag
INGEST_MIN_STEPS = 5         # profiled steps before the share is trusted
PREFILL_INTERFERENCE_FRAC = 0.20  # interference share of decode tick time
PREFILL_MIN_TICKS = 20       # interleaved ticks before the share is trusted
MFU_DROP_FRAC = 0.10         # trailing-window MFU drop vs the earlier mean
MFU_MIN_LEVEL = 0.02         # earlier-mean floor (CPU dev noise guard)
TENANT_REAP_STUCK_S = 10.0   # death with no reap for this long = wedged
TENANT_KILL_RECENT_S = 120.0  # explained incident stays visible this long

# -- continuous-profiling thresholds (signals only the always-on sampler
# and the lock-timing plane can express) -------------------------------------
GIL_SATURATION_FRAC = 0.35   # sustained off-GIL fraction to call a process
                             # core-bound (healthy loaded heads sit < 0.2)
GIL_MIN_POINTS = 4           # sustained means: the whole trailing stretch
LOCK_WAIT_MIN_S = 1.0        # measured wait a lock must accumulate over the
                             # window before its ratio is worth reading
LOCK_WAIT_HOLD_RATIO = 2.0   # waiters paid >= 2x the hold behind them: a
                             # convoy, not incidental contention
SERIALIZATION_HOT_FRAC = 0.35  # share of sampled busy time inside
                               # serialization frames to flag


def _finding(rule: str, severity: str, summary: str,
             evidence: Sequence[dict], remedy: str) -> dict:
    return {
        "rule": rule,
        "severity": severity,
        "summary": summary,
        "remedy": remedy,
        "count": len(evidence),
        "evidence": list(evidence)[:5],
    }


def _rows(events: Sequence[dict], source: str,
          message: Optional[str] = None,
          prefix: Optional[str] = None) -> List[dict]:
    out = []
    for e in events:
        if e.get("source") != source:
            continue
        m = e.get("message", "")
        if message is not None and m != message:
            continue
        if prefix is not None and not m.startswith(prefix):
            continue
        out.append(e)
    return out


# ---------------------------------------------------------------------------
# rules (each: events, tasks -> finding | None)
# ---------------------------------------------------------------------------

def _rule_backpressure_stall(events, tasks):
    stalls = _rows(events, "streaming", "backpressure stall")
    # total_stalled_s is cumulative per executor: take each executor's max
    # (rows don't carry an executor id — op is the closest key)
    by_op: Dict[str, float] = {}
    for r in stalls:
        d = r.get("data") or {}
        op = str(d.get("op", "?"))
        by_op[op] = max(by_op[op] if op in by_op else 0.0,
                        float(d.get("total_stalled_s") or 0.0))
    total = sum(by_op.values())
    if total < STALL_TOTAL_S:
        return None
    return _finding(
        "backpressure_stall", "WARNING",
        f"streaming pump stalled {total:.2f}s on per-split block budgets "
        f"(ops: {', '.join(sorted(by_op))})",
        stalls,
        "consumers are slower than the pipeline: raise "
        "RAY_TPU_STREAMING_BLOCK_BUDGET / max_in_flight_blocks, speed up "
        "the consumer, or add splits")


def _rule_split_starvation(events, tasks):
    rows = _rows(events, "streaming", "split starved")
    total = sum(float((r.get("data") or {}).get("wait_s") or 0.0)
                for r in rows)
    if total < STARVATION_TOTAL_S:
        return None
    return _finding(
        "split_starvation", "WARNING",
        f"streaming consumers sat {total:.2f}s on empty splits "
        f"({len(rows)} waits) — the pipeline can't keep up",
        rows,
        "producers are the bottleneck: add parallelism to the source/map "
        "stage or raise the block budget so submission runs ahead")


def _rule_spill_thrash(events, tasks):
    rows = _rows(events, "object_store", "spilled object to disk")
    if len(rows) < SPILL_COUNT:
        return None
    mb = sum(float((r.get("data") or {}).get("size_mb") or 0.0)
             for r in rows)
    return _finding(
        "spill_thrash", "WARNING",
        f"object store spilled {len(rows)} objects (~{mb:.0f} MB) to disk",
        rows,
        "working set exceeds shm capacity: raise the object-store "
        "capacity, free refs sooner, or stream instead of materializing")


def _rule_oom_kills(events, tasks):
    rows = _rows(events, "scheduler", "OOM kill")
    if not rows:
        return None
    return _finding(
        "oom_kills", "ERROR",
        f"{len(rows)} worker(s) OOM-killed by the memory monitor",
        rows,
        "tasks exceed per-worker memory: lower per-node concurrency, "
        "shrink task working sets, or add memory/nodes")


def _rule_gang_restart(events, tasks):
    restarts = _rows(events, "train", "gang restarted")
    failures = _rows(events, "train", prefix="gang failure")
    if not restarts and not failures:
        return None
    return _finding(
        "gang_restart", "ERROR" if failures else "WARNING",
        f"train gang restarted {len(restarts)}x / "
        f"{len(failures)} rank failure(s)",
        failures + restarts,
        "a rank is dying mid-training (see the evidence rows' error "
        "field): check worker OOMs/preemptions; checkpoints bound lost "
        "work")


def _rule_stuck_channel(events, tasks):
    dead = [r for r in _rows(events, "compiled_dag")
            if r.get("severity") == "ERROR"]
    # only SEND-side waits count as stuck: a long recv wait is a loop
    # idling between requests (normal), a long blocked put means the
    # consumer stopped draining
    stuck = [r for r in _rows(events, "compiled_dag", "channel wait")
             if float(r.get("span_dur") or 0.0) >= CHANNEL_WAIT_STUCK_S
             and (r.get("data") or {}).get("op") == "send"]
    if not dead and not stuck:
        return None
    return _finding(
        "stuck_channel", "ERROR" if dead else "WARNING",
        f"compiled-graph channels unhealthy: {len(dead)} loop death(s), "
        f"{len(stuck)} channel wait(s) >= {CHANNEL_WAIT_STUCK_S:.0f}s",
        dead + stuck,
        "a node loop died (poisoning its edges) or a stage starves its "
        "peers: check the ERROR rows' actor, teardown() and recompile; "
        "balance stage times or raise max_inflight")


def _rule_router_saturation(events, tasks):
    rows = [r for r in _rows(events, "serve",
                             "router stalled: no replica available")
            if (r.get("data") or {}).get("replicas", 0) > 0]
    if len(rows) < ROUTER_STALL_COUNT:
        return None
    return _finding(
        "router_saturation", "WARNING",
        f"serve router(s) stalled {len(rows)}x with every replica at "
        f"max_concurrent_queries",
        rows,
        "replicas are saturated: raise num_replicas (or autoscaling "
        "max), raise max_concurrent_queries, or speed up the handler")


def _rule_ingress_shedding(events, tasks):
    """The serve ingress is ACTIVELY refusing work: a ``shedding
    started`` episode (router backlog watermark or proxy in-flight cap)
    with no later ``stopped`` for the same entity is an open overload
    incident.  Shedding that started and stopped is the mechanism
    working — degradation was graceful, demand receded, nothing to page
    about — so doctor stays quiet once recovery lands."""
    started = _rows(events, "serve", "ingress shedding started")
    if not started:
        return None
    stopped = _rows(events, "serve", "ingress shedding stopped")
    last_stop: Dict[str, float] = {}
    for r in stopped:
        eid = str(r.get("entity_id"))
        last_stop[eid] = max(last_stop.get(eid, 0.0),
                             float(r.get("ts") or 0.0))
    open_rows: Dict[str, dict] = {}
    for r in started:
        eid = str(r.get("entity_id"))
        ts = float(r.get("ts") or 0.0)
        if ts > last_stop.get(eid, -1.0):
            prev = open_rows.get(eid)
            if prev is None or ts >= float(prev.get("ts") or 0.0):
                open_rows[eid] = r
    if not open_rows:
        return None
    who = ", ".join(sorted(open_rows))
    return _finding(
        "ingress_shedding", "WARNING",
        f"serve ingress is shedding load on {who} — requests are being "
        f"refused (503 + Retry-After) at the backlog watermark",
        list(open_rows.values()),
        "demand exceeds serving capacity: raise num_replicas (or the "
        "autoscaling max), raise max_queued_requests if the backlog is a "
        "burst, or speed up the handler; shedding that has stopped "
        "clears this finding")


def _rule_drain_stuck(events, tasks):
    """A graceful replica drain that neither finished nor timed out long
    after starting — in-flight requests (or live streams) are wedged on
    a replica the controller wants gone.  Terminal events (``replica
    drained`` / ``replica drain timeout``) close the incident; a drain
    that TIMED OUT is also surfaced (accepted work was cut off at the
    graceful window — the zero-lost-requests story has a hole)."""
    starts = _rows(events, "serve", "replica draining")
    if not starts:
        return None
    done = _rows(events, "serve", "replica drained")
    timeouts = _rows(events, "serve", "replica drain timeout")
    closed: Dict[str, float] = {}
    for r in done + timeouts:
        eid = str(r.get("entity_id"))
        closed[eid] = max(closed.get(eid, 0.0), float(r.get("ts") or 0.0))
    # "now" inside a recorded-event table is the newest row's timestamp
    now = max((float(e.get("ts") or 0.0) for e in events), default=0.0)
    stuck = []
    for r in starts:
        eid = str(r.get("entity_id"))
        ts = float(r.get("ts") or 0.0)
        if ts > closed.get(eid, -1.0) and now - ts >= DRAIN_STUCK_S:
            stuck.append(r)
    if not stuck and not timeouts:
        return None
    sev = "ERROR" if stuck else "WARNING"
    summary = []
    if stuck:
        summary.append(
            f"{len(stuck)} replica drain(s) open > {DRAIN_STUCK_S:.0f}s")
    if timeouts:
        summary.append(
            f"{len(timeouts)} drain(s) hit the graceful window with "
            "requests still in flight")
    return _finding(
        "drain_stuck", sev,
        "graceful replica draining is not completing: "
        + "; ".join(summary),
        stuck + timeouts,
        "a handler is outliving graceful_shutdown_timeout_s: shorten "
        "request runtimes, raise the graceful window, or accept the "
        "cutoff (the evidence rows carry the in-flight counts)")


def _rule_tenant_killed(events, tasks):
    """A tenant's driver died.  Two shapes: a death with NO matching
    "tenant reaped" is an OPEN incident (the head's reap is wedged —
    that job's actors and pins are leaking) and stays ERROR until the
    reap lands; a death whose reap completed is EXPLAINED at WARNING
    while recent (``TENANT_KILL_RECENT_S`` against the event table's own
    clock), then the rule goes quiet — the cluster is healthy again and
    the incident is history, not a finding."""
    deaths = _rows(events, "client_proxy", "tenant driver died")
    if not deaths:
        return None
    reaps = _rows(events, "client_proxy", "tenant reaped")
    reaped_ts: Dict[str, float] = {}
    for r in reaps:
        eid = str(r.get("entity_id"))
        reaped_ts[eid] = max(reaped_ts.get(eid, 0.0), float(r.get("ts") or 0.0))
    now = max((float(e.get("ts") or 0.0) for e in events), default=0.0)
    open_, recent = [], []
    for r in deaths:
        eid = str(r.get("entity_id"))
        ts = float(r.get("ts") or 0.0)
        if reaped_ts.get(eid, -1.0) < ts:
            if now - ts >= TENANT_REAP_STUCK_S:
                open_.append(r)
        elif now - ts <= TENANT_KILL_RECENT_S:
            recent.append(r)
    if open_:
        return _finding(
            "tenant_killed", "ERROR",
            f"{len(open_)} tenant driver death(s) with no completed reap: "
            "the dead job's actors and object pins are still held",
            open_,
            "the head's client-disconnect reap did not run; check the "
            "head log for the tenant's job id")
    if recent:
        jobs = sorted({str(r.get("entity_id")) for r in recent})
        return _finding(
            "tenant_killed", "WARNING",
            f"tenant driver died and was reaped: {', '.join(jobs)} — "
            "non-detached actors killed, pins released; other tenants "
            "unaffected",
            recent,
            "no action needed unless the death was unexpected; the "
            "chaos/events tables show whether it was injected")
    return None


def _rule_worker_churn(events, tasks):
    rows = [r for r in _rows(events, "worker_pool", prefix="worker died")
            if r.get("severity") == "WARNING"]
    if len(rows) < WORKER_CHURN_COUNT:
        return None
    return _finding(
        "worker_churn", "WARNING",
        f"{len(rows)} workers died while holding tasks/actors",
        rows,
        "repeated unexpected worker deaths (segfaults, OOM, kills): "
        "check the per-worker logs under the session dir")


def _rule_log_error_burst(events, tasks):
    # the log store watches its ingest for error/traceback line bursts
    # from a single source — a worker spewing exceptions shows up here
    # before it dies (or without ever dying)
    rows = _rows(events, "log", prefix="error burst")
    if not rows:
        return None
    srcs = sorted({r.get("entity_id") for r in rows if r.get("entity_id")})
    return _finding(
        "log_error_burst", "WARNING",
        f"error/traceback log bursts from {len(srcs) or len(rows)} "
        f"source(s): {', '.join(srcs[:4])}",
        rows,
        "a process is emitting errors at a high rate: read them with "
        "`ray_tpu logs <stream> --errors` (or `ray_tpu logs --errors` "
        "cluster-wide) and check the owning task/actor")


def _rule_worker_stderr_at_death(events, tasks):
    # a worker died AND its shipped stderr tail held a traceback — the
    # crash explanation is already on the head, surface it next to the
    # death instead of making the user dig for the file
    rows = _rows(events, "log",
                 prefix="worker died with uncollected stderr")
    if not rows:
        return None
    sev = "ERROR" if any(r.get("severity") == "ERROR" for r in rows) \
        else "WARNING"
    # pull the first retained tail line into the summary: the point of
    # this rule is that the evidence IS the explanation
    tail_hint = ""
    for r in rows:
        tail = (r.get("data") or {}).get("tail") or []
        if tail:
            tail_hint = f" — last stderr: {tail[-1][:120]!r}"
            break
    return _finding(
        "worker_stderr_at_death", sev,
        f"{len(rows)} worker(s) died with unread stderr{tail_hint}",
        rows,
        "the dead worker's final stderr was captured before the death "
        "was processed: `ray_tpu logs <stream> --errors` or "
        "state.tail_log(stream, errors=True) has the full tail")


def _rule_slow_node_skew(events, tasks):
    # same task name, >=2 nodes, enough samples each: a node whose mean
    # exec time is SKEW_RATIO x the fastest is dragging the tail
    by_name_node: Dict[str, Dict[str, List[float]]] = {}
    for t in tasks or ():
        if t.get("exec_start") is None or t.get("exec_end") is None \
                or not t.get("node_id"):
            continue
        dur = t["exec_end"] - t["exec_start"]
        by_name_node.setdefault(t.get("name", "?"), {}) \
            .setdefault(t["node_id"], []).append(dur)
    worst = None
    for name, per_node in by_name_node.items():
        means = {n: sum(v) / len(v) for n, v in per_node.items()
                 if len(v) >= SKEW_MIN_TASKS}
        if len(means) < 2:
            continue
        fast_n, fast = min(means.items(), key=lambda kv: kv[1])
        slow_n, slow = max(means.items(), key=lambda kv: kv[1])
        if slow < fast * SKEW_RATIO or slow - fast < SKEW_MIN_DELTA_S:
            continue
        if worst is None or slow / max(fast, 1e-9) > worst["ratio"]:
            worst = {"name": name, "slow": slow_n, "fast": fast_n,
                     "ratio": slow / max(fast, 1e-9),
                     "slow_s": slow, "fast_s": fast}
    if worst is None:
        return None
    return _finding(
        "slow_node_skew", "WARNING",
        f"node {worst['slow']} runs {worst['name']!r} "
        f"{worst['ratio']:.1f}x slower than {worst['fast']} "
        f"({worst['slow_s'] * 1e3:.0f}ms vs {worst['fast_s'] * 1e3:.0f}ms "
        f"mean)",
        [worst],
        "a straggler node skews the gang/tail: check its host_stats on "
        "the dashboard (CPU steal, thermal, noisy neighbor) or drain it")


def _rule_slice_degraded(events, tasks):
    """A slice with a dead/paused member and NO replacement in flight.

    A slice is one failure domain: one dead host wedges any STRICT gang
    leased on it, and per-host healing can't restore the lease — the only
    remedy is slice-atomic replacement.  The head emits ``slice
    degraded`` when a member dies unexpectedly (deliberate scale-downs
    mark the slice draining first and stay silent); the autoscaler emits
    ``slice replacement started`` / ``replaced`` / ``failed`` as it
    heals.  A degraded slice whose LAST degradation has no completed
    replacement at or after it — and no replacement in flight (a
    ``started`` not superseded by a later ``failed``) — is an open
    incident; a FAILED replacement re-opens it (the slice is still
    degraded; suppressing on 'started' alone would keep doctor silent
    forever under e.g. persistent quota exhaustion)."""
    degraded = _rows(events, "node", "slice degraded")
    if not degraded:
        return None

    def _last_ts(source, message):
        out: Dict[str, float] = {}
        for r in _rows(events, source, message):
            sid = r.get("entity_id")
            out[sid] = max(out.get(sid, 0.0), float(r.get("ts") or 0.0))
        return out

    replaced = _last_ts("autoscaler", "slice replaced")
    started = _last_ts("autoscaler", "slice replacement started")
    failed = _last_ts("autoscaler", "slice replacement failed")
    last_degraded: Dict[str, dict] = {}
    for r in degraded:
        sid = r.get("entity_id")
        if (sid not in last_degraded
                or float(r.get("ts") or 0.0)
                >= float(last_degraded[sid].get("ts") or 0.0)):
            last_degraded[sid] = r

    def _open(sid, row):
        ts = float(row.get("ts") or 0.0)
        if replaced.get(sid, -1.0) >= ts:
            return False  # repair landed
        in_flight = (started.get(sid, -1.0) >= ts
                     and failed.get(sid, -1.0) < started.get(sid, -1.0))
        return not in_flight

    open_rows = [r for sid, r in sorted(last_degraded.items())
                 if _open(sid, r)]
    if not open_rows:
        return None
    sids = ", ".join(str(r.get("entity_id")) for r in open_rows)
    return _finding(
        "slice_degraded", "ERROR",
        f"slice(s) {sids} hold dead member(s) with no replacement in "
        f"flight — any STRICT gang on them is wedged",
        open_rows,
        "replace the slice atomically (TrendAutoscaler.repair_slices / "
        "provider.replace_slice, create-before-terminate); per-host "
        "replacement cannot restore the gang lease")


def _rule_recompile_storm(events, tasks):
    """One jit function accumulating many distinct shape signatures is a
    recompile storm: every new shape pays seconds of XLA compile on the
    hot path (the classic cause: un-bucketed dynamic batch/sequence
    shapes).  The step profiler's compile events carry ``n_sigs`` per
    function, so the storm is a counter, not a guess."""
    rows = _rows(events, "perf", "jit compile")
    worst: Dict[str, dict] = {}
    for r in rows:
        d = r.get("data") or {}
        fn = str(d.get("fn", "?"))
        if fn not in worst or (d.get("n_sigs") or 0) > (
                (worst[fn].get("data") or {}).get("n_sigs") or 0):
            worst[fn] = r
    storms = [r for r in worst.values()
              if ((r.get("data") or {}).get("n_sigs") or 0)
              >= RECOMPILE_STORM_SIGS]
    if not storms:
        return None
    names = ", ".join(
        f"{(r.get('data') or {}).get('fn')} "
        f"({(r.get('data') or {}).get('n_sigs')} signatures)"
        for r in storms)
    return _finding(
        "recompile_storm", "WARNING",
        f"jit recompile storm: {names} — every new shape signature pays "
        f"a fresh XLA compile on the hot path",
        storms,
        "bucket the dynamic dimensions (pad batch/sequence to a fixed "
        "set of shapes) or hoist the varying value out of the traced "
        "arguments; see the signatures in the evidence rows")


def _rule_ingest_bound(events, tasks):
    """Training that spends a large share of every step waiting on data
    is ingest-bound — the chip idles while the input pipeline catches
    up.  Only the step profiler's phase attribution can say this: a
    step-time histogram alone cannot split waiting from computing."""
    rows = _rows(events, "perf", "step phases")
    if len(rows) < INGEST_MIN_STEPS:
        return None
    wall = ingest = 0.0
    for r in rows:
        d = r.get("data") or {}
        phases = d.get("phases") or {}
        wall += float(d.get("wall_s") or r.get("span_dur") or 0.0)
        ingest += float(phases.get("ingest") or 0.0)
    if wall <= 0:
        return None
    frac = ingest / wall
    if frac < INGEST_FRACTION:
        return None
    ev = [{"steps": len(rows), "ingest_s": round(ingest, 4),
           "wall_s": round(wall, 4), "ingest_frac": round(frac, 4)}]
    return _finding(
        "ingest_bound", "WARNING",
        f"training is ingest-bound: {frac * 100:.0f}% of step wall "
        f"({ingest:.2f}s of {wall:.2f}s over {len(rows)} steps) waits "
        f"on data",
        ev,
        "the input pipeline can't keep up: raise streaming parallelism "
        "/ prefetch_blocks, move transforms off the train host, or "
        "shard the source wider")


def _rule_prefill_interference(events, tasks):
    """Decode ticks co-scheduled with prefill chunks run long — the
    serve engine's tick meter bills that excess to the prefills.  A high
    billed share IS the decode-tail explanation (gpt2 p99/p50=1.39x):
    bound it with chunked prefill or an interleave budget."""
    rows = _rows(events, "perf", "prefill interference")
    # latest meter state per (origin, engine): engine ids are per-process
    # (pids collide across hosts), so the shipping origin must qualify
    # the key or one replica's healthy meter shadows another's pathology
    latest: Dict[tuple, dict] = {}
    for r in rows:
        eid = (str(r.get("origin") or "head"), str(r.get("entity_id")))
        if eid not in latest or float(r.get("ts") or 0.0) >= float(
                latest[eid].get("ts") or 0.0):
            latest[eid] = r
    flagged = []
    for r in latest.values():
        d = r.get("data") or {}
        if (d.get("interleaved_ticks") or 0) >= PREFILL_MIN_TICKS \
                and (d.get("interference_frac") or 0.0) \
                >= PREFILL_INTERFERENCE_FRAC:
            flagged.append(r)
    if not flagged:
        return None
    worst = max((r.get("data") or {}).get("interference_frac", 0.0)
                for r in flagged)
    return _finding(
        "prefill_interference", "WARNING",
        f"prefill chunks are billed {worst * 100:.0f}% of decode tick "
        f"time on {len(flagged)} engine(s) — the decode tail is "
        f"prefill interference, not decode variance",
        flagged,
        "bound the interleave: chunk prefills smaller, cap admissions "
        "per tick, or disaggregate prefill onto its own replica "
        "(serve.llm.prefill_decode_graph)")


# ---------------------------------------------------------------------------
# trend rules (each: series_map -> finding | None).  series_map is
# {metric_name: [{"tags": {...}, "points": [[ts, value], ...]}, ...]} —
# the shape `query_metric` returns, so the rules run identically over a
# live TSDB and synthetic fixtures.
# ---------------------------------------------------------------------------

def _slope_per_min(points) -> float:
    """Least-squares slope in value-units per minute."""
    n = len(points)
    if n < 2:
        return 0.0
    xs = [p[0] for p in points]
    ys = [p[1] for p in points]
    mx = sum(xs) / n
    my = sum(ys) / n
    den = sum((x - mx) ** 2 for x in xs)
    if den <= 0:
        return 0.0
    return sum((x - mx) * (y - my) for x, y in points) / den * 60.0


def _monotone_frac(points) -> float:
    deltas = [b[1] - a[1] for a, b in zip(points, points[1:])]
    if not deltas:
        return 0.0
    return sum(1 for d in deltas if d > 0) / len(deltas)


def _trend_rule_rss_growth(series_map):
    """A worker whose RSS climbs monotonically for the whole window is
    leaking (or unboundedly caching) — a snapshot can't see it, a slope
    can."""
    worst = None
    for s in series_map.get("ray_tpu_proc_rss_mb", ()):
        pts = s.get("points") or []
        if len(pts) < TREND_MIN_POINTS:
            continue
        growth = pts[-1][1] - pts[0][1]
        slope = _slope_per_min(pts)
        mono = _monotone_frac(pts)
        if (slope >= RSS_SLOPE_MB_PER_MIN and growth >= RSS_GROWTH_MIN_MB
                and mono >= RSS_MONOTONE_FRAC):
            row = {"tags": s.get("tags", {}), "slope_mb_per_min": round(slope, 2),
                   "growth_mb": round(growth, 1), "monotone_frac": round(mono, 2),
                   "window_points": len(pts)}
            if worst is None or slope > worst["slope_mb_per_min"]:
                worst = row
    if worst is None:
        return None
    who = worst["tags"].get("worker_id", "?")
    return _finding(
        "rss_growth", "WARNING",
        f"process {who} RSS grew {worst['growth_mb']:.0f}MB at "
        f"{worst['slope_mb_per_min']:.1f}MB/min, "
        f"{worst['monotone_frac'] * 100:.0f}% monotone — memory leak "
        "suspect",
        [worst],
        "a worker/actor is accumulating memory: check for unbounded "
        "caches or growing actor state; restart_policy/max_calls bound "
        "the blast radius while you find it")


def _trend_rule_store_leak(series_map):
    """Object-store bytes climbing steadily means refs are being created
    faster than released — the 'who owns these 6 GiB' precursor."""
    for name in ("ray_tpu_object_store_bytes", "ray_tpu_arena_bytes_used"):
        for s in series_map.get(name, ()):
            pts = s.get("points") or []
            if len(pts) < TREND_MIN_POINTS:
                continue
            growth_mb = (pts[-1][1] - pts[0][1]) / (1 << 20)
            slope_mb = _slope_per_min(pts) / (1 << 20)
            if (slope_mb >= STORE_SLOPE_MB_PER_MIN
                    and growth_mb >= STORE_GROWTH_MIN_MB
                    and _monotone_frac(pts) >= RSS_MONOTONE_FRAC):
                ev = {"metric": name, "tags": s.get("tags", {}),
                      "slope_mb_per_min": round(slope_mb, 2),
                      "growth_mb": round(growth_mb, 1)}
                return _finding(
                    "object_store_leak", "WARNING",
                    f"{name} grew {growth_mb:.0f}MB at "
                    f"{slope_mb:.1f}MB/min without receding — object "
                    "refs are outliving their use",
                    [ev],
                    "run `ray_tpu memory` to see which owner holds the "
                    "bytes; del refs promptly, or stream instead of "
                    "materializing")
    return None


def _trend_rule_queue_climb(series_map):
    """A queue that never drains AND keeps climbing is demand outrunning
    capacity — backlog, not burst."""
    for s in series_map.get("ray_tpu_sched_queue_depth", ()):
        pts = s.get("points") or []
        if len(pts) < TREND_MIN_POINTS:
            continue
        lo = min(p[1] for p in pts)
        first = max(pts[0][1], QUEUE_CLIMB_MIN_DEPTH)
        last = pts[-1][1]
        if (lo >= QUEUE_CLIMB_MIN_DEPTH and last >= first * QUEUE_CLIMB_RATIO
                and _slope_per_min(pts) > 0):
            ev = {"tags": s.get("tags", {}), "min_depth": lo,
                  "start_depth": pts[0][1], "end_depth": last,
                  "slope_per_min": round(_slope_per_min(pts), 2)}
            return _finding(
                "queue_depth_climb", "WARNING",
                f"scheduler queue climbed {pts[0][1]:.0f} -> {last:.0f} "
                f"without ever draining below {lo:.0f} — sustained "
                "overload, not a burst",
                [ev],
                "demand exceeds cluster capacity: add nodes, lower "
                "submission rate, or batch smaller tasks into fewer "
                "larger ones")
    return None


def _trend_rule_mfu_regression(series_map):
    """Live MFU sagging against its own trailing history: the step
    profiler's per-step MFU gauge makes "the run got slower" a measured
    regression instead of an end-of-run surprise.  Compares the trailing
    quarter of the window against the earlier mean — a sustained drop,
    not a single slow step."""
    worst = None
    for s in series_map.get("ray_tpu_train_step_mfu", ()):
        pts = s.get("points") or []
        if len(pts) < 2 * TREND_MIN_POINTS:
            continue
        half = pts[:len(pts) // 2]
        tail = pts[-max(3, len(pts) // 4):]
        earlier = sum(p[1] for p in half) / len(half)
        trailing = sum(p[1] for p in tail) / len(tail)
        if earlier < MFU_MIN_LEVEL:
            continue
        drop = 1.0 - trailing / earlier
        if drop < MFU_DROP_FRAC:
            continue
        row = {"tags": s.get("tags", {}),
               "earlier_mfu": round(earlier, 4),
               "trailing_mfu": round(trailing, 4),
               "drop_frac": round(drop, 4),
               "window_points": len(pts)}
        if worst is None or drop > worst["drop_frac"]:
            worst = row
    if worst is None:
        return None
    return _finding(
        "mfu_regression", "WARNING",
        f"live MFU regressed {worst['drop_frac'] * 100:.0f}%: "
        f"{worst['earlier_mfu']:.3f} -> {worst['trailing_mfu']:.3f} "
        f"over the trailing window",
        [worst],
        "something slowed the step mid-run: check `ray_tpu perf` for a "
        "phase that grew (ingest? collective? a recompile storm?), HBM "
        "pressure, or a straggler rank")


def _trend_rule_gil_saturation(series_map):
    """A process whose continuous profiler keeps reporting high tick
    lateness is core-bound: its threads sit runnable behind the GIL.
    This is the measured number behind ROADMAP's "core-bound" label —
    sustained, not one hot burst."""
    worst = None
    for s in series_map.get("ray_tpu_gil_lateness_frac", ()):
        pts = s.get("points") or []
        if len(pts) < GIL_MIN_POINTS:
            continue
        tail = pts[-GIL_MIN_POINTS:]
        if min(p[1] for p in tail) < GIL_SATURATION_FRAC:
            continue
        mean = sum(p[1] for p in tail) / len(tail)
        row = {"tags": s.get("tags", {}), "mean_frac": round(mean, 3),
               "window_points": len(pts)}
        if worst is None or mean > worst["mean_frac"]:
            worst = row
    if worst is None:
        return None
    who = worst["tags"].get("origin", "a process")
    return _finding(
        "gil_saturation", "WARNING",
        f"{who} spends {worst['mean_frac'] * 100:.0f}% of sampled wall "
        "waiting for the GIL — the process is core-bound, threads will "
        "not help",
        [worst],
        "one interpreter core is the ceiling: move work into more "
        "worker processes, or — if this is the head — ROADMAP item 3 "
        "(native dispatch) is the structural fix; `ray_tpu profile "
        "--live --origin <who>` shows which frames own the core")


def _trend_rule_lock_contention(series_map):
    """A named lock whose measured wait outruns the hold behind it is a
    convoy: threads queue faster than the critical section drains.
    make_lock's timing plane measures both sides, so the ratio is
    arithmetic, not inference."""
    # cumulative gauges: the window's cost is last - first per series
    def _delta(name, tags):
        for s in series_map.get(name, ()):
            if s.get("tags") == tags:
                pts = s.get("points") or []
                if len(pts) >= 2:
                    return max(0.0, pts[-1][1] - pts[0][1])
        return 0.0

    worst = None
    for s in series_map.get("ray_tpu_lock_wait_s", ()):
        pts = s.get("points") or []
        if len(pts) < 2:
            continue
        tags = s.get("tags", {})
        wait = max(0.0, pts[-1][1] - pts[0][1])
        if wait < LOCK_WAIT_MIN_S:
            continue
        hold = _delta("ray_tpu_lock_hold_s", tags)
        ratio = wait / max(hold, 1e-6)
        if ratio < LOCK_WAIT_HOLD_RATIO:
            continue
        row = {"tags": tags, "wait_s": round(wait, 3),
               "hold_s": round(hold, 3), "ratio": round(ratio, 1)}
        if worst is None or wait > worst["wait_s"]:
            worst = row
    if worst is None:
        return None
    name = worst["tags"].get("lock", "?")
    if name.startswith(("node.", "profile_store")):
        remedy = (
            "the head control plane is convoying on its own lock — "
            "ROADMAP item 3 (native dispatch: refcounts and dispatch "
            "off the GIL) is the structural fix; until then shrink the "
            "critical section or shard the state it guards")
    else:
        remedy = (
            "threads queue on this lock faster than its critical "
            "section drains: shrink what runs under it, shard the "
            "guarded state, or hand the work to a single owner thread "
            "(RAY_TPU_LOCKPROF=1 captures every acquire for the trace)")
    return _finding(
        "lock_contention", "WARNING",
        f"lock {name}: threads waited {worst['wait_s']:.1f}s behind "
        f"{worst['hold_s']:.1f}s of holds ({worst['ratio']:.0f}x) over "
        "the window — a convoy",
        [worst], remedy)


def _trend_rule_serialization_hot(series_map):
    """Serialization frames owning a large share of all sampled busy
    time means the cluster ships bytes instead of doing work — the
    continuous profiler sees it cluster-wide, without anyone asking for
    a profile."""
    for s in series_map.get("ray_tpu_profile_serialization_frac", ()):
        pts = s.get("points") or []
        if len(pts) < GIL_MIN_POINTS:
            continue
        tail = pts[-GIL_MIN_POINTS:]
        if min(p[1] for p in tail) < SERIALIZATION_HOT_FRAC:
            continue
        mean = sum(p[1] for p in tail) / len(tail)
        ev = {"tags": s.get("tags", {}), "serialize_frac": round(mean, 3),
              "window_points": len(pts)}
        return _finding(
            "serialization_hot", "WARNING",
            f"{mean * 100:.0f}% of sampled busy time cluster-wide is "
            "serialization — the workload ships bytes instead of "
            "computing",
            [ev],
            "pass object refs instead of values, move big transfers "
            "onto the data plane (ROADMAP item 5: channel transport), "
            "and check `ray_tpu profile --live` for the pickle-heavy "
            "call sites")
    return None


TREND_RULES = (
    _trend_rule_rss_growth,
    _trend_rule_store_leak,
    _trend_rule_queue_climb,
    _trend_rule_mfu_regression,
    _trend_rule_gil_saturation,
    _trend_rule_lock_contention,
    _trend_rule_serialization_hot,
)

# metric names the live doctor pulls from the TSDB for the trend pass
TREND_METRICS = (
    "ray_tpu_proc_rss_mb",
    "ray_tpu_object_store_bytes",
    "ray_tpu_arena_bytes_used",
    "ray_tpu_sched_queue_depth",
    "ray_tpu_train_step_mfu",
    "ray_tpu_gil_lateness_frac",
    "ray_tpu_lock_wait_s",
    "ray_tpu_lock_hold_s",
    "ray_tpu_profile_serialization_frac",
)


def diagnose_trends(series_map: Dict[str, list]) -> List[dict]:
    """Run the trend rules over queried series (same finding shape as
    :func:`diagnose`; pure — feed it synthetic series in tests)."""
    findings = []
    for rule in TREND_RULES:
        f = rule(series_map)
        if f is not None:
            findings.append(f)
    findings.sort(key=lambda f: _SEV_ORDER.get(f["severity"], 9))
    return findings


RULES = (
    _rule_oom_kills,
    _rule_slice_degraded,
    _rule_gang_restart,
    _rule_stuck_channel,
    _rule_backpressure_stall,
    _rule_split_starvation,
    _rule_spill_thrash,
    _rule_router_saturation,
    _rule_ingress_shedding,
    _rule_drain_stuck,
    _rule_tenant_killed,
    _rule_worker_churn,
    _rule_log_error_burst,
    _rule_worker_stderr_at_death,
    _rule_slow_node_skew,
    _rule_recompile_storm,
    _rule_ingest_bound,
    _rule_prefill_interference,
)


def diagnose(events: Sequence[dict],
             tasks: Sequence[dict] = ()) -> List[dict]:
    """Run every rule over recorded events + task rows; returns findings
    sorted by severity (an empty list IS the healthy verdict)."""
    findings = []
    for rule in RULES:
        f = rule(events, tasks)
        if f is not None:
            findings.append(f)
    findings.sort(key=lambda f: _SEV_ORDER.get(f["severity"], 9))
    return findings


class DoctorState:
    """Incremental doctor evaluation — the watchdog-tick path.

    Instead of re-pulling up to 100k event rows per evaluation, the state
    holds a bounded trailing window of rows and ``feed()`` pulls only the
    *delta* since the last look via cursors: the head ``EventTable``'s
    ingest version and the process-local ring's seq.  ``diagnose()``
    re-runs the rule set only when new rows arrived (dirty flag) — an
    idle cluster's tick costs two cursor compares, not a diagnosis.

    Shared by the watchdog tick and the head's ``doctor_report`` RPC so
    the on-demand CLI and the continuous loop read one path."""

    def __init__(self, window_rows: int = 20_000,
                 event_window_s: Optional[float] = None):
        from collections import deque

        self._rows: "deque[dict]" = deque(maxlen=max(100, int(window_rows)))
        self._table_cursor = 0
        self._local_seq = 0
        self._dirty = True
        self._findings: List[dict] = []
        # sliding TIME window: with it set, diagnose() only sees rows
        # newer than now - event_window_s, so a finding whose evidence
        # aged out goes clear and its incident can auto-resolve.  Without
        # it (the one-shot RPC path) the full retained window is read.
        self._event_window_s = event_window_s

    def feed(self, table=None, local=None) -> bool:
        """Pull event deltas from the head EventTable and/or a local
        EventBuffer; returns True when anything new arrived."""
        new = False
        if table is not None:
            rows, self._table_cursor = table.since(self._table_cursor)
            if rows:
                self._rows.extend(rows)
                new = True
        if local is not None:
            rows = local.since(self._local_seq)
            if rows:
                self._local_seq = max(r.get("seq", 0) for r in rows)
                self._rows.extend(rows)
                new = True
        if new:
            self._dirty = True
        return new

    def feed_rows(self, rows: Sequence[dict]) -> None:
        """Direct row injection (tests / custom gathers)."""
        if rows:
            self._rows.extend(rows)
            self._dirty = True

    def diagnose(self, tasks: Sequence[dict] = (),
                 force: bool = False,
                 now: Optional[float] = None) -> List[dict]:
        """Event-rule findings over the current window; cached until the
        next ``feed()`` delta (``force=True`` re-runs regardless, e.g.
        when the task table changed without an event).  A time-windowed
        state re-runs whenever it holds rows — the window's trailing edge
        moves even when no new event arrives."""
        if self._event_window_s:
            if now is None:
                now = time.time()
            horizon = now - self._event_window_s
            # drop aged-out rows for good: the deque is append-only in
            # time, so popping from the left is exact
            while self._rows and self._rows[0].get("ts", now) < horizon:
                self._rows.popleft()
                self._dirty = True
            if self._dirty or force or self._findings:
                # table + local rows interleave slightly out of ts order,
                # so filter the survivors too (exact window, not just the
                # deque's left edge)
                rows = [r for r in self._rows
                        if r.get("ts", now) >= horizon]
                self._findings = diagnose(rows, tasks)
                self._dirty = False
        elif self._dirty or force:
            # the window holds table + local rows in arrival order; the
            # rules themselves sort nothing and tolerate interleaving
            self._findings = diagnose(list(self._rows), tasks)
            self._dirty = False
        return list(self._findings)

    @property
    def dirty(self) -> bool:
        return self._dirty

    def window_len(self) -> int:
        return len(self._rows)


def head_report(events_table, local_buffer, tsdb,
                tasks: Sequence[dict] = (),
                state: Optional[DoctorState] = None,
                trend_window_s: float = 1800.0) -> List[dict]:
    """One full doctor pass over HEAD-LOCAL tables — zero state-API
    pulls.  ``state`` carries the incremental window between calls (the
    watchdog's persistent DoctorState); without one, an ephemeral state
    reads the tables' full retained history (the ``doctor_report`` RPC's
    cold path, still head-local)."""
    st = state if state is not None else DoctorState()
    st.feed(table=events_table, local=local_buffer)
    findings = st.diagnose(tasks, force=state is None)
    series_map: Dict[str, list] = {}
    if tsdb is not None:
        for name in TREND_METRICS:
            try:
                q = tsdb.query(name, window_s=trend_window_s)
                series_map[name] = q.get("series", [])
            except Exception:  # noqa: BLE001 — a metric with no samples
                continue
    findings = findings + diagnose_trends(series_map)
    findings.sort(key=lambda f: _SEV_ORDER.get(f["severity"], 9))
    return findings


def run_doctor(limit: int = 100_000,
               trend_window_s: float = 1800.0) -> List[dict]:
    """Diagnose the live cluster.  The head runs the full pass over its
    own tables (one ``doctor_report`` RPC) — the client no longer issues
    two 100k-row ``list_events``/``list_tasks`` pulls per invocation.
    Falls back to the legacy client-side pull against a head without the
    RPC."""
    import warnings

    from ray_tpu.experimental.state import api as state

    try:
        findings = state.doctor_report(trend_window_s=trend_window_s)
        if isinstance(findings, list):
            return findings
    except Exception:  # noqa: BLE001 — old head / proxied client: fall
        # back to pulling the tables over the state API
        pass
    with warnings.catch_warnings():
        # the doctor reads capped tables knowingly; the truncation
        # warning is for listings presented as complete views
        warnings.simplefilter("ignore")
        events = state.list_events(limit=limit)
        tasks = state.list_tasks(limit=limit)
    findings = diagnose(events, tasks)
    series_map: Dict[str, list] = {}
    for name in TREND_METRICS:
        try:
            q = state.query_metric(name, window_s=trend_window_s)
            series_map[name] = q.get("series", [])
        except Exception:  # noqa: BLE001 — an old head without a TSDB
            # still gets the event/task diagnosis
            break
    findings.extend(diagnose_trends(series_map))
    findings.sort(key=lambda f: _SEV_ORDER.get(f["severity"], 9))
    return findings


def render(findings: List[dict]) -> str:
    """The doctor's report as text (what ``ray_tpu doctor`` prints)."""
    if not findings:
        return ("ray_tpu doctor: no findings — recorded state shows no "
                "known pathology.")
    out = [f"ray_tpu doctor: {len(findings)} finding(s)\n"]
    for f in findings:
        out.append(f"[{f['severity']}] {f['rule']}: {f['summary']}")
        out.append(f"  remedy: {f['remedy']}")
        for ev in f["evidence"][:3]:
            desc = {k: v for k, v in ev.items()
                    if k in ("ts", "message", "entity_id", "origin",
                             "data", "name", "slow", "fast", "ratio",
                             "tags", "metric", "slope_mb_per_min",
                             "growth_mb", "monotone_frac", "min_depth",
                             "start_depth", "end_depth", "slope_per_min",
                             "steps", "ingest_s", "wall_s", "ingest_frac",
                             "earlier_mfu", "trailing_mfu", "drop_frac",
                             "mean_frac", "wait_s", "hold_s",
                             "serialize_frac", "window_points",
                             "incident_id", "bundle_dir", "threshold")}
            out.append(f"  evidence: {desc}")
        if f["count"] > 3:
            out.append(f"  ... {f['count'] - 3} more evidence row(s)")
        out.append("")
    return "\n".join(out).rstrip()
