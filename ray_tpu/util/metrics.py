"""Application + runtime metrics (Counter/Gauge/Histogram).

Analog of ``ray.util.metrics`` (``python/ray/util/metrics.py``) over the
reference's OpenCensus pipeline (``src/ray/stats/metric.h:103-206``,
exported through the node metrics agent to Prometheus).  Here every
process keeps a local registry; workers ship periodic snapshots to the
head over their control connection, and the head's dashboard serves the
merged registry in Prometheus text exposition format at ``/metrics``.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Optional, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


class _Registry:
    def __init__(self):
        self.lock = threading.Lock()
        # name -> {"type", "help", "values": {labelkey: value-or-histogram}}
        self.metrics: Dict[str, dict] = {}

    def register(self, name: str, mtype: str, help_: str) -> dict:
        with self.lock:
            m = self.metrics.setdefault(
                name, {"type": mtype, "help": help_, "values": {}}
            )
            if m["type"] != mtype:
                raise ValueError(f"metric {name} already registered as {m['type']}")
            return m

    def snapshot(self) -> Dict[str, dict]:
        with self.lock:
            return {
                name: {"type": m["type"], "help": m["help"],
                       "values": dict(m["values"])}
                for name, m in self.metrics.items()
            }

    def merge(self, origin: str, snap: Dict[str, dict]) -> None:
        """Fold a remote process's snapshot in, labeled by origin."""
        with self.lock:
            for name, m in snap.items():
                cur = self.metrics.setdefault(
                    name, {"type": m["type"], "help": m["help"], "values": {}}
                )
                for key, value in m["values"].items():
                    cur["values"][tuple(key) + (("origin", origin),)] = value


_global = _Registry()


def _labelkey(tags: Optional[Dict[str, str]]) -> LabelKey:
    return tuple(sorted((tags or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Tuple[str, ...]] = None):
        self._name = name
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._m = _global.register(name, self._TYPE, description)

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> LabelKey:
        merged = dict(self._default_tags)
        merged.update(tags or {})
        return _labelkey(merged)


class Counter(Metric):
    _TYPE = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError(
                f"Counter.inc() takes a non-negative value, got {value} "
                "(counters are monotone; use a Gauge for values that fall)")
        key = self._key(tags)
        with _global.lock:
            vals = self._m["values"]
            vals[key] = vals.get(key, 0.0) + value


class Gauge(Metric):
    _TYPE = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        with _global.lock:
            self._m["values"][self._key(tags)] = float(value)


DEFAULT_BOUNDARIES = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60)


class Histogram(Metric):
    _TYPE = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Optional[Tuple[str, ...]] = None):
        self.boundaries = tuple(boundaries or DEFAULT_BOUNDARIES)
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        key = self._key(tags)
        with _global.lock:
            vals = self._m["values"]
            h = vals.get(key)
            if h is None:
                h = {"buckets": [0] * (len(self.boundaries) + 1),
                     "bounds": self.boundaries, "sum": 0.0, "count": 0}
                vals[key] = h
            h["buckets"][bisect.bisect_left(self.boundaries, value)] += 1
            h["sum"] += value
            h["count"] += 1


def registry() -> _Registry:
    return _global


def merge_snapshots(*snaps: Dict[str, dict]) -> Dict[str, dict]:
    """Combine registry snapshots (head + worker-reported) for exposition."""
    out: Dict[str, dict] = {}
    for snap in snaps:
        for name, m in snap.items():
            cur = out.setdefault(
                name, {"type": m["type"], "help": m["help"], "values": {}}
            )
            cur["values"].update(m["values"])
    return out


def _escape_label_value(v) -> str:
    """Prometheus exposition escaping for label values: backslash, double
    quote, and newline must be escaped or a crafted value (e.g. a
    user-chosen deployment name) corrupts the whole scrape."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def prometheus_text(snap: Optional[Dict[str, dict]] = None) -> str:
    """Render a registry snapshot in Prometheus exposition format (the
    ``prometheus_exporter.py`` analog)."""
    snap = snap if snap is not None else _global.snapshot()
    out: List[str] = []
    for name, m in sorted(snap.items()):
        if m["help"]:
            out.append(f"# HELP {name} {m['help']}")
        out.append(f"# TYPE {name} {m['type']}")
        for key, value in sorted(m["values"].items()):
            labels = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
            suffix = f"{{{labels}}}" if labels else ""
            if m["type"] == "histogram" and isinstance(value, dict):
                acc = 0
                for bound, cnt in zip(list(value["bounds"]) + ["+Inf"], value["buckets"]):
                    acc += cnt
                    lb = (labels + "," if labels else "") + f'le="{bound}"'
                    out.append(f"{name}_bucket{{{lb}}} {acc}")
                out.append(f"{name}_sum{suffix} {value['sum']}")
                out.append(f"{name}_count{suffix} {value['count']}")
            else:
                out.append(f"{name}{suffix} {value}")
    return "\n".join(out) + "\n"


class MetricsPusher:
    """Background thread shipping this process's registry to the head
    (the per-node metrics-agent push path).

    Send failures are retried with bounded exponential backoff — a
    transient head hiccup (GC pause, reconnect) must not permanently
    silence this process's metrics.  The loop only exits when
    :meth:`stop` is called or ``closed_fn`` reports the client closed."""

    def __init__(self, send_fn, origin: str, interval_s: float = 5.0,
                 closed_fn=None):
        self._send = send_fn
        self._origin = origin
        self._interval = interval_s
        self._closed = closed_fn
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="metrics-pusher")

    def start(self) -> "MetricsPusher":
        self._thread.start()
        return self

    def _loop(self) -> None:
        backoff = self._interval
        while not self._stop.wait(backoff):
            if self._closed is not None and self._closed():
                return
            snap = _global.snapshot()
            if not snap:
                backoff = self._interval
                continue
            try:
                self._send({"type": "metrics_report", "origin": self._origin,
                            "metrics": snap})
                backoff = self._interval
            except Exception:
                backoff = min(30.0, backoff * 2)

    def stop(self) -> None:
        self._stop.set()
