"""Application + runtime metrics (Counter/Gauge/Histogram).

Analog of ``ray.util.metrics`` (``python/ray/util/metrics.py``) over the
reference's OpenCensus pipeline (``src/ray/stats/metric.h:103-206``,
exported through the node metrics agent to Prometheus).  Here every
process keeps a local registry; workers ship periodic snapshots to the
head over their control connection, and the head's dashboard serves the
merged registry in Prometheus text exposition format at ``/metrics``.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.locks import make_lock

LabelKey = Tuple[Tuple[str, str], ...]


class _Registry:
    def __init__(self):
        self.lock = make_lock("metrics.registry")
        # name -> {"type", "help", "values": {labelkey: value-or-histogram}}
        self.metrics: Dict[str, dict] = {}
        # origin -> last merge wall time: dead origins (a worker that
        # exited, a node that left) stop refreshing and get expired by
        # expire_origins instead of polluting /metrics forever
        self.origin_seen: Dict[str, float] = {}
        # origin -> {metric name -> full label keys it last pushed}: the
        # replacement-merge and expiry index, so both touch only the
        # origin's OWN series (never a rebuild of the cross-origin dict)
        self.origin_keys: Dict[str, Dict[str, set]] = {}

    def register(self, name: str, mtype: str, help_: str) -> dict:
        with self.lock:
            m = self.metrics.setdefault(
                name, {"type": mtype, "help": help_, "values": {}}
            )
            if m["type"] != mtype:
                raise ValueError(f"metric {name} already registered as {m['type']}")
            return m

    def snapshot(self) -> Dict[str, dict]:
        with self.lock:
            return {
                name: {"type": m["type"], "help": m["help"],
                       "values": dict(m["values"])}
                for name, m in self.metrics.items()
            }

    def merge(self, origin: str, snap: Dict[str, dict]) -> None:
        """Fold a remote process's snapshot in, labeled by origin.

        REPLACEMENT semantics per (origin, metric): each push carries the
        origin's complete current value set for every metric it reports,
        so label series absent from this push no longer exist at the
        origin (a dead worker pid in a node agent's per-process gauges, a
        series retired via ``Metric.remove``) and must leave the merged
        view — accumulate-only merging kept them forever.  The
        ``origin_keys`` index makes the replacement O(this origin's
        series), not a rebuild of every origin's values."""
        origin_tag = ("origin", origin)
        with self.lock:
            self.origin_seen[origin] = time.time()
            prev = self.origin_keys.setdefault(origin, {})
            for name, m in snap.items():
                cur = self.metrics.setdefault(
                    name, {"type": m["type"], "help": m["help"], "values": {}}
                )
                vals = cur["values"]
                new_keys = set()
                for key, value in m["values"].items():
                    fk = tuple(key) + (origin_tag,)
                    vals[fk] = value
                    new_keys.add(fk)
                for fk in prev.get(name, set()) - new_keys:
                    vals.pop(fk, None)
                prev[name] = new_keys

    def expire_origins(self, max_age_s: float,
                       now: Optional[float] = None) -> List[str]:
        """Drop every merged label series whose origin has not pushed
        within ``max_age_s`` (3 push intervals at the head).  Without
        this, merge() keeps dead workers'/nodes' series forever and the
        merged registry grows monotonically with churn."""
        if now is None:
            now = time.time()
        with self.lock:
            stale = {o for o, ts in self.origin_seen.items()
                     if now - ts > max_age_s}
            if not stale:
                return []
            for o in stale:
                for name, keys in self.origin_keys.pop(o, {}).items():
                    m = self.metrics.get(name)
                    if m is None:
                        continue
                    for fk in keys:
                        m["values"].pop(fk, None)
                del self.origin_seen[o]
            return sorted(stale)


_global = _Registry()


def _labelkey(tags: Optional[Dict[str, str]]) -> LabelKey:
    return tuple(sorted((tags or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Tuple[str, ...]] = None):
        self._name = name
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._m = _global.register(name, self._TYPE, description)

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> LabelKey:
        merged = dict(self._default_tags)
        merged.update(tags or {})
        return _labelkey(merged)

    def remove(self, tags: Optional[Dict[str, str]] = None) -> bool:
        """Retire one label series (e.g. a per-worker gauge after that
        worker dies) without restarting the process.  Returns whether the
        series existed."""
        key = self._key(tags)
        with _global.lock:
            return self._m["values"].pop(key, None) is not None

    def label_sets(self) -> List[Dict[str, str]]:
        """The live label sets of this metric (samplers diff this against
        what they just observed to find series to retire)."""
        with _global.lock:
            return [dict(key) for key in self._m["values"]]


class Counter(Metric):
    _TYPE = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError(
                f"Counter.inc() takes a non-negative value, got {value} "
                "(counters are monotone; use a Gauge for values that fall)")
        key = self._key(tags)
        with _global.lock:
            vals = self._m["values"]
            vals[key] = vals.get(key, 0.0) + value


class Gauge(Metric):
    _TYPE = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        with _global.lock:
            self._m["values"][self._key(tags)] = float(value)


DEFAULT_BOUNDARIES = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60)


class Histogram(Metric):
    _TYPE = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Optional[Tuple[str, ...]] = None):
        self.boundaries = tuple(boundaries or DEFAULT_BOUNDARIES)
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        key = self._key(tags)
        with _global.lock:
            vals = self._m["values"]
            h = vals.get(key)
            if h is None:
                h = {"buckets": [0] * (len(self.boundaries) + 1),
                     "bounds": self.boundaries, "sum": 0.0, "count": 0}
                vals[key] = h
            h["buckets"][bisect.bisect_left(self.boundaries, value)] += 1
            h["sum"] += value
            h["count"] += 1


def registry() -> _Registry:
    return _global


def merge_snapshots(*snaps: Dict[str, dict]) -> Dict[str, dict]:
    """Combine registry snapshots (head + worker-reported) for exposition."""
    out: Dict[str, dict] = {}
    for snap in snaps:
        for name, m in snap.items():
            cur = out.setdefault(
                name, {"type": m["type"], "help": m["help"], "values": {}}
            )
            cur["values"].update(m["values"])
    return out


def _escape_label_value(v) -> str:
    """Prometheus exposition escaping for label values: backslash, double
    quote, and newline must be escaped or a crafted value (e.g. a
    user-chosen deployment name) corrupts the whole scrape."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def prometheus_text(snap: Optional[Dict[str, dict]] = None) -> str:
    """Render a registry snapshot in Prometheus exposition format (the
    ``prometheus_exporter.py`` analog)."""
    snap = snap if snap is not None else _global.snapshot()
    out: List[str] = []
    for name, m in sorted(snap.items()):
        if m["help"]:
            out.append(f"# HELP {name} {m['help']}")
        out.append(f"# TYPE {name} {m['type']}")
        for key, value in sorted(m["values"].items()):
            labels = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
            suffix = f"{{{labels}}}" if labels else ""
            if m["type"] == "histogram" and isinstance(value, dict):
                acc = 0
                for bound, cnt in zip(list(value["bounds"]) + ["+Inf"], value["buckets"]):
                    acc += cnt
                    lb = (labels + "," if labels else "") + f'le="{bound}"'
                    out.append(f"{name}_bucket{{{lb}}} {acc}")
                out.append(f"{name}_sum{suffix} {value['sum']}")
                out.append(f"{name}_count{suffix} {value['count']}")
            else:
                out.append(f"{name}{suffix} {value}")
    return "\n".join(out) + "\n"


def push_interval_s() -> float:
    """The cluster-wide metrics push cadence (workers, node agents, and
    the head's self-sample loop all tick at this; the head's TSDB and its
    origin-expiry windows are sized from it)."""
    try:
        return max(0.05, float(os.environ.get("RAY_TPU_METRICS_PUSH_S", "5")))
    except ValueError:
        return 5.0


def grid_ticks(interval_s: float, wait_fn):
    """Deadline-grid ticker shared by every sampling/push loop (this
    pusher, the node agent's resource sampler, the head's TSDB loop).

    Ticks are scheduled on a fixed grid (next = start + k*interval), not
    ``interval`` after the previous body finished: sleep-after-work
    drifts by the body's duration, and the TSDB's downsampling assumes
    uniform sample spacing.  Grid points the body overran are skipped
    (no burst catch-up; the grid phase is preserved).

    ``wait_fn(timeout) -> truthy`` ends the loop (an ``Event.wait``, or
    a sleep returning a shutdown flag).  Yields ``stalled``: True when
    the previous tick was delayed by more than one extra interval —
    loops that expire peers by timestamp must skip expiry on such a
    tick, because a stall of THIS process delays everyone's timestamps
    equally and would read every live peer as dead."""
    next_tick = time.monotonic() + interval_s
    last = time.monotonic()
    while True:
        if wait_fn(max(0.0, next_tick - time.monotonic())):
            return
        now = time.monotonic()
        next_tick += interval_s
        if next_tick <= now:  # body overran: skip to the next future
            next_tick = now + interval_s - ((now - next_tick) % interval_s)
        stalled = now - last > 2 * interval_s
        last = now
        yield stalled


class MetricsPusher:
    """Background thread shipping this process's registry to the head
    (the per-node metrics-agent push path).

    Ticks ride the shared deadline grid (:func:`grid_ticks`) so the
    sample spacing the head's TSDB assumes stays uniform under slow
    sends.  A failed send is simply retried at the NEXT grid tick — one
    small send per interval costs nothing, and any longer backoff would
    open a gap wider than the head's 3-interval origin-expiry window,
    letting a single transient failure pass for this process's death
    (wiping its series from /metrics and its TSDB history).  The loop
    only exits when :meth:`stop` is called or ``closed_fn`` reports the
    client closed."""

    def __init__(self, send_fn, origin: str, interval_s: Optional[float] = None,
                 closed_fn=None):
        self._send = send_fn
        self._origin = origin
        self._interval = interval_s if interval_s is not None \
            else push_interval_s()
        self._closed = closed_fn
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="metrics-pusher")

    def start(self) -> "MetricsPusher":
        self._thread.start()
        return self

    def _loop(self) -> None:
        for _ in grid_ticks(self._interval, self._stop.wait):
            if self._closed is not None and self._closed():
                return
            snap = _global.snapshot()
            if not snap:
                continue
            try:
                self._send({"type": "metrics_report", "origin": self._origin,
                            "metrics": snap})
            except Exception:
                pass  # retried at the next grid tick (see class docstring)

    def stop(self) -> None:
        self._stop.set()
