"""Per-trace critical-path analysis over assembled span trees.

Input is what ``experimental.state.api.get_trace`` returns: a flat list of
spans (``{name, span_id, parent_span_id, phase, source, start, end}``)
assembled by the head's TraceTable from flight-recorder span events plus
task-table rows.  This module answers the question the trace exists for:
*where did the wall time of this request go* — router admission vs
scheduler queue vs execution vs channel wait vs object transfer.

Method: a time sweep over the trace window attributing every instant to
the DEEPEST span covering it (nesting depth via the parent chain; ties go
to the later-started span).  That yields

- ``phases``: seconds per phase, summing exactly to the trace wall time
  (instants no span covers are ``idle`` — uninstrumented gaps), and
- ``critical_path``: the deepest-span sequence in time order — the chain
  of operations that actually gated completion; shortening anything OFF
  this path cannot shorten the request.

O(B * S) for B interval boundaries over S spans — traces are capped at
``RAY_TPU_TRACE_SPANS`` spans, so this stays interactive.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def _depths(spans: List[dict]) -> Dict[int, int]:
    """Nesting depth per span (id() keyed — span_ids may collide across
    malformed inputs and synthetic task sub-spans must stay distinct)."""
    by_id: Dict[str, dict] = {}
    for s in spans:
        sid = s.get("span_id")
        if sid:
            by_id.setdefault(sid, s)
    depths: Dict[int, int] = {}
    for s in spans:
        d = 0
        seen = set()
        cur = s
        while True:
            pid = cur.get("parent_span_id")
            if not pid or pid in seen or pid not in by_id:
                break
            seen.add(pid)
            cur = by_id[pid]
            d += 1
        depths[id(s)] = d
    return depths


def analyze(trace: Optional[dict]) -> dict:
    """Phase attribution + critical path for one assembled trace."""
    import heapq

    spans = [s for s in (trace or {}).get("spans", [])
             if s.get("start") is not None and s.get("end") is not None
             and s["end"] >= s["start"]]
    if not spans:
        return {"wall_s": 0.0, "num_spans": 0, "phases": {},
                "critical_path": []}
    depths = _depths(spans)
    start = min(s["start"] for s in spans)
    end = max(s["end"] for s in spans)
    bounds = sorted({s["start"] for s in spans} | {s["end"] for s in spans})
    # Sorted sweep with a lazy-deletion max-heap: O((S+B) log S), where a
    # per-interval covering rescan would be O(B*S) — a traced 10k-task job
    # joins ~30k spans and must stay interactive on the head's HTTP
    # thread.  Every span start/end is itself a boundary, so a span with
    # end > a covers the whole interval [a, b).
    by_start = sorted(spans, key=lambda s: s["start"])
    heap: List[tuple] = []  # (-depth, -start, end, tiebreak, span)
    si = 0
    phases: Dict[str, float] = {}
    segments: List[dict] = []
    for a, b in zip(bounds, bounds[1:]):
        if b <= a:
            continue
        while si < len(by_start) and by_start[si]["start"] <= a:
            s = by_start[si]
            heapq.heappush(
                heap, (-depths[id(s)], -s["start"], s["end"], si, s))
            si += 1
        while heap and heap[0][2] <= a:  # ended at/before this interval
            heapq.heappop(heap)
        if not heap:
            phases["idle"] = phases.get("idle", 0.0) + (b - a)
            continue
        deepest = heap[0][4]
        phase = deepest.get("phase") or "span"
        phases[phase] = phases.get(phase, 0.0) + (b - a)
        if segments and segments[-1]["_span"] is deepest:
            segments[-1]["end"] = b
        else:
            segments.append({"_span": deepest, "start": a, "end": b})
    critical = [
        {
            "name": seg["_span"].get("name", ""),
            "phase": seg["_span"].get("phase") or "span",
            "source": seg["_span"].get("source"),
            "span_id": seg["_span"].get("span_id"),
            "start": seg["start"],
            "duration_s": round(seg["end"] - seg["start"], 6),
        }
        for seg in segments
    ]
    return {
        "wall_s": round(end - start, 6),
        "num_spans": len(spans),
        "phases": {k: round(v, 6) for k, v in
                   sorted(phases.items(), key=lambda kv: -kv[1])},
        "critical_path": critical,
    }


def span_tree_lines(trace: dict) -> List[str]:
    """The span tree as indented text lines (children under parents,
    both in start order; orphaned parents render at the root level)."""
    spans = sorted((trace or {}).get("spans", []),
                   key=lambda s: (s.get("start") or 0.0))
    ids = {s.get("span_id") for s in spans if s.get("span_id")}
    children: Dict[str, List[dict]] = {}
    roots: List[dict] = []
    for s in spans:
        pid = s.get("parent_span_id")
        if pid and pid in ids and pid != s.get("span_id"):
            children.setdefault(pid, []).append(s)
        else:
            roots.append(s)
    t0 = min((s.get("start") or 0.0) for s in spans) if spans else 0.0
    lines: List[str] = []

    def walk(s: dict, depth: int) -> None:
        dur_ms = ((s.get("end") or 0.0) - (s.get("start") or 0.0)) * 1e3
        off_ms = ((s.get("start") or 0.0) - t0) * 1e3
        lines.append(
            f"{'  ' * depth}{s.get('name', '?'):<40.40s} "
            f"+{off_ms:9.2f}ms {dur_ms:9.2f}ms  "
            f"[{s.get('phase', 'span')}] {s.get('source') or ''}")
        for c in children.get(s.get("span_id"), ()):
            walk(c, depth + 1)

    for r in roots:
        walk(r, 0)
    return lines


def render_trace(trace: dict, analysis: Optional[dict] = None) -> str:
    """Human-readable report for ``ray_tpu trace <id>``: the span tree,
    the phase attribution table, and the critical path."""
    if not trace or not trace.get("spans"):
        return "(trace unknown or empty)"
    a = analysis or analyze(trace)
    out = [f"trace {trace.get('trace_id', '?')} — "
           f"{a['num_spans']} spans, wall {a['wall_s'] * 1e3:.2f}ms"]
    if trace.get("dropped_spans"):
        out.append(f"  ({trace['dropped_spans']} spans dropped at the "
                   f"per-trace cap)")
    out.append("")
    out.extend(span_tree_lines(trace))
    out.append("")
    out.append("phase attribution (critical-path share of wall time):")
    wall = a["wall_s"] or 1.0
    for phase, secs in a["phases"].items():
        out.append(f"  {phase:<18s} {secs * 1e3:9.2f}ms  "
                   f"{100.0 * secs / wall:5.1f}%")
    out.append("")
    out.append("critical path:")
    for seg in a["critical_path"]:
        out.append(f"  {seg['duration_s'] * 1e3:9.2f}ms  "
                   f"[{seg['phase']}] {seg['name']}")
    return "\n".join(out)
