"""joblib backend: ``with joblib.parallel_backend("ray_tpu"): ...``.

Reference: ``python/ray/util/joblib/ray_backend.py`` (a
``MultiprocessingBackend`` whose pool is the cluster-actor Pool, so
scikit-learn et al. fan out over the cluster unchanged).  The reference
rebinds ``PicklingPool.__bases__`` to swap its pool class in; here the
backend just constructs :class:`ray_tpu.util.multiprocessing.Pool`
directly — same effect without patching joblib internals.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.util.multiprocessing import Pool


def register_ray_tpu() -> None:
    """Register the backend under both ``"ray_tpu"`` and ``"ray"``."""
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray_tpu", RayTpuBackend)
    register_parallel_backend("ray", RayTpuBackend)


# keep the reference's function name importable too
register_ray = register_ray_tpu


def _backend_base():
    from joblib._parallel_backends import MultiprocessingBackend

    return MultiprocessingBackend


class RayTpuBackend(_backend_base()):
    """joblib executes batches via ``self._pool.apply_async(batch, cb)``
    (PoolManagerMixin); our Pool speaks that exact surface."""

    def __init__(self, nesting_level: Optional[int] = None,
                 inner_max_num_threads: Optional[int] = None,
                 ray_remote_args: Optional[Dict[str, Any]] = None, **kwargs):
        from ray_tpu._private.usage import record_feature

        record_feature("util.joblib")
        self.ray_remote_args = ray_remote_args
        super().__init__(nesting_level=nesting_level,
                         inner_max_num_threads=inner_max_num_threads,
                         **kwargs)

    def effective_n_jobs(self, n_jobs):
        import ray_tpu

        if n_jobs == 0:
            raise ValueError("n_jobs == 0 has no meaning")
        if n_jobs is None:
            return 1
        if n_jobs < 0:
            # joblib semantics: -1 = all cluster CPUs, -2 = all but one, …
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            total = int(ray_tpu.cluster_resources().get("CPU", 1))
            return max(1, total + 1 + n_jobs)
        return n_jobs

    def configure(self, n_jobs=1, parallel=None, prefer=None, require=None,
                  ray_remote_args: Optional[Dict[str, Any]] = None,
                  **memmappingpool_args):
        n_jobs = self.effective_n_jobs(n_jobs)
        self._pool = Pool(
            processes=n_jobs,
            ray_remote_args=ray_remote_args or self.ray_remote_args,
        )
        self.parallel = parallel
        return n_jobs

    def terminate(self):
        if getattr(self, "_pool", None) is not None:
            self._pool.terminate()
            self._pool = None


__all__ = ["register_ray_tpu", "register_ray", "RayTpuBackend"]
