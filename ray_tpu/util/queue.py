"""Distributed FIFO queue backed by a named actor.

Role of the reference's ``python/ray/util/queue.py`` (``Queue`` over a
``_QueueActor``): a process-crossing queue any task/actor can put to and
get from, with maxsize back-pressure and batch operations.  The actor here
serves blocking gets without busy-waiting by parking callers on the
threaded-actor executor (``max_concurrency``), which round 2's async actor
work made safe.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    """Raised by non-blocking/timeout get on an empty queue."""


class Full(Exception):
    """Raised by non-blocking/timeout put on a full queue."""


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        import collections
        import threading

        self._maxsize = maxsize
        self._q = collections.deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)

    def qsize(self) -> int:
        with self._lock:
            return len(self._q)

    def put(self, item, block: bool, timeout: Optional[float]) -> bool:
        with self._not_full:
            if self._maxsize > 0:
                if not block and len(self._q) >= self._maxsize:
                    return False
                deadline = None if timeout is None else time.monotonic() + timeout
                while len(self._q) >= self._maxsize:
                    remaining = None if deadline is None else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        return False
                    self._not_full.wait(remaining)
            self._q.append(item)
            self._not_empty.notify()
            return True

    def put_batch(self, items: List[Any]) -> bool:
        with self._not_empty:
            if self._maxsize > 0 and len(self._q) + len(items) > self._maxsize:
                return False
            self._q.extend(items)
            self._not_empty.notify_all()
            return True

    def get(self, block: bool, timeout: Optional[float]):
        with self._not_empty:
            if not block and not self._q:
                return False, None
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._q:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False, None
                self._not_empty.wait(remaining)
            item = self._q.popleft()
            self._not_full.notify()
            return True, item

    def get_batch(self, max_items: int):
        with self._lock:
            n = min(max_items, len(self._q))
            out = [self._q.popleft() for _ in range(n)]
            if n:
                self._not_full.notify_all()
            return out


class Queue:
    """Client handle; picklable, shareable across tasks and actors."""

    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict] = None,
                 _actor=None):
        if _actor is not None:
            self._actor = _actor
            return
        opts = dict(actor_options or {})
        # blocking put/get park a thread inside the actor until satisfied —
        # concurrency must exceed any realistic number of simultaneously
        # blocked callers or the queue deadlocks (reference uses an asyncio
        # actor with unbounded concurrency)
        opts.setdefault("max_concurrency", 1000)
        self._actor = _QueueActor.options(**opts).remote(maxsize)

    def __reduce__(self):
        return (_rebuild_queue, (self._actor,))

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None) -> None:
        ok = ray_tpu.get(self._actor.put.remote(item, block, timeout))
        if not ok:
            raise Full("queue full")

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        if not ray_tpu.get(self._actor.put_batch.remote(list(items))):
            raise Full("batch does not fit in queue")

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        ok, item = ray_tpu.get(self._actor.get.remote(block, timeout))
        if not ok:
            raise Empty("queue empty")
        return item

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, max_items: int) -> List[Any]:
        return ray_tpu.get(self._actor.get_batch.remote(max_items))

    def shutdown(self) -> None:
        ray_tpu.kill(self._actor)


def _rebuild_queue(actor):
    return Queue(_actor=actor)
