"""Placement groups (analog of ``python/ray/util/placement_group.py``).

``placement_group()`` (reference ``placement_group.py:128``) reserves gangs
of resource bundles; strategies STRICT_PACK/PACK/SPREAD/STRICT_SPREAD map to
the head's bundle policies.  STRICT_PACK is the gang lease: all bundles on
one node, or — when no single node holds them — all within ONE slice
(hosts sharing a ``slice_id`` failure domain), leased atomically with a
deterministic rank→host mapping.  For TPU pod slices, a STRICT_PACK bundle per
host with ``TPU`` resources is the gang-scheduling primitive (SURVEY §7
phase 2: a slice = bundles that must be leased atomically and die together).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private.object_ref import ObjectRef, new_id
from ray_tpu._private.worker import global_worker

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: bytes, bundles: List[Dict[str, float]], ready_ref: ObjectRef):
        self.id = pg_id
        self._bundles = bundles
        self._ready_ref = ready_ref

    def ready(self) -> ObjectRef:
        """ObjectRef sealed once all bundles are reserved."""
        return self._ready_ref

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self._bundles)

    @property
    def bundle_count(self) -> int:
        return len(self._bundles)

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        import ray_tpu

        try:
            ray_tpu.get(self._ready_ref, timeout=timeout_seconds)
            return True
        except Exception:
            return False

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles, self._ready_ref))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: Optional[str] = None,
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"Invalid strategy {strategy!r}; must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    w = global_worker
    if not w.connected:
        import ray_tpu

        ray_tpu.init()
    pg_id = new_id()
    ready_oid = new_id()
    w.client.create_pg({
        "pg_id": pg_id,
        "bundles": [dict(b) for b in bundles],
        "strategy": strategy,
        "name": name,
        "ready_oid": ready_oid,
    })
    return PlacementGroup(pg_id, bundles, ObjectRef(ready_oid))


def remove_placement_group(pg: PlacementGroup) -> None:
    global_worker.client.remove_pg(pg.id)


def placement_group_table() -> dict:
    snap = global_worker.client.state_snapshot()
    return {
        pg.pg_id.hex(): {
            "state": pg.state,
            "strategy": pg.strategy,
            "bundles": pg.bundles,
            "bundle_nodes": pg.bundle_nodes,
        }
        for pg in snap["placement_groups"]
    }
