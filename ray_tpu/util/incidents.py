"""Incident lifecycle + alert sinks for the watchdog plane.

Doctor findings and SLO burns are *stateless* — the same pathology
re-reported every evaluation, nothing ever "resolves".  This module gives
them identity and a lifecycle: one :class:`IncidentTable` entry per
``(rule, entity)`` pair with a stable id, moving open → ack → resolved
under hysteresis (a finding must stay clear for N consecutive ticks to
resolve; a resolved incident whose finding returns re-opens, and a flappy
incident that keeps re-opening escalates its severity instead of paging
again at the same level).

Every transition is pushed to pluggable **alert sinks** through a bounded
queue drained by a dedicated daemon sender thread — delivery I/O (webhook
POSTs with bounded retry + a dead-letter counter, command hooks) never
runs under a watchdog lock and can never block a tick.

The table is bounded both ways: at most ``max_incidents`` records
(oldest resolved evicted first) and a capped transition history per
incident.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import shutil
import subprocess
import threading
import time
import urllib.request
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

# hysteresis: consecutive clear ticks before an open incident resolves
DEFAULT_RESOLVE_TICKS = 3
# a resolved incident re-opening this many times escalates its severity
DEFAULT_ESCALATE_REOPENS = 3
DEFAULT_MAX_INCIDENTS = 256
DEFAULT_HISTORY_PER_INCIDENT = 20

_SEV_ESCALATION = {"INFO": "WARNING", "WARNING": "ERROR",
                   "ERROR": "CRITICAL", "CRITICAL": "CRITICAL"}


def incident_id(rule: str, entity: str) -> str:
    """Stable slug for one (rule, entity) pair — deterministic on purpose
    (no per-open entropy): the same pathology on the same entity is the
    same incident across re-opens, restarts, and CLI invocations."""
    ent = str(entity or "cluster")
    safe = "".join(c if (c.isalnum() or c in "._-") else "-" for c in ent)
    return f"{rule}--{safe[:80]}"


class IncidentTable:
    """Bounded (rule, entity) → incident map with open/ack/resolve
    hysteresis.  ``observe()`` is the only mutator on the tick path; it
    computes transitions under the lock and returns snapshots — event
    emission, sink pushes, and bundle captures are the caller's job,
    after release."""

    def __init__(self, resolve_ticks: int = DEFAULT_RESOLVE_TICKS,
                 escalate_reopens: int = DEFAULT_ESCALATE_REOPENS,
                 max_incidents: int = DEFAULT_MAX_INCIDENTS,
                 history_per_incident: int = DEFAULT_HISTORY_PER_INCIDENT):
        self.resolve_ticks = max(1, int(resolve_ticks))
        self.escalate_reopens = max(1, int(escalate_reopens))
        self.max_incidents = max(1, int(max_incidents))
        self._history_cap = max(1, int(history_per_incident))
        self._incidents: Dict[str, dict] = {}
        self._lock = threading.Lock()

    # -- tick path ------------------------------------------------------
    def observe(self, findings: List[dict],
                now: Optional[float] = None) -> List[Tuple[dict, str]]:
        """Fold one tick's findings (doctor rules + SLO burns, each a dict
        with at least ``rule``/``severity``/``summary``) into the table.
        Returns ``[(incident_snapshot, transition), ...]`` where
        transition is ``open``/``reopen``/``escalate``/``resolve``."""
        if now is None:
            now = time.time()
        out: List[Tuple[dict, str]] = []
        with self._lock:
            active_ids = set()
            for f in findings:
                rule = str(f.get("rule", "unknown"))
                entity = str(f.get("entity", "") or "cluster")
                iid = incident_id(rule, entity)
                active_ids.add(iid)
                inc = self._incidents.get(iid)
                if inc is None:
                    inc = self._new_incident(iid, rule, entity, f, now)
                    self._incidents[iid] = inc
                    self._record(inc, "open", now)
                    out.append((self._snapshot(inc), "open"))
                elif inc["state"] == "resolved":
                    inc["state"] = "open"
                    inc["reopen_count"] += 1
                    inc["resolved_at"] = None
                    inc["ack_at"] = None
                    inc["clear_streak"] = 0
                    self._update_from_finding(inc, f, now)
                    self._record(inc, "reopen", now)
                    out.append((self._snapshot(inc), "reopen"))
                    if (inc["reopen_count"] >= self.escalate_reopens
                            and not inc["escalated"]):
                        inc["escalated"] = True
                        inc["severity"] = _SEV_ESCALATION.get(
                            inc["severity"], "ERROR")
                        self._record(inc, "escalate", now)
                        out.append((self._snapshot(inc), "escalate"))
                else:  # open/ack: refresh, reset hysteresis, no transition
                    inc["clear_streak"] = 0
                    self._update_from_finding(inc, f, now)
            for iid, inc in self._incidents.items():
                if iid in active_ids or inc["state"] == "resolved":
                    continue
                inc["clear_streak"] += 1
                if inc["clear_streak"] >= self.resolve_ticks:
                    inc["state"] = "resolved"
                    inc["resolved_at"] = now
                    inc["updated_at"] = now
                    self._record(inc, "resolve", now)
                    out.append((self._snapshot(inc), "resolve"))
            self._evict_locked()
        return out

    def _new_incident(self, iid: str, rule: str, entity: str, f: dict,
                      now: float) -> dict:
        return {
            "id": iid, "rule": rule, "entity": entity,
            "severity": f.get("severity", "WARNING"),
            "summary": f.get("summary", ""),
            "remedy": f.get("remedy", ""),
            "count": int(f.get("count", 1) or 1),
            "evidence": list(f.get("evidence", ()))[:5],
            "state": "open", "opened_at": now, "updated_at": now,
            "resolved_at": None, "ack_at": None,
            "reopen_count": 0, "clear_streak": 0, "escalated": False,
            "bundle_dir": None,
            "history": deque(maxlen=self._history_cap),
        }

    def _update_from_finding(self, inc: dict, f: dict, now: float) -> None:
        inc["updated_at"] = now
        inc["summary"] = f.get("summary", inc["summary"])
        inc["remedy"] = f.get("remedy", inc["remedy"])
        inc["count"] = int(f.get("count", inc["count"]) or inc["count"])
        if f.get("evidence"):
            inc["evidence"] = list(f["evidence"])[:5]
        if not inc["escalated"]:
            inc["severity"] = f.get("severity", inc["severity"])

    def _record(self, inc: dict, transition: str, now: float) -> None:
        inc["history"].append({"transition": transition, "ts": now,
                               "severity": inc["severity"]})

    def _evict_locked(self) -> None:
        while len(self._incidents) > self.max_incidents:
            resolved = [(inc["updated_at"], iid)
                        for iid, inc in self._incidents.items()
                        if inc["state"] == "resolved"]
            if resolved:
                resolved.sort()
                del self._incidents[resolved[0][1]]
                continue
            oldest = min(self._incidents,
                         key=lambda k: self._incidents[k]["updated_at"])
            del self._incidents[oldest]

    # -- surfaces -------------------------------------------------------
    def ack(self, iid: str,
            now: Optional[float] = None) -> Optional[dict]:
        """Acknowledge an open incident (snapshot or None if unknown /
        not open).  Ack'd incidents still resolve via hysteresis."""
        if now is None:
            now = time.time()
        with self._lock:
            inc = self._incidents.get(iid)
            if inc is None or inc["state"] != "open":
                return None
            inc["state"] = "ack"
            inc["ack_at"] = now
            inc["updated_at"] = now
            self._record(inc, "ack", now)
            return self._snapshot(inc)

    def get(self, iid: str) -> Optional[dict]:
        with self._lock:
            inc = self._incidents.get(iid)
            return self._snapshot(inc) if inc is not None else None

    def set_bundle_dir(self, iid: str, path: str) -> None:
        with self._lock:
            inc = self._incidents.get(iid)
            if inc is not None:
                inc["bundle_dir"] = path

    def list(self, include_resolved: bool = True) -> List[dict]:
        with self._lock:
            rows = [self._snapshot(i) for i in self._incidents.values()
                    if include_resolved or i["state"] != "resolved"]
        rows.sort(key=lambda r: r["opened_at"])
        return rows

    def counts(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for inc in self._incidents.values():
                out[inc["state"]] = out.get(inc["state"], 0) + 1
            return out

    @staticmethod
    def _snapshot(inc: dict) -> dict:
        out = dict(inc)
        out["history"] = list(inc["history"])
        out["evidence"] = list(inc["evidence"])
        return out


# ---------------------------------------------------------------------------
# alert sinks
# ---------------------------------------------------------------------------


class LogSink:
    """Default sink: one structured line per transition on the watchdog
    logger — always on, so a bare cluster still records its pages."""

    name = "log"

    def deliver(self, payload: dict) -> None:
        inc = payload.get("incident", {})
        logger.warning(
            "incident %s %s [%s] %s", payload.get("transition"),
            inc.get("id"), inc.get("severity"), inc.get("summary"))


class WebhookSink:
    """POST each transition as JSON to ``url`` (stdlib http only) with
    bounded retry; a payload that exhausts its retries raises so the
    sender thread counts it into the dead-letter ledger."""

    def __init__(self, url: str, retries: int = 3, timeout_s: float = 2.0,
                 backoff_s: float = 0.25):
        self.name = "webhook"
        self.url = url
        self.retries = max(1, int(retries))
        self.timeout_s = timeout_s
        self.backoff_s = backoff_s

    def deliver(self, payload: dict) -> None:
        body = json.dumps(payload, default=str).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        last: Optional[BaseException] = None
        for attempt in range(self.retries):
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as resp:
                    if 200 <= resp.status < 300:
                        return
                    last = RuntimeError(f"webhook HTTP {resp.status}")
            except Exception as e:  # noqa: BLE001 — refused/timeout/5xx
                last = e
            if attempt + 1 < self.retries:
                time.sleep(self.backoff_s * (2 ** attempt))
        raise RuntimeError(
            f"webhook delivery failed after {self.retries} attempts: {last}")


class CommandSink:
    """Run a shell hook per transition; the payload arrives on stdin as
    JSON (the PagerDuty-script escape hatch)."""

    def __init__(self, cmd: str, timeout_s: float = 5.0):
        self.name = "command"
        self.cmd = cmd
        self.timeout_s = timeout_s

    def deliver(self, payload: dict) -> None:
        proc = subprocess.run(
            self.cmd, shell=True,
            input=json.dumps(payload, default=str).encode(),
            capture_output=True, timeout=self.timeout_s)
        if proc.returncode != 0:
            raise RuntimeError(
                f"command sink exited {proc.returncode}: "
                f"{proc.stderr[-200:].decode(errors='replace')}")


class SinkSet:
    """Bounded queue in front of the sinks, drained by one daemon sender
    thread — the tick path only enqueues (lock-free beyond the queue's
    own), and a slow webhook can neither block a tick nor grow memory:
    past ``maxsize`` the oldest pending payload is dropped and counted."""

    def __init__(self, sinks: Optional[List[Any]] = None,
                 maxsize: int = 256):
        self.sinks = list(sinks) if sinks is not None else [LogSink()]
        self._q: "queue.Queue[Optional[dict]]" = queue.Queue(
            maxsize=max(1, int(maxsize)))
        self._stats_lock = threading.Lock()
        self._delivered: Dict[str, int] = {}
        self._dead_letter: Dict[str, int] = {}
        self._dropped = 0
        self._stop = False
        self._thread = threading.Thread(
            target=self._drain, name="watchdog-sinks", daemon=True)
        self._thread.start()

    def push(self, payload: dict) -> None:
        while True:
            try:
                self._q.put_nowait(payload)
                return
            except queue.Full:
                try:
                    self._q.get_nowait()
                    with self._stats_lock:
                        self._dropped += 1
                except queue.Empty:
                    pass

    def _drain(self) -> None:
        while True:
            payload = self._q.get()
            if payload is None:
                return
            for sink in self.sinks:
                name = getattr(sink, "name", type(sink).__name__)
                try:
                    sink.deliver(payload)
                except Exception:  # noqa: BLE001 — delivery is best-effort
                    with self._stats_lock:
                        self._dead_letter[name] = (
                            self._dead_letter.get(name, 0) + 1)
                else:
                    with self._stats_lock:
                        self._delivered[name] = (
                            self._delivered.get(name, 0) + 1)

    def flush(self, timeout_s: float = 2.0) -> bool:
        """Best-effort wait for the queue to drain (tests)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._q.empty():
                return True
            time.sleep(0.02)
        return self._q.empty()

    def stop(self) -> None:
        if not self._stop:
            self._stop = True
            self.push(None)  # type: ignore[arg-type]

    def stats(self) -> dict:
        with self._stats_lock:
            return {"queued": self._q.qsize(), "dropped": self._dropped,
                    "delivered": dict(self._delivered),
                    "dead_letter": dict(self._dead_letter)}


def sinks_from_env() -> List[Any]:
    """Sink list from the environment: the log sink always, a webhook
    when ``RAY_TPU_INCIDENT_WEBHOOK`` names a URL, a command hook when
    ``RAY_TPU_INCIDENT_CMD`` names a shell command."""
    sinks: List[Any] = [LogSink()]
    url = os.environ.get("RAY_TPU_INCIDENT_WEBHOOK", "").strip()
    if url:
        sinks.append(WebhookSink(url))
    cmd = os.environ.get("RAY_TPU_INCIDENT_CMD", "").strip()
    if cmd:
        sinks.append(CommandSink(cmd))
    return sinks


def prune_bundle_dirs(root: str, keep: int) -> List[str]:
    """Retention cap for ``<session>/incidents/``: keep the newest
    ``keep`` bundle directories, delete the rest (oldest mtime first).
    Returns the pruned paths."""
    try:
        entries = [os.path.join(root, d) for d in os.listdir(root)]
    except OSError:
        return []
    dirs = [(os.path.getmtime(p), p) for p in entries if os.path.isdir(p)]
    dirs.sort()
    pruned = []
    while len(dirs) > max(0, int(keep)):
        _, victim = dirs.pop(0)
        shutil.rmtree(victim, ignore_errors=True)
        pruned.append(victim)
    return pruned
