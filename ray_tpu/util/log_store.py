"""Head-side bounded log store (the consume half of the log plane).

The ProfileStore/TSDB pattern applied to log records: per-stream rings
under a global byte cap with LRU eviction and dead-stream retirement, so
an arbitrarily chatty cluster costs the head a fixed amount of memory.
Records arrive from :class:`~ray_tpu._private.log_plane.LogMonitor`
batches (``log_report`` frames from node agents, direct ``ingest`` from
the head's own monitor) already parsed into
``(ts, stream, src, job, task, actor, trace, line)`` tuples; the store
adds a global monotone ``seq`` so ``ray_tpu logs --follow`` and driver
streaming can cursor past data they have already seen.

Retired streams (their worker died) keep their ring until
:meth:`retire_stale`'s horizon passes — that is what makes a SIGKILL'd
worker's last stderr retrievable from the head after death.

Error bursts: the store watches stderr/traceback line rates per stream
and emits one ``log``-source flight-recorder event per burst (via the
injected ``emit_fn`` — no import edge back into ``_private``), which the
doctor's ``log_error_burst`` rule surfaces.

Caps are constructor params (env-default) so tests can force every stage
cheaply: ``RAY_TPU_LOG_STORE_BYTES`` (default 32 MiB),
``RAY_TPU_LOG_STORE_LINES`` (per stream, default 10000),
``RAY_TPU_LOG_MAX_STREAMS`` (default 512), ``RAY_TPU_LOG_BURST_N`` /
``RAY_TPU_LOG_BURST_WINDOW_S`` (burst rule: N error lines inside the
window, default 50 in 30s).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

# incoming record layout (log_plane wire tuples)
_TS, _STREAM, _SRC, _JOB, _TASK, _ACTOR, _TRACE, _LINE = range(8)

_ERR_SRCS = ("e", "E", "C")


def _int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def is_error_record(src: str, line: str) -> bool:
    """stderr output, ERROR/CRITICAL logger records, and traceback bodies
    count toward ``--errors`` and burst detection."""
    return src in _ERR_SRCS or line.startswith("Traceback (")


class _Stream:
    __slots__ = ("ring", "bytes", "meta", "last_ingest", "retired",
                 "err_times", "burst_at", "total_lines")

    def __init__(self, meta: dict, now: float):
        # stored tuples: (seq, ts, src, job, task, actor, trace, line)
        self.ring: deque = deque()
        self.bytes = 0
        self.meta = dict(meta or {})
        self.last_ingest = now
        self.retired = False
        self.err_times: deque = deque()
        self.burst_at = 0.0
        self.total_lines = 0


class LogStore:
    def __init__(self,
                 max_lines_per_stream: Optional[int] = None,
                 max_total_bytes: Optional[int] = None,
                 max_streams: Optional[int] = None,
                 burst_n: Optional[int] = None,
                 burst_window_s: Optional[float] = None,
                 emit_fn: Optional[Callable] = None):
        self.max_lines_per_stream = (
            max_lines_per_stream if max_lines_per_stream is not None
            else _int_env("RAY_TPU_LOG_STORE_LINES", 10_000))
        self.max_total_bytes = (
            max_total_bytes if max_total_bytes is not None
            else _int_env("RAY_TPU_LOG_STORE_BYTES", 32 << 20))
        self.max_streams = (
            max_streams if max_streams is not None
            else _int_env("RAY_TPU_LOG_MAX_STREAMS", 512))
        self.burst_n = (burst_n if burst_n is not None
                        else _int_env("RAY_TPU_LOG_BURST_N", 50))
        self.burst_window_s = (burst_window_s if burst_window_s is not None
                               else float(_int_env(
                                   "RAY_TPU_LOG_BURST_WINDOW_S", 30)))
        self.emit_fn = emit_fn
        self._streams: Dict[str, _Stream] = {}
        self._total_bytes = 0
        self._seq = 0
        # cumulative ship-pressure counters (never decremented): total
        # records absorbed and suppression markers among them — the
        # ray_tpu_log_records_total / _suppressed_total gauge sources
        self._ingested_total = 0
        self._suppressed_total = 0
        self._lock = threading.Lock()

    # -- ingest ---------------------------------------------------------
    def ingest(self, node: str, records: List[tuple],
               metas: Optional[Dict[str, dict]] = None,
               now: Optional[float] = None) -> Dict[str, List[tuple]]:
        """Absorb one shipped batch.  Returns records grouped by job —
        ``{job: [(seq, ts, stream, src, task, actor, trace, line), ...]}``
        — so the head can publish each job's slice to its subscribed
        drivers without a second pass."""
        if now is None:
            now = time.time()
        by_job: Dict[str, List[tuple]] = {}
        bursts: List[Tuple[str, int, dict]] = []
        with self._lock:
            for rec in records:
                name = rec[_STREAM]
                st = self._streams.get(name)
                if st is None:
                    meta = dict((metas or {}).get(name) or {})
                    meta.setdefault("node", node)
                    st = _Stream(meta, now)
                    self._streams[name] = st
                    self._evict_streams_locked()
                elif metas and name in metas:
                    st.meta.update(metas[name])
                    st.meta.setdefault("node", node)
                self._seq += 1
                self._ingested_total += 1
                if rec[_SRC] == "m":  # suppression marker record
                    self._suppressed_total += 1
                line = rec[_LINE]
                stored = (self._seq, rec[_TS], rec[_SRC], rec[_JOB],
                          rec[_TASK], rec[_ACTOR], rec[_TRACE], line)
                st.ring.append(stored)
                cost = len(line) + 64
                st.bytes += cost
                self._total_bytes += cost
                st.last_ingest = now
                st.total_lines += 1
                if len(st.ring) > self.max_lines_per_stream:
                    old = st.ring.popleft()
                    drop = len(old[7]) + 64
                    st.bytes -= drop
                    self._total_bytes -= drop
                if rec[_JOB]:
                    by_job.setdefault(rec[_JOB], []).append(
                        (self._seq, rec[_TS], name, rec[_SRC], rec[_TASK],
                         rec[_ACTOR], rec[_TRACE], line))
                if is_error_record(rec[_SRC], line):
                    st.err_times.append(rec[_TS])
                    horizon = now - self.burst_window_s
                    while st.err_times and st.err_times[0] < horizon:
                        st.err_times.popleft()
                    if (len(st.err_times) >= self.burst_n
                            and now - st.burst_at > self.burst_window_s):
                        st.burst_at = now
                        bursts.append((name, len(st.err_times),
                                       dict(st.meta)))
            self._enforce_locked()
        if self.emit_fn is not None:
            for name, n, meta in bursts:
                try:
                    self.emit_fn(
                        "log",
                        f"error burst: {n} error/traceback lines in "
                        f"{self.burst_window_s:.0f}s from {name}",
                        severity="WARNING", entity_id=name,
                        node=meta.get("node"), pid=meta.get("pid"))
                except Exception:
                    pass
        return by_job

    def _evict_streams_locked(self) -> None:
        while len(self._streams) > self.max_streams:
            victim = min(self._streams,
                         key=lambda k: self._streams[k].last_ingest)
            self._total_bytes -= self._streams[victim].bytes
            del self._streams[victim]

    def _enforce_locked(self) -> None:
        """Byte pressure: shed the oldest records of the least-recently
        active streams first — a quiet stream's history yields to a live
        one's present, the LRU shape every other head store uses."""
        if self._total_bytes <= self.max_total_bytes:
            return
        order = sorted(self._streams.values(), key=lambda s: s.last_ingest)
        for st in order:
            while st.ring and self._total_bytes > self.max_total_bytes:
                old = st.ring.popleft()
                drop = len(old[7]) + 64
                st.bytes -= drop
                self._total_bytes -= drop
            if self._total_bytes <= self.max_total_bytes:
                return

    # -- lifecycle ------------------------------------------------------
    def retire(self, stream: str) -> None:
        """Its process died: stop expecting ingest but KEEP the ring so
        the death tail stays queryable until retire_stale's horizon."""
        with self._lock:
            st = self._streams.get(stream)
            if st is not None:
                st.retired = True

    def retire_stale(self, max_age_s: float,
                     now: Optional[float] = None) -> List[str]:
        """Drop retired streams idle past ``max_age_s``.  Returns the
        dropped names so the caller can emit events."""
        if now is None:
            now = time.time()
        dropped = []
        with self._lock:
            for name in list(self._streams):
                st = self._streams[name]
                if st.retired and now - st.last_ingest > max_age_s:
                    self._total_bytes -= st.bytes
                    del self._streams[name]
                    dropped.append(name)
        return dropped

    # -- queries --------------------------------------------------------
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def query(self, stream: Optional[str] = None, job: Optional[str] = None,
              task: Optional[str] = None, actor: Optional[str] = None,
              node: Optional[str] = None, pid: Optional[int] = None,
              trace: Optional[str] = None, grep: Optional[str] = None,
              errors: bool = False, since_seq: int = 0,
              limit: int = 1000) -> Tuple[List[dict], int]:
        """Filtered records as dicts, oldest-first, the LAST ``limit``
        matches.  Returns ``(rows, cursor)`` where ``cursor`` is the max
        seq in the store — pass it back as ``since_seq`` to follow."""
        needle = grep.lower() if grep else None
        out: List[dict] = []
        with self._lock:
            cursor = self._seq
            for name, st in self._streams.items():
                if stream is not None and name != stream:
                    continue
                if node is not None and st.meta.get("node") != node:
                    continue
                if pid is not None and st.meta.get("pid") != pid:
                    continue
                for (seq, ts, src, rjob, rtask, ractor, rtrace,
                     line) in st.ring:
                    if seq <= since_seq:
                        continue
                    if job is not None and rjob != job:
                        continue
                    if task is not None and rtask != task:
                        continue
                    if actor is not None and ractor != actor:
                        continue
                    if trace is not None and rtrace != trace:
                        continue
                    if errors and not is_error_record(src, line):
                        continue
                    if needle is not None and needle not in line.lower():
                        continue
                    out.append({"seq": seq, "ts": ts, "stream": name,
                                "src": src, "job": rjob, "task": rtask,
                                "actor": ractor, "trace": rtrace,
                                "line": line,
                                "node": st.meta.get("node"),
                                "pid": st.meta.get("pid")})
        out.sort(key=lambda r: r["seq"])
        if limit and len(out) > limit:
            out = out[-limit:]
        return out, cursor

    def tail_text(self, stream: str, n: int = 100,
                  errors_only: bool = False) -> List[str]:
        """The last ``n`` raw lines of one stream (death tails, CLI
        ``tail_log``)."""
        with self._lock:
            st = self._streams.get(stream)
            if st is None:
                return []
            recs = list(st.ring)
        if errors_only:
            recs = [r for r in recs if is_error_record(r[2], r[7])]
        return [r[7] for r in recs[-n:]]

    def __contains__(self, stream: str) -> bool:
        with self._lock:
            return stream in self._streams

    def stream_meta(self, stream: str) -> dict:
        with self._lock:
            st = self._streams.get(stream)
            return dict(st.meta) if st is not None else {}

    def counters(self) -> Dict[str, int]:
        """Cumulative ship-pressure counters: records absorbed since
        boot and suppression markers among them (each marker stands for
        a burst the source-side limiter dropped)."""
        with self._lock:
            return {"ingested_total": self._ingested_total,
                    "suppressed_total": self._suppressed_total}

    def stats(self) -> List[dict]:
        """One row per stream — the state API's ``logs`` table."""
        with self._lock:
            # linear snapshot only while held; the O(n log n) sort and
            # row assembly run after release
            snap = [(name, dict(st.meta),
                     st.ring[-1][1] if st.ring else None,
                     len(st.ring), st.total_lines, st.bytes, st.retired)
                    for name, st in self._streams.items()]
        snap.sort(key=lambda r: r[0])
        return [{"stream": name,
                 "node": meta.get("node"),
                 "pid": meta.get("pid"),
                 "job": meta.get("job"),
                 "lines": lines,
                 "total_lines": total_lines,
                 "bytes": nbytes,
                 "retired": retired,
                 "last_ts": last_ts}
                for name, meta, last_ts, lines, total_lines, nbytes,
                retired in snap]
