"""Multi-tenant client proxy server (``proxier.py`` analog of the
reference's ``python/ray/util/client/server/proxier.py``).

One listener, one isolated driver subprocess PER client connection.  The
proxy is only on the handshake path: after ``proxy_hello`` it passes the
accepted socket fd to the spawned ``ray_tpu.util.client.driver`` process
(the reference's ``SpecificServer`` analog) and steps out — tenant
traffic flows client ↔ driver ↔ head with a single extra hop, and a
SIGKILL'd driver takes down exactly one tenant's connection.

Run standalone::

    python -m ray_tpu.util.client.proxier --head auto --port 10001

or embed next to an in-process head (tests, bench)::

    proxy = ProxyServer(head_address, authkey).start()
    ray_tpu.init(f"ray_tpu://{proxy.address[0]}:{proxy.address[1]}")
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from multiprocessing.connection import Listener
from typing import Dict, Optional, Tuple

from ray_tpu._private import events as events_mod
from ray_tpu._private import wire

SPAWN_TIMEOUT_S = 30.0


class TenantDriver:
    """Bookkeeping for one connection's driver subprocess."""

    def __init__(self, proc: subprocess.Popen, namespace: Optional[str]):
        self.proc = proc
        self.namespace = namespace
        self.started = time.time()

    @property
    def pid(self) -> int:
        return self.proc.pid

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None


class ProxyServer:
    def __init__(self, head_address: str, authkey: bytes,
                 host: str = "127.0.0.1", port: int = 0):
        self._head_address = head_address
        self._authkey = authkey
        self._listener = Listener((host, port), family="AF_INET",
                                  authkey=authkey, backlog=16)
        self.address: Tuple[str, int] = self._listener.address
        self.tenants: Dict[int, TenantDriver] = {}  # pid -> driver
        self._lock = threading.Lock()
        self._stopped = False
        self._accept_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def start(self) -> "ProxyServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="proxy-accept")
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                raw = self._listener.accept()
            except Exception:  # noqa: BLE001 — an auth failure or
                # mid-handshake EOF from one peer must not kill the
                # listener; stop() closing it is the real exit
                if self._stopped:
                    return
                continue
            threading.Thread(target=self._serve_conn, args=(raw,),
                             daemon=True, name="proxy-handshake").start()

    def _serve_conn(self, raw) -> None:
        """Handshake one client: read ``proxy_hello``, spawn its driver
        around the socket fd, confirm with ``proxy_ready``, close our fd.
        From then on the proxy holds no piece of the tenant's data path."""
        conn = wire.wrap(raw)
        try:
            try:
                hello = conn.recv()
            except (EOFError, OSError):
                conn.close()
                return
            mtype = hello.get("type")
            if mtype == "proxy_hello":
                namespace = hello.get("namespace")
            else:
                conn.send({"type": "proxy_error",
                           "error": f"expected proxy_hello, got {mtype!r}"})
                conn.close()
                return
            try:
                driver = self._spawn_driver(raw.fileno(), namespace)
            except (OSError, TimeoutError, RuntimeError) as e:
                conn.send({"type": "proxy_error",
                           "error": f"driver spawn failed: {e}"})
                conn.close()
                return
            with self._lock:
                self.tenants[driver.pid] = driver
            # reaper armed BEFORE proxy_ready: if the client vanished
            # mid-handshake the send below raises, and the spawned driver
            # (exiting on its client-fd EOF) must still be wait()ed and
            # dropped from the directory — not left a zombie behind a
            # forever-"alive" tenants row
            threading.Thread(target=self._reap, args=(driver,), daemon=True,
                             name=f"proxy-reap-{driver.pid}").start()
            events_mod.emit(
                "client_proxy", "tenant driver spawned", severity="INFO",
                pid=driver.pid, namespace=namespace)
            conn.send({"type": "proxy_ready"})
        finally:
            # the driver subprocess owns its dup of the socket now; our
            # descriptor must go or the client never sees EOF on driver
            # death (the fd would stay half-open here)
            try:
                conn.close()
            except OSError:
                pass

    def _tenant_log_path(self) -> Optional[str]:
        """Capture file for the next tenant driver, when the head's
        session logs dir is reachable from this host (the common
        proxy-on-head deployment).  The head's log monitor adopts
        ``tenant-*.log`` files there by glob — spawn-time registration
        can't cross processes."""
        import json

        try:
            with open("/tmp/ray_tpu/last_session.json") as f:
                sess_dir = json.load(f).get("session_dir")
            if not sess_dir:
                return None
            log_dir = os.path.join(sess_dir, "logs")
            if not os.path.isdir(log_dir):
                return None
            return os.path.join(
                log_dir, f"tenant-{os.getpid()}-{len(self.tenants)}.log")
        except (OSError, ValueError):
            return None

    def _spawn_driver(self, fd: int, namespace: Optional[str]) -> TenantDriver:
        env = dict(os.environ)
        env["RAY_TPU_PROXY_CONN_FD"] = str(fd)
        env["RAY_TPU_PROXY_HEAD"] = self._head_address
        env["RAY_TPU_AUTHKEY"] = self._authkey.hex()
        log_path = self._tenant_log_path()
        if log_path:
            env["RAY_TPU_DRIVER_LOG"] = log_path
        if namespace:
            env["RAY_TPU_PROXY_NAMESPACE"] = namespace
        else:
            env.pop("RAY_TPU_PROXY_NAMESPACE", None)
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.util.client.driver"],
            env=env, pass_fds=[fd], stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True,
        )
        # the driver prints READY once its head connection is live; a
        # driver that can't reach the head dies before printing and the
        # client gets proxy_error instead of a dead pipe.  EVERY failure
        # path kills + collects the child here — no reaper thread exists
        # for it yet, so skipping the wait() would leave a zombie.
        try:
            line = _readline_with_timeout(proc, SPAWN_TIMEOUT_S)
        except TimeoutError:
            proc.kill()
            proc.wait()
            raise
        if line.strip() != "READY":
            proc.kill()
            proc.wait()
            raise RuntimeError(
                f"driver failed to come up (got {line!r})")
        return TenantDriver(proc, namespace)

    def _reap(self, driver: TenantDriver) -> None:
        """Collect the subprocess when it exits (no zombies) and record
        the departure in the flight recorder."""
        driver.proc.wait()
        with self._lock:
            self.tenants.pop(driver.pid, None)
        events_mod.emit(
            "client_proxy", "tenant driver exited", severity="INFO",
            pid=driver.pid, namespace=driver.namespace,
            returncode=driver.proc.returncode)

    # ------------------------------------------------------------------
    def list_tenants(self) -> list:
        with self._lock:
            return [{"pid": d.pid, "namespace": d.namespace,
                     "alive": d.alive, "started": d.started}
                    for d in self.tenants.values()]

    def stop(self) -> None:
        self._stopped = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            drivers = list(self.tenants.values())
        for d in drivers:
            try:
                d.proc.terminate()
            except OSError:
                pass


def _readline_with_timeout(proc: subprocess.Popen, timeout: float) -> str:
    """One stdout line from the child, bounded: a wedged driver must fail
    the handshake, not park the proxy's accept thread forever."""
    box = {"line": ""}

    def read():
        try:
            box["line"] = proc.stdout.readline()
        except (OSError, ValueError):
            pass

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise TimeoutError(f"driver produced no READY within {timeout}s")
    return box["line"]


def main(argv=None) -> None:
    import argparse
    import json

    p = argparse.ArgumentParser(
        description="multi-tenant ray_tpu client proxy")
    p.add_argument("--head", default="auto",
                   help='head address ("auto" reads the last session file)')
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10001)
    args = p.parse_args(argv)

    head = args.head
    if head == "auto":
        with open("/tmp/ray_tpu/last_session.json") as f:
            sess = json.load(f)
        head = sess["address"]
        authkey = bytes.fromhex(sess["authkey"])
    else:
        if ":" in head and not head.startswith("tcp://") \
                and not head.startswith("/"):
            # bare host:port — the driver treats unprefixed strings as
            # unix socket paths, so normalize here
            head = f"tcp://{head}"
        key = os.environ.get("RAY_TPU_AUTHKEY")
        if not key:
            raise SystemExit(
                "RAY_TPU_AUTHKEY must be exported when --head is not "
                "'auto' (hex authkey of the target cluster)")
        authkey = bytes.fromhex(key)
    server = ProxyServer(head, authkey, host=args.host, port=args.port)
    server.start()
    print(f"ray_tpu client proxy on {server.address[0]}:{server.address[1]} "
          f"-> {head}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
