"""Per-connection tenant driver subprocess (the reference proxier's
``SpecificServer`` analog).

Spawned by :mod:`ray_tpu.util.client.proxier` with the tenant's accepted
socket fd.  Opens its OWN connection to the head and relays both ways,
so the tenant's whole control-plane presence — job id, namespace, object
pins, flight-recorder origin, and above all the PID — is isolated in
this process.  Kill it and the head sees exactly one client disconnect:
that tenant's non-detached actors and pins are reaped while every other
tenant keeps running.

The relay inspects frames only through the registration handshake: the
client's ``register_client`` is the single frame rewritten in flight
(this process's pid, the proxy-assigned namespace default,
``proxied=True``), and the head's reply is sniffed to learn the job id
this driver ships flight-recorder events under.  After that BOTH
directions degrade to a raw fd-level byte splice — no framing, no
decode, one read+write per chunk — so proxy mode's task-throughput
overhead is two socket hops, not two codec traversals
(``proxy_mode_overhead`` bench gate).  Flight-recorder events ride a
separate head connection so they can never interleave into the spliced
byte stream.
"""

from __future__ import annotations

import os
import threading
from multiprocessing.connection import Connection

from ray_tpu._private import events as events_mod
from ray_tpu._private import wire
from ray_tpu._private.client import connect_control


def _writeall(fd: int, data: bytes) -> None:
    view = memoryview(data)
    while view:
        n = os.write(fd, view)
        view = view[n:]


def _splice(src_fd: int, dst_fd: int) -> None:
    """Pump bytes until EOF/error.  Only entered once this direction's
    last inspected frame was fully consumed, so chunk boundaries need no
    alignment with frame boundaries."""
    while True:
        try:
            data = os.read(src_fd, 1 << 16)
        except OSError:
            return
        if not data:
            return
        try:
            _writeall(dst_fd, data)
        except OSError:
            return


def main() -> None:
    fd = int(os.environ["RAY_TPU_PROXY_CONN_FD"])
    head_address = os.environ["RAY_TPU_PROXY_HEAD"]
    authkey = bytes.fromhex(os.environ["RAY_TPU_AUTHKEY"])
    namespace = os.environ.get("RAY_TPU_PROXY_NAMESPACE")

    down = wire.wrap(Connection(fd))  # the tenant client (auth done by proxy)
    up = connect_control(head_address, authkey)

    # tell the proxy we are live BEFORE any tenant traffic: it answers the
    # client's proxy_ready off this line
    print("READY", flush=True)

    # after the handshake nothing reads this process's stdout/stderr —
    # redirect both into the tenant capture file (set by the proxy when
    # the session logs dir is local) so the log plane sees this driver
    # like any worker
    log_path = os.environ.get("RAY_TPU_DRIVER_LOG")
    if log_path:
        from ray_tpu._private.log_plane import redirect_process_output

        redirect_process_output(log_path)

    state = {"reg_req_id": None, "job_id": None, "pusher": None,
             "pusher_conn": None}
    done = threading.Event()

    def client_to_head() -> None:
        while True:
            try:
                buf = down._conn.recv_bytes()
            except Exception:  # noqa: BLE001 — any failure on a dying
                # socket is a disconnect, not a crash
                break
            try:
                msg = wire.decode(buf)
            except Exception:  # noqa: BLE001 — pass opaque frames on
                msg = None
            if msg is not None and msg.get("type") == "register_client":
                # the one enrichment: bind this connection to a tenant
                # identity.  A proxied tenant with no explicit namespace
                # gets an ISOLATED one derived from its pid — tenants
                # collide only when they opt into a shared namespace.
                if not msg.get("namespace"):
                    msg["namespace"] = namespace or f"tenant-{os.getpid()}"
                msg["pid"] = os.getpid()
                msg["proxied"] = True
                state["reg_req_id"] = msg.get("req_id")
                try:
                    up.send(msg)
                except (OSError, ValueError):
                    break
                _splice(down._conn.fileno(), up._conn.fileno())
                break
            try:
                up._conn.send_bytes(buf)
            except (OSError, ValueError):
                break
        done.set()

    def head_to_client() -> None:
        while True:
            try:
                buf = up._conn.recv_bytes()
            except Exception:  # noqa: BLE001 — same: EOF = gone
                break
            if state["reg_req_id"] is not None and state["pusher"] is None:
                try:
                    msg = wire.decode(buf)
                except Exception:  # noqa: BLE001
                    msg = None
                if (msg is not None
                        and msg.get("type") == "reply"
                        and msg.get("req_id") == state["reg_req_id"]
                        and isinstance(msg.get("value"), dict)):
                    _start_pusher(msg["value"])
                    try:
                        down._conn.send_bytes(buf)
                    except (OSError, ValueError):
                        break
                    _splice(up._conn.fileno(), down._conn.fileno())
                    break
            try:
                down._conn.send_bytes(buf)
            except (OSError, ValueError):
                break
        done.set()

    def _start_pusher(ident: dict) -> None:
        """This driver's OWN flight-recorder identity, on its OWN head
        connection (events must never interleave into the spliced
        relay stream)."""
        job_id = ident.get("job_id")
        state["job_id"] = job_id
        try:
            conn = connect_control(head_address, authkey)
        except (OSError, EOFError):
            return  # relay works without events; never kill the tenant
        state["pusher_conn"] = conn
        state["pusher"] = events_mod.EventsPusher(
            conn.send, origin=f"tenant-{job_id}",
            closed_fn=done.is_set).start()
        # proxied drivers profile too — their submit-side stacks are the
        # one part of the task path the head can't see from its own
        # sampler (the pusher's dedicated head conn keeps profile frames
        # out of the spliced relay stream)
        from ray_tpu._private import sampling_profiler as _sp

        if _sp.continuous_enabled():
            state["profiler"] = _sp.ContinuousProfiler(
                f"tenant-{job_id}", send_fn=conn.send,
                closed_fn=done.is_set).start()
        events_mod.emit(
            "client_proxy", "tenant driver online", severity="INFO",
            entity_id=job_id, pid=os.getpid(),
            namespace=ident.get("namespace"))

    threads = [
        threading.Thread(target=client_to_head, daemon=True, name="c2h"),
        threading.Thread(target=head_to_client, daemon=True, name="h2c"),
    ]
    for t in threads:
        t.start()
    done.wait()
    # either side went away: drop both ends.  Closing the head conn is
    # what triggers the head's tenant reap; closing the client conn is
    # what tells the tenant its session died.
    for key in ("profiler", "pusher"):
        stoppable = state.get(key)
        if stoppable is not None:
            try:
                stoppable.stop()
            except Exception:  # noqa: BLE001 — final ship is best-effort
                pass
    for c in (down, up, state["pusher_conn"]):
        if c is None:
            continue
        try:
            c.close()
        except OSError:
            pass
    os._exit(0)


if __name__ == "__main__":
    main()
