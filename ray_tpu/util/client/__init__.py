"""Multi-tenant client proxy (reference ``ray.util.client`` +
``util/client/server/proxier.py`` analog).

``ProxyServer`` (``proxier.py``) is a head-adjacent server that accepts
``ray_tpu.init("ray_tpu://host:port", namespace=...)`` connections and
spawns one ISOLATED driver subprocess per connection (``driver.py``).
The subprocess owns the tenant's whole control-plane presence: its own
job id, namespace, flight-recorder identity, and — critically — its own
pid, so one tenant's driver can die (or be chaos-killed) without touching
the proxy or any other tenant.  Driver death or client disconnect reaps
the subprocess, and the head releases everything the job owned
(non-detached actors, named-actor entries, object pins).
"""

from ray_tpu.util.client.proxier import ProxyServer

__all__ = ["ProxyServer"]
