"""Actor pool: fan work out over a fixed set of actors.

Role of the reference's ``python/ray/util/actor_pool.py`` (``ActorPool``):
a driver-side load balancer that keeps every actor busy, yields results as
they complete (ordered or unordered), and lets actors be pushed/popped at
runtime.  Re-designed around ``ray_tpu.wait`` — no polling.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, TYPE_CHECKING

import ray_tpu

if TYPE_CHECKING:
    from ray_tpu.actor import ActorHandle


class ActorPool:
    """Schedule tasks over a pool of actor handles.

    Example::

        pool = ActorPool([Worker.remote() for _ in range(4)])
        for out in pool.map(lambda a, x: a.double.remote(x), range(100)):
            ...
    """

    def __init__(self, actors: Iterable["ActorHandle"]):
        self._idle: List["ActorHandle"] = list(actors)
        # in-flight: ObjectRef -> (actor, submission index)
        self._inflight: dict = {}
        self._next_submit_idx = 0
        self._next_yield_idx = 0
        # completed-but-not-yet-yielded results for ordered iteration
        self._done: dict = {}

    # -- submission ------------------------------------------------------

    def submit(self, fn: Callable[["ActorHandle", Any], Any], value: Any) -> None:
        """Apply ``fn(actor, value)`` on an idle actor; blocks until one frees."""
        if not self._idle:
            self._wait_one()
        actor = self._idle.pop()
        ref = fn(actor, value)
        self._inflight[ref] = (actor, self._next_submit_idx)
        self._next_submit_idx += 1

    def has_next(self) -> bool:
        return bool(self._inflight) or bool(self._done)

    def has_free(self) -> bool:
        return bool(self._idle)

    def get_next(self, timeout: float = None) -> Any:
        """Next result in submission order."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        idx = self._next_yield_idx
        while idx not in self._done:
            if not self._inflight:
                raise StopIteration("no pending results")
            remaining = None if deadline is None else max(0.0, deadline - _time.monotonic())
            self._wait_one(timeout=remaining)
        self._next_yield_idx += 1
        return self._done.pop(idx)

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Next result to complete, regardless of submission order."""
        if self._done:
            idx = next(iter(self._done))
            return self._done.pop(idx)
        if not self._inflight:
            raise StopIteration("no pending results")
        self._wait_one(timeout=timeout)
        idx = next(iter(self._done))
        return self._done.pop(idx)

    # -- iteration -------------------------------------------------------

    def map(self, fn: Callable, values: Iterable[Any]) -> Iterator[Any]:
        """Ordered map; keeps all actors busy, yields in input order."""
        for v in values:
            if not self._idle:
                yield self.get_next()
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]) -> Iterator[Any]:
        """Unordered map; lower latency to first result."""
        for v in values:
            if not self._idle:
                yield self.get_next_unordered()
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # -- pool membership -------------------------------------------------

    def push(self, actor: "ActorHandle") -> None:
        """Add an idle actor to the pool."""
        self._idle.append(actor)

    def pop_idle(self) -> "ActorHandle":
        """Remove and return an idle actor (raises if none idle)."""
        if not self._idle:
            raise ValueError("no idle actor to pop")
        return self._idle.pop()

    # -- internals -------------------------------------------------------

    def _wait_one(self, timeout: float = None) -> None:
        refs = list(self._inflight)
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("ActorPool.get_next timed out")
        ref = ready[0]
        actor, idx = self._inflight.pop(ref)
        self._idle.append(actor)
        self._done[idx] = ray_tpu.get(ref)
