"""Distributed trace-context propagation across task/actor boundaries.

Analog of the reference's ``python/ray/util/tracing/tracing_helper.py``
(monkey-patched remote calls inject OpenTelemetry span contexts into task
metadata; workers resume the trace).  Here propagation is first-class
instead of patched on: when tracing is enabled, every task spec carries the
submitter's trace context, the executing worker adopts it for the duration
of the task (so nested submissions chain), and the head records it on
TaskInfo — ``ray_tpu timeline`` then emits chrome-trace flow arrows linking
parents to children.  If the OpenTelemetry SDK is importable, real spans
are started as well (the reference's lazy-import pattern).

Beyond task specs, the context crosses every runtime boundary: serve HTTP
ingress opens a root trace per request, the router's admission wait becomes
a child span the replica task chains under, compiled-graph ``execute()``
rides the channel payloads (``dag/compiled.py`` ``_Traced``) so per-node
loop spans join the caller's trace, the streaming pump adopts its
consumer's context, and long ``ray_tpu.get`` waits emit ``get_wait``
spans.  Timed spans land in the flight recorder (``_private/events.py``)
under the ``trace`` source, so shipping to the head, crash-dump JSONL, and
the chrome-trace merge all come for free; the head folds them into a
per-trace :class:`~ray_tpu._private.events.TraceTable` served by
``experimental.state.api.get_trace`` / ``ray_tpu trace <id>``.

Presence of a context IS the enable signal: outside any ``trace()`` block
nothing is recorded and task specs stay clean, so the disabled path costs
one contextvar read per submission.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from typing import Any, Dict, Iterator, Optional

from ray_tpu._private import events as _events
from ray_tpu._private import log_plane as _log_plane

# flight-recorder source for span events (one row per closed span)
TRACE_SOURCE = "trace"

_current: contextvars.ContextVar[Optional[Dict[str, str]]] = contextvars.ContextVar(
    "ray_tpu_trace", default=None
)


def _ctx_set(ctx):
    """``_current.set`` + log-plane stamp-cache invalidation: every line
    a thread prints while a context is active must carry its trace id."""
    token = _current.set(ctx)
    _log_plane.bump_context_epoch()
    return token


def _ctx_reset(token):
    _current.reset(token)
    _log_plane.bump_context_epoch()

# --- id generation --------------------------------------------------------
# NOT uuid4 per span: uuid4 reads os.urandom every call, and on this
# kernel one urandom read costs ~200us — per-task span ids at that price
# ate ~30% of task throughput.  Instead: one urandom read per PROCESS
# (22 hex chars of prefix + a random-start counter).  Forked children
# (the forkserver's warm template) re-seed via the at-fork hook instead
# of a per-call getpid() — this kernel charges ~16us per getpid too.
_id_lock = threading.Lock()
_id_prefix = ""
_id_n = 0


def _reseed_ids() -> None:
    # fresh lock too: the fork may have happened while another thread of
    # the parent held _id_lock — the child inherits it locked forever
    global _id_lock, _id_prefix, _id_n
    _id_lock = threading.Lock()
    _id_prefix = ""
    _id_n = 0


os.register_at_fork(after_in_child=_reseed_ids)


def _next_id() -> int:
    global _id_prefix, _id_n
    with _id_lock:
        if not _id_prefix:
            _id_prefix = os.urandom(11).hex()  # raylint: disable=R3 (one-shot, off the per-task path)
            _id_n = int.from_bytes(os.urandom(5), "big")  # raylint: disable=R3 (one-shot, off the per-task path)
        _id_n += 1
        return _id_n


def new_trace_id() -> str:
    """32 hex chars, globally unique (22-hex process prefix + counter)."""
    n = _next_id()  # first: seeds the prefix for this process
    return _id_prefix + format(n & 0xFFFFFFFFFF, "010x")


def new_span_id() -> str:
    """16 hex chars, unique in-process by counter and cross-process by
    the random prefix + random counter start."""
    n = _next_id()
    return _id_prefix[:6] + format(n & 0xFFFFFFFFFF, "010x")


def current_context() -> Optional[Dict[str, str]]:
    """The active trace context, or None (outside any trace).  Presence of
    a context IS the enable signal — specs stay clean when tracing is
    unused, and workers propagate whenever a spec carries one."""
    return _current.get()


@contextlib.contextmanager
def trace(name: str, attributes: Optional[dict] = None,
          phase: str = "span") -> Iterator[Dict[str, str]]:
    """Open a span.  Tasks submitted inside the block carry its context;
    their workers continue the same trace.  On exit the timed span is
    emitted into the flight recorder (``trace`` source), which is what
    the head's TraceTable assembles per-trace span trees from."""
    parent = _current.get()
    ctx = {
        "trace_id": parent["trace_id"] if parent else new_trace_id(),
        "span_id": new_span_id(),
        "parent_span_id": parent["span_id"] if parent else "",
        "name": name,
    }
    job = parent.get("job") if parent else _current_job()
    if job:
        # tenant identity rides the context: every span of the trace can
        # be attributed to the submitting job (multi-tenant trace audit)
        ctx["job"] = job
    token = _ctx_set(ctx)
    otel_cm = _otel_span(name, attributes)
    t0 = time.perf_counter()
    try:
        with otel_cm:
            yield ctx
    finally:
        _ctx_reset(token)
        emit_span(name, time.perf_counter() - t0, ctx, phase=phase,
                  attributes=attributes)


def _current_job() -> Optional[str]:
    """The running process's tenant job id (driver identity or the
    executing task's), for root-span attribution.  Lazy import: tracing
    must stay importable before the worker runtime is."""
    try:
        from ray_tpu._private.worker import global_worker
    except ImportError:
        return None
    return global_worker.current_job_id or global_worker.job_id


def _otel_span(name: str, attributes: Optional[dict]):
    """A real OpenTelemetry span when the SDK is importable, else a no-op
    (``tracing_helper.py:53-59`` lazy import)."""
    try:
        from opentelemetry import trace as otel  # type: ignore
    except ImportError:
        return contextlib.nullcontext()
    tracer = otel.get_tracer("ray_tpu")
    return tracer.start_as_current_span(name, attributes=attributes or {})


def child_context(name: str) -> Optional[Dict[str, str]]:
    """A fresh span context chained under the caller's (None when tracing
    is off).  Used for outgoing task specs, router admissions, compiled
    ``execute()`` payloads — anything that continues the trace in another
    process."""
    parent = current_context()
    if parent is None:
        return None
    ctx = {
        "trace_id": parent["trace_id"],
        "span_id": new_span_id(),
        "parent_span_id": parent["span_id"],
        "name": name,
    }
    if parent.get("job"):
        ctx["job"] = parent["job"]
    return ctx


# outgoing-task alias kept for the original call sites (worker.py)
def child_context_for_task(task_name: str) -> Optional[Dict[str, str]]:
    """Context to embed in an outgoing task spec: a fresh span chained
    under the caller's (None when tracing is off — specs stay clean)."""
    return child_context(task_name)


def adopt(ctx: Optional[Dict[str, str]]) -> Any:
    """Make ``ctx`` the current context on this thread (the executing
    worker resuming a submitter's trace).  Returns a token for
    :func:`restore`; pass None to clear (a pooled worker must not leak
    the previous task's context)."""
    return _ctx_set(ctx)


def restore(token: Any) -> None:
    """Undo a matching :func:`adopt` (public inverse — callers must not
    reach into the module's contextvar)."""
    _ctx_reset(token)


# attribute keys that would collide with emit parameters or span lineage;
# user attributes with these names are prefixed, never dropped or crashed on
_RESERVED_KEYS = frozenset((
    "source", "message", "severity", "entity_id", "span_dur",
    "trace_id", "span_id", "parent_span_id", "phase", "name",
))


def span_fields(ctx: Optional[Dict[str, str]], phase: str,
                span_id: Optional[str] = None) -> Dict[str, str]:
    """Span-lineage kwargs for a raw ``events.emit``: a fresh child span
    of ``ctx`` (or the explicit ``span_id``).  {} without a context, so
    call sites can splat it unconditionally."""
    if ctx is None:
        return {}
    return {"trace_id": ctx["trace_id"],
            "span_id": span_id or new_span_id(),
            "parent_span_id": ctx["span_id"], "phase": phase}


def emit_span(name: str, dur_s: float, ctx: Optional[Dict[str, str]],
              phase: str = "span", severity: str = "DEBUG",
              attributes: Optional[dict] = None, **data) -> None:
    """Record one closed span [now - dur_s, now] in the flight recorder,
    tagged with its trace lineage so the head's TraceTable can assemble
    the tree.  No-op without a context or with the observability layer
    disabled — callers can invoke it unconditionally.  User attribute
    keys shadowing span/emit fields are prefixed ``attr_`` instead of
    crashing or clobbering the lineage."""
    if ctx is None or not _events.ENABLED:
        return
    merged = dict(attributes or ())
    merged.update(data)
    if ctx.get("job"):
        merged.setdefault("job", ctx["job"])
    safe = {(f"attr_{k}" if k in _RESERVED_KEYS else k): v
            for k, v in merged.items()}
    _events.emit(
        TRACE_SOURCE, name, severity=severity, entity_id=ctx["trace_id"],
        span_dur=dur_s, trace_id=ctx["trace_id"], span_id=ctx["span_id"],
        parent_span_id=ctx.get("parent_span_id", ""), phase=phase, **safe)


@contextlib.contextmanager
def span(name: str, phase: str = "span", **data) -> Iterator[Optional[dict]]:
    """Child-span context manager: times the block and emits it as a child
    of the current context.  Unlike :func:`trace` it never STARTS a trace
    — outside any context it is a pure no-op (no uuid, no event)."""
    ctx = child_context(name)
    if ctx is None:
        yield None
        return
    token = _ctx_set(ctx)
    t0 = time.perf_counter()
    try:
        yield ctx
    finally:
        _ctx_reset(token)
        emit_span(name, time.perf_counter() - t0, ctx, phase=phase, **data)
