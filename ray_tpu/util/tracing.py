"""Distributed trace-context propagation across task/actor boundaries.

Analog of the reference's ``python/ray/util/tracing/tracing_helper.py``
(monkey-patched remote calls inject OpenTelemetry span contexts into task
metadata; workers resume the trace).  Here propagation is first-class
instead of patched on: when tracing is enabled, every task spec carries the
submitter's trace context, the executing worker adopts it for the duration
of the task (so nested submissions chain), and the head records it on
TaskInfo — ``ray_tpu timeline`` then emits chrome-trace flow arrows linking
parents to children.  If the OpenTelemetry SDK is importable, real spans
are started as well (the reference's lazy-import pattern).
"""

from __future__ import annotations

import contextlib
import contextvars
import uuid
from typing import Any, Dict, Iterator, Optional

_current: contextvars.ContextVar[Optional[Dict[str, str]]] = contextvars.ContextVar(
    "ray_tpu_trace", default=None
)


def current_context() -> Optional[Dict[str, str]]:
    """The active trace context, or None (outside any trace).  Presence of
    a context IS the enable signal — specs stay clean when tracing is
    unused, and workers propagate whenever a spec carries one."""
    return _current.get()


@contextlib.contextmanager
def trace(name: str, attributes: Optional[dict] = None) -> Iterator[Dict[str, str]]:
    """Open a span.  Tasks submitted inside the block carry its context;
    their workers continue the same trace."""
    parent = _current.get()
    ctx = {
        "trace_id": parent["trace_id"] if parent else uuid.uuid4().hex,
        "span_id": uuid.uuid4().hex[:16],
        "parent_span_id": parent["span_id"] if parent else "",
        "name": name,
    }
    token = _current.set(ctx)
    otel_cm = _otel_span(name, attributes)
    try:
        with otel_cm:
            yield ctx
    finally:
        _current.reset(token)


def _otel_span(name: str, attributes: Optional[dict]):
    """A real OpenTelemetry span when the SDK is importable, else a no-op
    (``tracing_helper.py:53-59`` lazy import)."""
    try:
        from opentelemetry import trace as otel  # type: ignore
    except ImportError:
        return contextlib.nullcontext()
    tracer = otel.get_tracer("ray_tpu")
    return tracer.start_as_current_span(name, attributes=attributes or {})


def child_context_for_task(task_name: str) -> Optional[Dict[str, str]]:
    """Context to embed in an outgoing task spec: a fresh span chained
    under the caller's (None when tracing is off — specs stay clean)."""
    parent = current_context()
    if parent is None:
        return None
    return {
        "trace_id": parent["trace_id"],
        "span_id": uuid.uuid4().hex[:16],
        "parent_span_id": parent["span_id"],
        "name": task_name,
    }
