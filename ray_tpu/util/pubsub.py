"""Cluster pubsub — the generalized publisher/subscriber channels.

Analog of ``src/ray/pubsub/`` (``Publisher``/``Subscriber``, channels in
``pubsub.proto``) as surfaced to Python.  The head fans published
messages out to subscriber connections; built-in channels:

- ``node_change`` — node join/death events (GcsNodeManager broadcast)
- ``error``       — task failures (the error-pubsub channel drivers print)

plus any application channel name.

    from ray_tpu.util import pubsub
    pubsub.subscribe("jobs_done", lambda data: print("done:", data))
    pubsub.publish("jobs_done", {"job": 1})
"""

from __future__ import annotations

from typing import Any, Callable


def _client():
    from ray_tpu._private.worker import global_worker

    if not global_worker.connected:
        raise RuntimeError("ray_tpu.init() must run before pubsub")
    return global_worker.client


def publish(channel: str, data: Any) -> None:
    _client().publish(channel, data)


def subscribe(channel: str, callback: Callable[[Any], None]) -> None:
    _client().subscribe(channel, callback)


def unsubscribe(channel: str, callback: Callable[[Any], None] = None) -> None:
    _client().unsubscribe(channel, callback)
