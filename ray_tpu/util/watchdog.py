"""Watchdog plane: continuous SLO/burn-rate evaluation on the head.

After the recording planes (events, traces, TSDB, flamegraphs, logs) the
cluster records everything but *watches* nothing — ``run_doctor`` is an
on-demand CLI, findings have no lifecycle, and no declared objective
exists for the numbers the benches gate.  The :class:`Watchdog` closes
that loop with a head-side evaluation thread (cadence
``RAY_TPU_WATCHDOG_S``, default 15s; ``RAY_TPU_WATCHDOG=0`` off) that
per tick:

1. runs the doctor rules **incrementally** — event-cursor deltas via
   :class:`ray_tpu.util.doctor.DoctorState` and head-local table access,
   never a 100k-row state-API pull, so a tick costs milliseconds;
2. evaluates **declarative SLOs** against the head TSDB (``slos.json``
   or :meth:`Watchdog.add_slo`) with SRE-style multi-window burn-rate:
   the fast (default 5min) AND slow (default 1h) windows must both
   breach before an SLO "burns" — single-window alerting flaps on noisy
   single-host benches;
3. folds findings + burns into the **incident lifecycle**
   (:mod:`ray_tpu.util.incidents`): stable ids, open → ack → resolved
   with hysteresis, re-open escalation, every transition a
   flight-recorder ``incident`` event plus a push to the alert sinks;
4. at incident-open, freezes a **post-mortem bundle** under
   ``<session>/incidents/<id>/`` — implicated log tails (including
   retired death tails), trace span trees, TSDB slices for the burning
   series, the latest collapsed profile, an event-ring excerpt, and the
   memory/owner audit — to disk before the bounded rings decay the
   evidence.  ``debug_dump()`` writes the same bundle on demand.

SLO declaration (``slos.json``: ``{"slos": [...]}`` or a bare list; the
same dict shape feeds ``add_slo``)::

    {"name": "serve_p99", "metric": "ray_tpu_serve_http_p99_s",
     "kind": "threshold", "agg": "avg", "op": "<=", "threshold": 2.0,
     "fast_window_s": 300, "slow_window_s": 3600, "severity": "ERROR"}

    {"name": "serve_5xx", "kind": "ratio",
     "metric": "ray_tpu_serve_http_requests_total",
     "tags": {"code_class": "5xx"},
     "denominator": "ray_tpu_serve_http_requests_total",
     "threshold": 0.05}

``kind: threshold`` aggregates the metric's points over each window and
compares against ``threshold`` with ``op`` (``<=``: objective is "stay
at or below"; ``>=``: a floor).  ``kind: ratio`` takes counter deltas
over each window (numerator tags vs denominator) and burns when the
ratio exceeds the ``threshold`` budget.  A window with insufficient
coverage (fewer than 2 points, or spanning less than ``min_coverage``
of the window) is not evaluable — short-lived clusters never burn their
1h window by accident.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private import events as events_mod
from ray_tpu._private.events import _float_env, _int_env
from ray_tpu.util import doctor
from ray_tpu.util.incidents import (
    IncidentTable,
    SinkSet,
    prune_bundle_dirs,
    sinks_from_env,
)

logger = logging.getLogger(__name__)

DEFAULT_CADENCE_S = 15.0
DEFAULT_FAST_WINDOW_S = 300.0
DEFAULT_SLOW_WINDOW_S = 3600.0
# fraction of a window that must hold samples before it is evaluable
DEFAULT_MIN_COVERAGE = 0.5
# bundle caps
BUNDLE_MAX_STREAMS = 8
BUNDLE_TAIL_LINES = 200
BUNDLE_MAX_TRACES = 3
BUNDLE_MAX_METRICS = 12
BUNDLE_EVENT_ROWS = 500
BUNDLE_TSDB_WINDOW_S = 1800.0
PROFILE_WINDOW_S = 600.0


def enabled() -> bool:
    return os.environ.get("RAY_TPU_WATCHDOG", "1") not in ("0", "false",
                                                           "no")


def cadence_s() -> float:
    return max(0.05, _float_env("RAY_TPU_WATCHDOG_S", DEFAULT_CADENCE_S))


# ---------------------------------------------------------------------------
# SLO declaration + burn-rate evaluation
# ---------------------------------------------------------------------------


def make_slo(name: str, metric: str, threshold: float, *,
             kind: str = "threshold", op: str = "<=", agg: str = "avg",
             tags: Optional[Dict[str, str]] = None,
             denominator: Optional[str] = None,
             den_tags: Optional[Dict[str, str]] = None,
             fast_window_s: float = DEFAULT_FAST_WINDOW_S,
             slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
             min_coverage: float = DEFAULT_MIN_COVERAGE,
             severity: str = "ERROR",
             description: str = "") -> dict:
    """Normalize one SLO declaration (raises on an unknown kind/op)."""
    if kind not in ("threshold", "ratio"):
        raise ValueError(f"unknown SLO kind {kind!r}")
    if op not in ("<=", ">="):
        raise ValueError(f"unknown SLO op {op!r} (use '<=' or '>=')")
    return {
        "name": str(name), "metric": str(metric),
        "threshold": float(threshold), "kind": kind, "op": op,
        "agg": agg, "tags": dict(tags or {}),
        "denominator": denominator or str(metric),
        "den_tags": dict(den_tags or {}),
        "fast_window_s": float(fast_window_s),
        "slow_window_s": float(slow_window_s),
        "min_coverage": float(min_coverage),
        "severity": severity, "description": description,
    }


def _series_points(tsdb, metric: str, tags: Optional[Dict[str, str]],
                   window_s: float,
                   now: Optional[float]) -> List[List[Tuple[float, float]]]:
    """Each matching label series' points, separately (cumulative
    counters must delta per series, never across merged series)."""
    try:
        q = tsdb.query(metric, window_s=window_s, step_s=0.0,
                       tags=tags or None, now=now)
    except Exception:  # noqa: BLE001 — metric unknown to the TSDB yet
        return []
    out = []
    for s in q.get("series", ()):
        pts = sorted((ts, v) for ts, v in s.get("points", ())
                     if v is not None)
        if pts:
            out.append(pts)
    return out


def _points(tsdb, metric: str, tags: Optional[Dict[str, str]],
            window_s: float, now: Optional[float]) -> List[Tuple[float,
                                                                 float]]:
    pts = [p for series in _series_points(tsdb, metric, tags, window_s,
                                          now) for p in series]
    pts.sort()
    return pts


def _coverage(pts: Sequence[Tuple[float, float]], window_s: float) -> float:
    if len(pts) < 2:
        return 0.0
    return max(0.0, (pts[-1][0] - pts[0][0]) / max(window_s, 1e-9))


def _counter_delta(pts: Sequence[Tuple[float, float]]) -> float:
    if len(pts) < 2:
        return 0.0
    return max(0.0, pts[-1][1] - pts[0][1])


def _eval_window(slo: dict, tsdb, window_s: float,
                 now: Optional[float]) -> dict:
    """One window's verdict: ``{"value", "breach", "coverage",
    "evaluable"}``."""
    out = {"window_s": window_s, "value": None, "breach": False,
           "coverage": 0.0, "evaluable": False}
    if slo["kind"] == "ratio":
        num = _series_points(tsdb, slo["metric"], slo["tags"], window_s,
                             now)
        den = _series_points(tsdb, slo["denominator"], slo["den_tags"],
                             window_s, now)
        den_flat = sorted(p for series in den for p in series)
        out["coverage"] = round(_coverage(den_flat, window_s), 3)
        d_den = sum(_counter_delta(s) for s in den)
        if d_den <= 0 or out["coverage"] < slo["min_coverage"]:
            return out
        ratio = sum(_counter_delta(s) for s in num) / d_den
        out.update(value=round(ratio, 6), evaluable=True,
                   breach=ratio > slo["threshold"])
        return out
    pts = _points(tsdb, slo["metric"], slo["tags"], window_s, now)
    out["coverage"] = round(_coverage(pts, window_s), 3)
    if out["coverage"] < slo["min_coverage"]:
        return out
    vals = [v for _, v in pts]
    agg = slo["agg"]
    if agg == "last":
        value = vals[-1]
    elif agg == "max":
        value = max(vals)
    elif agg == "min":
        value = min(vals)
    else:
        value = sum(vals) / len(vals)
    breach = value > slo["threshold"] if slo["op"] == "<=" \
        else value < slo["threshold"]
    out.update(value=round(value, 6), evaluable=True, breach=breach)
    return out


def evaluate_slo(slo: dict, tsdb, now: Optional[float] = None) -> dict:
    """Multi-window burn-rate verdict: burning iff the fast AND slow
    windows are both evaluable and both breach."""
    fast = _eval_window(slo, tsdb, slo["fast_window_s"], now)
    slow = _eval_window(slo, tsdb, slo["slow_window_s"], now)
    return {"name": slo["name"], "fast": fast, "slow": slow,
            "burning": bool(fast["breach"] and slow["breach"]
                            and fast["evaluable"] and slow["evaluable"])}


def default_slos() -> List[dict]:
    """The wellknown objectives for the numbers the benches gate.  Each
    only ever burns once its metric actually carries enough data to
    cover both windows — declaring them on an idle cluster is free."""
    return [
        make_slo("serve_p99", "ray_tpu_serve_http_p99_s", 2.0,
                 op="<=", agg="avg", severity="ERROR",
                 description="serve HTTP p99 stays at or under 2s"),
        make_slo("serve_5xx", "ray_tpu_serve_http_requests_total", 0.05,
                 kind="ratio", tags={"code_class": "5xx"},
                 severity="ERROR",
                 description="serve 5xx share of requests under 5%"),
        make_slo("mfu_floor", "ray_tpu_train_step_mfu", 0.05,
                 op=">=", agg="avg", severity="WARNING",
                 description="training MFU holds above the floor"),
        make_slo("ingest_floor", "ray_tpu_train_ingest_gbps", 0.1,
                 op=">=", agg="avg", severity="WARNING",
                 description="train ingest throughput holds above the "
                             "floor"),
        make_slo("queue_drain", "ray_tpu_sched_queue_depth", 5000.0,
                 op="<=", agg="avg", severity="WARNING",
                 description="the scheduler queue drains (sustained "
                             "depth stays bounded)"),
    ]


def load_slos_file(path: str) -> List[dict]:
    """Parse an ``slos.json`` (``{"slos": [...]}`` or a bare list) into
    normalized declarations; bad entries are skipped with a log line,
    not fatal — one typo must not take the watchdog down."""
    with open(path) as f:
        raw = json.load(f)
    if isinstance(raw, dict):
        raw = raw.get("slos", [])
    out = []
    for entry in raw:
        try:
            out.append(make_slo(**entry))
        except Exception as e:  # noqa: BLE001 — bad declaration
            logger.warning("skipping bad SLO %r: %s", entry, e)
    return out


def _burn_finding(slo: dict, ev: dict) -> dict:
    fast, slow = ev["fast"], ev["slow"]
    return {
        "rule": f"slo:{slo['name']}", "severity": slo["severity"],
        "entity": slo["name"], "slo": True, "metric": slo["metric"],
        "summary": (
            f"SLO {slo['name']} burning: {slo['metric']} "
            f"fast({int(slo['fast_window_s'])}s)={fast['value']} and "
            f"slow({int(slo['slow_window_s'])}s)={slow['value']} both "
            f"breach {slo['op']} {slo['threshold']}"),
        "remedy": slo["description"] or (
            "both burn-rate windows breach the declared objective — "
            "check the metric's TSDB slice in the incident bundle"),
        "count": 1,
        "evidence": [{"metric": slo["metric"], "fast": fast,
                      "slow": slow, "threshold": slo["threshold"]}],
    }


# ---------------------------------------------------------------------------
# the watchdog itself
# ---------------------------------------------------------------------------


class Watchdog:
    """Head-side evaluation loop.  ``tick()`` is synchronous and
    idempotent — the loop thread calls it on cadence; tests and the
    bench probe call it directly."""

    def __init__(self, node, cadence: Optional[float] = None,
                 sinks: Optional[SinkSet] = None,
                 capture_bundles: bool = True):
        self._node = node
        self.cadence_s = cadence if cadence is not None else cadence_s()
        self._doctor = doctor.DoctorState(
            window_rows=_int_env("RAY_TPU_WATCHDOG_WINDOW_ROWS", 20_000),
            event_window_s=_float_env("RAY_TPU_WATCHDOG_EVENT_WINDOW_S",
                                      600.0))
        self.incidents = IncidentTable(
            resolve_ticks=_int_env("RAY_TPU_WATCHDOG_RESOLVE_TICKS", 3),
            escalate_reopens=_int_env("RAY_TPU_WATCHDOG_ESCALATE", 3))
        self.sinks = sinks if sinks is not None else SinkSet(
            sinks_from_env())
        self._capture_bundles = capture_bundles and os.environ.get(
            "RAY_TPU_INCIDENT_BUNDLES", "") != "0"
        self._bundle_keep = max(1, _int_env("RAY_TPU_INCIDENT_BUNDLES", 20))
        self._trend_window_s = _float_env("RAY_TPU_WATCHDOG_TREND_S",
                                          1800.0)
        self._lock = threading.Lock()
        self._slos: List[dict] = []
        self._slo_state: Dict[str, dict] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ticks = 0
        self._last_tick_s = 0.0
        self._total_tick_s = 0.0
        self._load_slos()

    # -- SLO registry ---------------------------------------------------
    def _load_slos(self) -> None:
        slos = default_slos()
        path = os.environ.get("RAY_TPU_SLOS", "").strip() or (
            "slos.json" if os.path.exists("slos.json") else "")
        if path:
            try:
                declared = load_slos_file(path)
            except Exception as e:  # noqa: BLE001 — unreadable file
                logger.warning("could not load SLOs from %s: %s", path, e)
            else:
                # declared objectives override same-name defaults
                names = {s["name"] for s in declared}
                slos = [s for s in slos if s["name"] not in names]
                slos.extend(declared)
        with self._lock:
            self._slos = slos

    def add_slo(self, name: str, metric: str, threshold: float,
                **kwargs) -> dict:
        slo = make_slo(name, metric, threshold, **kwargs)
        with self._lock:
            self._slos = [s for s in self._slos if s["name"] != name]
            self._slos.append(slo)
        return slo

    def remove_slo(self, name: str) -> bool:
        with self._lock:
            before = len(self._slos)
            self._slos = [s for s in self._slos if s["name"] != name]
            return len(self._slos) != before

    def slos(self) -> List[dict]:
        """Declared SLOs with their latest evaluation folded in (the
        ``list_slos`` table body)."""
        with self._lock:
            slos = [dict(s) for s in self._slos]
            state = dict(self._slo_state)
        for s in slos:
            ev = state.get(s["name"])
            if ev:
                s["burning"] = ev["burning"]
                s["fast"] = ev["fast"]
                s["slow"] = ev["slow"]
            else:
                s["burning"] = False
        return slos

    # -- tick -----------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> List[Tuple[dict, str]]:
        """One evaluation pass; returns the incident transitions it
        produced.  Head-local by construction: event-cursor deltas, the
        gcs task table, and direct TSDB queries — zero state-API RPCs."""
        t0 = time.perf_counter()
        if now is None:
            now = time.time()
        node = self._node
        self._doctor.feed(table=node.events, local=events_mod.buffer())
        tasks = self._task_rows()
        findings = self._doctor.diagnose(tasks, now=now)
        series_map: Dict[str, list] = {}
        for name in doctor.TREND_METRICS:
            try:
                q = node.tsdb.query(name, window_s=self._trend_window_s)
                series_map[name] = q.get("series", [])
            except Exception:  # noqa: BLE001 — no samples yet
                continue
        findings = findings + doctor.diagnose_trends(series_map)
        burns = []
        with self._lock:
            slos = list(self._slos)
        for slo in slos:
            ev = evaluate_slo(slo, node.tsdb, now=now)
            with self._lock:
                self._slo_state[slo["name"]] = ev
            if ev["burning"]:
                burns.append(_burn_finding(slo, ev))
        transitions = self.incidents.observe(findings + burns, now=now)
        for inc, tr in transitions:
            self._publish(inc, tr, now)
            if tr in ("open", "reopen") and self._capture_bundles:
                try:
                    path = self.capture_bundle(inc)
                    self.incidents.set_bundle_dir(inc["id"], path)
                except Exception:  # noqa: BLE001 — the bundle is
                    # best-effort evidence; capture failure must not
                    # break the lifecycle
                    logger.exception("bundle capture failed for %s",
                                     inc["id"])
        dt = time.perf_counter() - t0
        with self._lock:
            self._ticks += 1
            self._last_tick_s = dt
            self._total_tick_s += dt
        return transitions

    def _task_rows(self, limit: int = 5000) -> List[dict]:
        try:
            rows, _total = self._node._list_state_page("tasks", limit)
            return rows
        except Exception:  # noqa: BLE001 — table shape drift must not
            # kill the tick; event rules still run
            return []

    def _publish(self, inc: dict, transition: str, now: float) -> None:
        sev = inc["severity"] if transition != "resolve" else "INFO"
        if events_mod.ENABLED:
            events_mod.emit(
                "incident", f"incident {transition}", severity=sev,
                entity_id=inc["id"], rule=inc["rule"],
                entity=inc["entity"], transition=transition,
                reopen_count=inc["reopen_count"],
                summary=inc["summary"][:200])
        self.sinks.push({
            "transition": transition, "ts": now,
            "incident": {k: inc[k] for k in
                         ("id", "rule", "entity", "severity", "summary",
                          "remedy", "state", "opened_at", "reopen_count",
                          "escalated")}})

    def ack(self, iid: str) -> Optional[dict]:
        snap = self.incidents.ack(iid)
        if snap is not None:
            self._publish(snap, "ack", time.time())
        return snap

    # -- post-mortem bundles --------------------------------------------
    @property
    def bundle_root(self) -> str:
        return os.path.join(self._node.session_dir, "incidents")

    def capture_bundle(self, incident: dict,
                       root: Optional[str] = None) -> str:
        """Freeze the evidence for one incident to disk before the
        bounded rings decay it.  Returns the bundle directory."""
        node = self._node
        base = root or self.bundle_root
        bdir = os.path.join(base, incident["id"])
        os.makedirs(os.path.join(bdir, "logs"), exist_ok=True)
        os.makedirs(os.path.join(bdir, "tsdb"), exist_ok=True)
        self._write_json(bdir, "incident.json", incident)
        rows, _ = node.events.list_with_total(limit=BUNDLE_EVENT_ROWS)
        rows = rows + events_mod.local_events(BUNDLE_EVENT_ROWS // 2)
        rows.sort(key=lambda r: r.get("ts", 0.0))
        self._write_json(bdir, "events.json", rows[-BUNDLE_EVENT_ROWS:])
        for stream in self._implicated_streams(incident):
            tail = node.log_store.tail_text(stream, n=BUNDLE_TAIL_LINES)
            if not tail:
                continue
            safe = "".join(c if (c.isalnum() or c in "._-") else "_"
                           for c in stream)
            with open(os.path.join(bdir, "logs", safe + ".txt"), "w",
                      errors="replace") as f:
                f.write("\n".join(tail) + "\n")
        tids = self._implicated_traces(incident)
        for tid in tids:
            try:
                trace = node._get_trace(tid)
            except Exception:  # noqa: BLE001
                trace = None
            if trace:
                self._write_json(bdir, f"trace-{tid[:24]}.json", trace)
        try:
            # recent trace summaries ride along even without explicit
            # trace ids in the evidence: the requests in flight around
            # the incident are usually the implicated ones
            node._fold_local_traces()
            recent = node.traces.list(20)
            if recent:
                self._write_json(bdir, "traces.json", recent)
                if not tids and recent:
                    t = node._get_trace(recent[-1]["trace_id"])
                    if t:
                        self._write_json(
                            bdir,
                            f"trace-{recent[-1]['trace_id'][:24]}.json", t)
        except Exception:  # noqa: BLE001
            pass
        for metric, tags in self._bundle_metrics(incident):
            try:
                q = node.tsdb.query(metric, window_s=BUNDLE_TSDB_WINDOW_S,
                                    tags=tags or None)
            except Exception:  # noqa: BLE001
                continue
            if q.get("series"):
                self._write_json(bdir, os.path.join("tsdb",
                                                    metric + ".json"), q)
        try:
            collapsed = node.profile_store.collapsed(PROFILE_WINDOW_S)
            if collapsed:
                with open(os.path.join(bdir, "profile_collapsed.txt"),
                          "w") as f:
                    f.write(collapsed + "\n")
        except Exception:  # noqa: BLE001
            pass
        try:
            self._write_json(bdir, "memory.json",
                             node._memory_audit(limit=200))
        except Exception:  # noqa: BLE001
            pass
        prune_bundle_dirs(base, self._bundle_keep)
        return bdir

    def _implicated_streams(self, incident: dict) -> List[str]:
        """Log streams worth freezing: anything the evidence names, plus
        recently retired streams (a SIGKILL'd worker's death tail is the
        single most valuable line in the bundle), capped."""
        needles = {str(incident.get("entity", ""))}
        for ev in incident.get("evidence", ()):
            if isinstance(ev, dict):
                for key in ("entity_id", "origin", "stream", "pid"):
                    v = ev.get(key)
                    if v:
                        needles.add(str(v))
                data = ev.get("data")
                if isinstance(data, dict):
                    for key in ("stream", "worker_id", "entity_id"):
                        if data.get(key):
                            needles.add(str(data[key]))
        needles.discard("")
        dump_all = incident.get("rule") == "debug_dump"
        out: List[str] = []
        retired: List[str] = []
        rest: List[str] = []
        for row in self._node.log_store.stats():
            name = row["stream"]
            if any(n in name for n in needles):
                out.append(name)
            elif row.get("retired"):
                retired.append(name)
            elif dump_all:
                rest.append(name)
        for name in retired + rest:
            if len(out) >= (16 if dump_all else BUNDLE_MAX_STREAMS):
                break
            if name not in out:
                out.append(name)
        return out[:16 if dump_all else BUNDLE_MAX_STREAMS]

    @staticmethod
    def _implicated_traces(incident: dict) -> List[str]:
        tids: List[str] = []
        for ev in incident.get("evidence", ()):
            if not isinstance(ev, dict):
                continue
            data = ev.get("data") if isinstance(ev.get("data"), dict) \
                else {}
            for src in (ev, data):
                tid = src.get("trace_id")
                if tid and tid not in tids:
                    tids.append(str(tid))
        return tids[:BUNDLE_MAX_TRACES]

    def _bundle_metrics(self, incident: dict) -> List[Tuple[str,
                                                            Dict[str,
                                                                 str]]]:
        """TSDB slices to freeze: the incident's own metric (an SLO
        burn), every declared SLO's metric, and the queue-depth trend —
        capped and deduped."""
        out: List[Tuple[str, Dict[str, str]]] = []
        seen = set()

        def _add(metric: Optional[str], tags: Optional[dict] = None):
            if metric and metric not in seen and \
                    len(out) < BUNDLE_MAX_METRICS:
                seen.add(metric)
                out.append((metric, dict(tags or {})))

        _add(incident.get("metric"))
        for slo in self.slos():
            _add(slo["metric"], slo.get("tags"))
        _add("ray_tpu_sched_queue_depth")
        _add("ray_tpu_proc_rss_mb")
        return out

    def debug_dump(self, label: Optional[str] = None) -> str:
        """One-shot whole-cluster bundle (``ray_tpu debug dump``)."""
        name = label or f"dump-{int(time.time())}"
        pseudo = {"id": name, "rule": "debug_dump", "entity": "cluster",
                  "severity": "INFO", "state": "dump",
                  "summary": "on-demand debug dump", "evidence": [],
                  "metric": None}
        return self.capture_bundle(pseudo)

    @staticmethod
    def _write_json(bdir: str, rel: str, obj: Any) -> None:
        with open(os.path.join(bdir, rel), "w") as f:
            json.dump(obj, f, indent=1, default=str)

    # -- loop + stats ---------------------------------------------------
    def start(self) -> threading.Thread:
        t = threading.Thread(target=self._loop, name="watchdog",
                             daemon=True)
        self._thread = t
        t.start()
        return t

    def _loop(self) -> None:
        while not self._stop.wait(self.cadence_s):
            if getattr(self._node, "_shutdown", False):
                return
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the watchdog must never
                # take the head down; next tick retries
                logger.exception("watchdog tick failed")

    def stop(self) -> None:
        self._stop.set()
        self.sinks.stop()

    def stats(self) -> dict:
        with self._lock:
            ticks = self._ticks
            last = self._last_tick_s
            avg = self._total_tick_s / ticks if ticks else 0.0
        return {"ticks": ticks, "cadence_s": self.cadence_s,
                "last_tick_ms": round(last * 1e3, 3),
                "avg_tick_ms": round(avg * 1e3, 3),
                "overhead_frac": round(avg / self.cadence_s, 6)
                if self.cadence_s else 0.0,
                "doctor_window_rows": self._doctor.window_len(),
                "incidents": self.incidents.counts(),
                "sinks": self.sinks.stats()}
