"""Head-side store for continuously-shipped folded stacks.

Every process in the cluster runs a
:class:`~ray_tpu._private.sampling_profiler.ContinuousProfiler` that
batch-ships time-bucketed folded stacks over its existing control
connection (``profile_report`` frames ride the same path as
``metrics_report``).  The head lands them here: bounded per-origin rings
with staged decay — recent windows keep full fine-grained buckets, old
fine buckets fold into coarse buckets, and origins that stop pushing are
retired wholesale — the TSDB discipline applied to profiles.

On top of the rings sit the query surfaces: merged flamegraphs over a
window (``query``/``collapsed``), differential folded stacks between two
windows (``diff``, flamegraph.pl ``difffolded`` ready), and the CPU cost
ledger (``cost_ledger``) that converts duty-cycle-sampled stacks into
per-task microsecond columns which must sum to the measured per-task
wall — the ``StepProfiler`` exactness discipline applied to the control
plane.
"""

from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional

# ---------------------------------------------------------------------------
# frame classification (shared by the ledger and the doctor's
# serialization gauge)
# ---------------------------------------------------------------------------

# A stack is "idle" when its leaf frame is a blocking wait: the thread is
# parked in the kernel, consuming no core.  Leaf function names cover the
# stdlib wait idioms; leaf files catch the socket/selector layers whose
# function names are too generic to list.
_IDLE_LEAF_FUNCS = frozenset((
    "wait", "_wait_for_tstate_lock", "select", "poll", "_poll", "epoll",
    "recv", "_recv", "recv_bytes", "recv_into", "readinto", "read",
    "readline", "accept", "sleep", "get", "park", "kqueue",
))
_IDLE_LEAF_FILES = frozenset(("selectors.py", "socket.py"))

# Busy stacks classify by the LEAF-MOST recognizable file: serialization
# inside a dispatch call tree is serialization — that nesting is exactly
# what the ledger exists to expose.
_CLASS_BY_FILE = {
    "serialization.py": "serialize", "wire.py": "serialize",
    "packed_wire.py": "serialize", "pickle.py": "serialize",
    "copyreg.py": "serialize", "struct.py": "serialize",
    "locks.py": "lock_wait",
    "node.py": "dispatch", "sharding.py": "dispatch",
    "object_store.py": "dispatch", "syncer.py": "dispatch",
    "remote_function.py": "submit", "client.py": "submit",
    "actor.py": "submit", "api.py": "submit",
    "worker.py": "exec",
}

BUSY_CLASSES = ("submit", "dispatch", "exec", "serialize", "lock_wait",
                "other")


def classify_stack(stack: str) -> str:
    """Map one ``|``-joined folded stack to an accounting class."""
    frames = stack.split("|")
    leaf_file, _, leaf_func = frames[-1].partition(":")
    if leaf_func in _IDLE_LEAF_FUNCS or leaf_file in _IDLE_LEAF_FILES:
        return "idle"
    for frame in reversed(frames):
        cls = _CLASS_BY_FILE.get(frame.partition(":")[0])
        if cls is not None:
            return cls
    return "other"


def _bucket_cost(folded: Dict[str, int]) -> int:
    # bookkeeping estimate: key bytes + counter slot
    return sum(len(s) + 32 for s in folded)


class ProfileStore:
    """Bounded per-origin rings of folded-stack buckets.

    ``fine`` buckets hold full resolution for the recent past; byte or
    age pressure folds the oldest of them into ``coarse`` buckets
    (wider span, top-K stacks, remainder under ``(decayed)``); coarse
    buckets beyond retention — and whole origins that stop pushing — are
    dropped.  All caps are constructor parameters so tests can force
    every stage cheaply.
    """

    def __init__(self, *, bucket_s: float = 60.0, coarse_s: float = 600.0,
                 max_bytes_per_origin: int = 1 << 20, max_origins: int = 64,
                 fine_retention_s: float = 1800.0,
                 coarse_retention_s: float = 7200.0,
                 coarse_top_k: int = 400):
        from ray_tpu._private.locks import make_lock

        self.bucket_s = bucket_s
        self.coarse_s = coarse_s
        self.max_bytes_per_origin = max_bytes_per_origin
        self.max_origins = max_origins
        self.fine_retention_s = fine_retention_s
        self.coarse_retention_s = coarse_retention_s
        self.coarse_top_k = coarse_top_k
        self._lock = make_lock("profile_store")
        # origin -> {"fine": {ts: {"folded": Counter, "ticks": float}},
        #            "coarse": {ts: ...}, "bytes": int, "last_push": float,
        #            "samples": int, "gil_frac": float, "meta": dict}
        self._origins: Dict[str, dict] = {}

    # -- ingest -------------------------------------------------------------
    def ingest(self, origin: str, buckets: List[dict],
               meta: Optional[dict] = None, now: Optional[float] = None) -> None:
        """Land one ``profile_report`` batch.  ``buckets`` is the wire
        shape ``[{"ts": float, "folded": {stack: n}}, ...]``; the batch's
        sampling ticks (duty-cycle denominator) are apportioned across
        its buckets by sample share."""
        now = time.time() if now is None else now
        meta = meta or {}
        total = sum(sum(b.get("folded", {}).values()) for b in buckets) or 1
        meta_ticks = float(meta.get("ticks", 0))
        with self._lock:
            st = self._origins.get(origin)
            if st is None:
                st = self._origins[origin] = {
                    "fine": {}, "coarse": {}, "bytes": 0, "last_push": now,
                    "samples": 0, "gil_frac": 0.0, "meta": {},
                }
                self._evict_origins_locked()
            st["last_push"] = now
            if meta:
                st["meta"] = dict(meta)
                lateness = float(meta.get("lateness_frac", 0.0))
                st["gil_frac"] = 0.5 * st["gil_frac"] + 0.5 * lateness
            for b in buckets:
                folded = b.get("folded") or {}
                if not folded:
                    continue
                ts = (float(b.get("ts", now)) // self.bucket_s) * self.bucket_s
                row = st["fine"].get(ts)
                if row is None:
                    row = st["fine"][ts] = {
                        "folded": collections.Counter(), "ticks": 0.0,
                        "busy": 0.0}
                before = _bucket_cost(row["folded"])
                row["folded"].update(folded)
                n = sum(folded.values())
                # the continuous profiler ships per-bucket duty counts;
                # batches without them (synthetic/legacy) apportion the
                # batch total by sample share
                row["ticks"] += float(
                    b.get("ticks", meta_ticks * n / total))
                row["busy"] += float(b.get("busy_ticks", 0.0))
                st["samples"] += n
                st["bytes"] += _bucket_cost(row["folded"]) - before
            self._enforce_locked(st)

    def _evict_origins_locked(self) -> None:
        while len(self._origins) > self.max_origins:
            oldest = min(self._origins, key=lambda o: self._origins[o]["last_push"])
            del self._origins[oldest]

    def _enforce_locked(self, st: dict) -> None:
        """Byte-pressure staged decay: oldest fine bucket folds to
        coarse; when only coarse remains, the oldest coarse is dropped."""
        while st["bytes"] > self.max_bytes_per_origin:
            if st["fine"]:
                ts = min(st["fine"])
                self._decay_bucket_locked(st, ts)
            elif st["coarse"]:
                ts = min(st["coarse"])
                row = st["coarse"].pop(ts)
                st["bytes"] -= _bucket_cost(row["folded"])
            else:
                break

    def _decay_bucket_locked(self, st: dict, ts: float) -> None:
        row = st["fine"].pop(ts)
        st["bytes"] -= _bucket_cost(row["folded"])
        cts = (ts // self.coarse_s) * self.coarse_s
        crow = st["coarse"].get(cts)
        if crow is None:
            crow = st["coarse"][cts] = {
                "folded": collections.Counter(), "ticks": 0.0, "busy": 0.0}
        before = _bucket_cost(crow["folded"])
        crow["folded"].update(row["folded"])
        crow["ticks"] += row["ticks"]
        crow["busy"] += row.get("busy", 0.0)
        # coarse keeps only the top-K stacks; the long tail merges into a
        # single marker so the byte cost of history is bounded by design
        if len(crow["folded"]) > self.coarse_top_k:
            keep = collections.Counter(
                dict(crow["folded"].most_common(self.coarse_top_k)))
            keep["(decayed)"] += (sum(crow["folded"].values())
                                  - sum(keep.values()))
            crow["folded"] = keep
        st["bytes"] += _bucket_cost(crow["folded"]) - before

    # -- maintenance --------------------------------------------------------
    def prune(self, now: Optional[float] = None) -> None:
        """Age-based staged decay (the byte caps handle pressure; this
        handles the clock): fine buckets past ``fine_retention_s`` fold
        to coarse, coarse past ``coarse_retention_s`` drop."""
        now = time.time() if now is None else now
        with self._lock:
            for st in self._origins.values():
                for ts in sorted(st["fine"]):
                    if now - ts > self.fine_retention_s:
                        self._decay_bucket_locked(st, ts)
                for ts in sorted(st["coarse"]):
                    if now - ts > self.coarse_retention_s:
                        row = st["coarse"].pop(ts)
                        st["bytes"] -= _bucket_cost(row["folded"])

    def retire_stale(self, max_age_s: float,
                     now: Optional[float] = None) -> List[str]:
        """Drop origins that missed their pushes for ``max_age_s``
        (dead worker, disconnected driver).  Returns the retired names
        so the caller can emit events."""
        now = time.time() if now is None else now
        with self._lock:
            dead = [o for o, st in self._origins.items()
                    if now - st["last_push"] > max_age_s]
            for o in dead:
                del self._origins[o]
        return dead

    # -- queries ------------------------------------------------------------
    def _merged_locked(self, lo: float, hi: float,
                       origin: Optional[str]) -> tuple:
        """Merge every bucket OVERLAPPING [lo, hi) — a window shorter
        than the bucket span must still see the bucket it sits inside."""
        folded: "collections.Counter[str]" = collections.Counter()
        ticks = 0.0
        busy = 0.0
        origins = []
        for name, st in self._origins.items():
            if origin is not None and name != origin:
                continue
            hit = False
            for ring, span in ((st["fine"], self.bucket_s),
                               (st["coarse"], self.coarse_s)):
                for ts, row in ring.items():
                    if ts + span > lo and ts < hi:
                        folded.update(row["folded"])
                        ticks += row["ticks"]
                        busy += row.get("busy", 0.0)
                        hit = True
            if hit:
                origins.append(name)
        return folded, ticks, busy, origins

    def query(self, window_s: float, origin: Optional[str] = None,
              now: Optional[float] = None) -> dict:
        """Merged folded stacks over the trailing window."""
        now = time.time() if now is None else now
        with self._lock:
            folded, ticks, busy, origins = self._merged_locked(
                now - window_s, now + 1e-9, origin)
        return {"window_s": window_s, "origin": origin,
                "origins": sorted(origins), "ticks": round(ticks, 1),
                "busy_ticks": round(busy, 1),
                "samples": sum(folded.values()), "folded": dict(folded)}

    def collapsed(self, window_s: float, origin: Optional[str] = None,
                  now: Optional[float] = None) -> str:
        """Folded-stack lines (``a;b;c N``) for speedscope/flamegraph.pl."""
        q = self.query(window_s, origin=origin, now=now)
        return "\n".join(
            f"{stack.replace('|', ';')} {n}"
            for stack, n in sorted(q["folded"].items(),
                                   key=lambda kv: -kv[1]))

    def diff(self, window_a: float, window_b: float,
             origin: Optional[str] = None,
             now: Optional[float] = None) -> dict:
        """Differential profile: the trailing ``window_b`` seconds (B)
        against the ``window_a``-long span before it (A) — "what changed
        recently".  A's counts are scaled to B's span so the per-stack
        ``delta`` (and the ``difffolded``-format ``collapsed`` lines,
        ``stack countA countB``) compare like with like."""
        now = time.time() if now is None else now
        window_a = max(float(window_a), 1e-9)
        window_b = max(float(window_b), 1e-9)
        with self._lock:
            a, ticks_a, _, _ = self._merged_locked(
                now - window_b - window_a, now - window_b, origin)
            b, ticks_b, _, origins = self._merged_locked(
                now - window_b, now + 1e-9, origin)
        scale = window_b / window_a
        delta = {}
        lines = []
        for stack in sorted(set(a) | set(b)):
            a_scaled = a.get(stack, 0) * scale
            d = b.get(stack, 0) - a_scaled
            if d:
                delta[stack] = round(d, 2)
            lines.append(f"{stack.replace('|', ';')} "
                         f"{round(a_scaled)} {b.get(stack, 0)}")
        return {"window_a": window_a, "window_b": window_b,
                "origin": origin, "origins": sorted(origins),
                "samples_a": sum(a.values()), "samples_b": sum(b.values()),
                "ticks_a": round(ticks_a, 1), "ticks_b": round(ticks_b, 1),
                "delta": delta, "collapsed": "\n".join(lines)}

    def stats(self, now: Optional[float] = None) -> List[dict]:
        """One row per origin (the ``list_profiles`` body)."""
        now = time.time() if now is None else now
        with self._lock:  # snapshot only; the O(n log n) sort runs after
            snap = [(name, len(st["fine"]), len(st["coarse"]), st["bytes"],
                     st["samples"], st["gil_frac"], st["last_push"],
                     list(st["fine"]) + list(st["coarse"]),
                     st["meta"].get("interval_s"), st["meta"].get("period_s"))
                    for name, st in self._origins.items()]
        rows = []
        for (name, fine_n, coarse_n, nbytes, samples, gil, last_push,
             all_ts, interval_s, period_s) in sorted(snap, key=lambda r: r[0]):
            rows.append({
                "origin": name,
                "buckets": fine_n,
                "coarse_buckets": coarse_n,
                "bytes": nbytes,
                "samples": samples,
                "gil_frac": round(gil, 4),
                "age_s": round(now - last_push, 1),
                "span_s": round(max(all_ts) - min(all_ts)
                                + self.bucket_s, 1) if all_ts else 0.0,
                "interval_s": interval_s,
                "period_s": period_s,
            })
        return rows

    # -- duty-cycle accounting / the ledger ---------------------------------
    def class_rates(self, window_s: float, origin: Optional[str] = None,
                    now: Optional[float] = None) -> dict:
        """Duty-cycle accounting over the window: thread-seconds/second
        per accounting class.  A burst's sample share per class equals
        its wall share per thread, so ``class samples / ticks`` is the
        class's thread-occupancy — it can exceed 1.0 on a multi-threaded
        process, and on CPython that excess is by definition GIL wait
        (``util`` clips at one core; ``raw_busy - util`` is the
        runnable-but-unscheduled surplus)."""
        now = time.time() if now is None else now
        with self._lock:
            folded, ticks, busy, origins = self._merged_locked(
                now - window_s, now + 1e-9, origin)
            gil = 0.0
            if origin is not None and origin in self._origins:
                gil = self._origins[origin]["gil_frac"]
        per_class: Dict[str, float] = {c: 0.0 for c in BUSY_CLASSES}
        idle = 0.0
        for stack, n in folded.items():
            cls = classify_stack(stack)
            if cls == "idle":
                idle += n
            else:
                per_class[cls] += n
        denom = max(ticks, 1e-9)
        rates = {c: v / denom for c, v in per_class.items()}
        raw_busy = sum(rates.values())
        # utilization: fraction of ticks that caught the process OFF a
        # blocking wait (sampler-counted, one core max per process).
        # raw_busy over-counts it badly on a multi-threaded CPython
        # process — GIL-waiting threads photograph as busy — so the
        # per-tick busy count is the denominator of record; raw_busy is
        # the fallback for batches that never carried duty counts.
        util = min(busy / denom, 1.0) if busy else min(raw_busy, 1.0)
        return {"window_s": window_s, "origin": origin,
                "origins": sorted(origins), "ticks": round(ticks, 1),
                "classes": {c: round(v, 4) for c, v in rates.items()},
                "raw_busy": round(raw_busy, 4),
                "util": round(util, 4),
                "idle": round(idle / denom, 4),
                "gil_frac": round(gil, 4)}

    def serialization_frac(self, window_s: float = 300.0,
                           now: Optional[float] = None) -> float:
        """Cluster-wide share of busy samples spent serializing — the
        gauge behind the doctor's ``serialization_hot`` rule."""
        r = self.class_rates(window_s, origin=None, now=now)
        busy = max(r["raw_busy"], 1e-9)
        return min(1.0, r["classes"]["serialize"] / busy)

    def cost_ledger(self, window_s: float, tasks: int,
                    roles: Dict[str, str],
                    now: Optional[float] = None) -> dict:
        """The per-task CPU cost ledger.

        ``roles`` maps origin -> "head" | "worker" (origins absent from
        the map — node agents, drivers off the task path — are
        excluded).  The head process — the GIL-serialized control plane,
        which also hosts the in-process driver — is the wall's clock:
        its measured utilization times the per-task wall is its budget,
        split between stack classes and the lateness-measured GIL-wait
        share.  Worker CPU lands on the wall only up to the head's idle
        gap — worker time overlapped with a busy head is pipelined and
        costs CPU but no wall, so it reports separately as
        ``overlapped_worker_cpu_us`` instead of inflating the sum.

        Nothing forces ``sum_over_wall`` to 1.0: it only gets there if
        the measured head utilization plus gap-filling worker time
        actually cover the wall.  Under-measured utilization (idle
        misclassification), a missing GIL clip, or a dead profiler all
        push it out of band — when the columns don't sum, the
        measurement (not the label) is wrong, which is the point.
        """
        now = time.time() if now is None else now
        tasks = max(int(tasks), 1)
        per_task_wall_us = window_s * 1e6 / tasks
        cols = {"driver_submit_us": 0.0, "head_dispatch_us": 0.0,
                "worker_exec_us": 0.0, "serialize_us": 0.0,
                "lock_wait_us": 0.0, "gil_wait_us": 0.0, "other_us": 0.0}
        origin_util = {}
        head_util = 0.0
        worker_pool_us = 0.0
        for origin, role in roles.items():
            r = self.class_rates(window_s, origin=origin, now=now)
            if not r["ticks"]:
                continue
            util = r["util"]
            origin_util[origin] = util
            if role != "head":
                worker_pool_us += util * per_task_wall_us
                continue
            head_util = max(head_util, util)
            raw = max(r["raw_busy"], 1e-9)
            gil_frac = r["gil_frac"]
            budget_us = util * per_task_wall_us
            cols["gil_wait_us"] += budget_us * gil_frac
            busy_us = budget_us * (1.0 - gil_frac)
            for cls, rate in r["classes"].items():
                share = busy_us * rate / raw
                if cls == "serialize":
                    cols["serialize_us"] += share
                elif cls == "lock_wait":
                    cols["lock_wait_us"] += share
                elif cls in ("submit", "exec"):
                    # in-process driver: client/remote_function and the
                    # global_worker machinery are driver time, the rest
                    # of the process is dispatch
                    cols["driver_submit_us"] += share
                elif cls == "dispatch":
                    cols["head_dispatch_us"] += share
                else:
                    cols["other_us"] += share
        gap_us = max(0.0, 1.0 - head_util) * per_task_wall_us
        cols["worker_exec_us"] = min(worker_pool_us, gap_us)
        overlapped_us = worker_pool_us - cols["worker_exec_us"]
        total_us = sum(cols.values())
        return {"window_s": window_s, "tasks": tasks,
                "per_task_wall_us": round(per_task_wall_us, 2),
                "columns": {k: round(v, 2) for k, v in cols.items()},
                "overlapped_worker_cpu_us": round(overlapped_us, 2),
                "sum_us": round(total_us, 2),
                "sum_over_wall": round(total_us / max(per_task_wall_us, 1e-9),
                                       4),
                "origin_util": {o: round(u, 4)
                                for o, u in origin_util.items()}}
