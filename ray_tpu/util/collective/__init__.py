from ray_tpu.util.collective.collective import (
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_rank,
    get_collective_group_size,
    init_collective_group,
    recv,
    reducescatter,
    send,
)

__all__ = [
    "init_collective_group",
    "create_collective_group",
    "destroy_collective_group",
    "get_rank",
    "get_collective_group_size",
    "allreduce",
    "allgather",
    "reducescatter",
    "broadcast",
    "send",
    "recv",
    "barrier",
]
